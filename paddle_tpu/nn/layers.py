"""Core layers.

The user-facing layer zoo, re-providing the reference's gserver layers
(gserver/layers/: FullyConnectedLayer, ConvBaseLayer + exconv/cudnn_conv variants,
BatchNormalizationLayer, embeddings via TableProjection, pooling layers, MixedLayer
projections) and the fluid layer builders (python/paddle/v2/fluid/layers.py: fc:18,
embedding:90, conv2d:638, batch_norm:765). Each layer is a Module: params are explicit,
__call__ is pure, XLA fuses the bias/activation into the matmul/conv.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ..ops import activations as A
from ..ops import conv as conv_ops
from ..ops import norm as norm_ops
from ..ops import pool as pool_ops
from ..ops.random import dropout as dropout_op
from . import initializer as I
from .module import Module


def _act(act: Union[None, str, Callable]):
    if act is None:
        return lambda x: x
    if callable(act):
        return act
    return A.get(act)


class Linear(Module):
    """Fully-connected layer (ref: gserver/layers/FullyConnectedLayer.cpp; fluid fc)."""

    def __init__(self, in_dim: int, out_dim: int, act: Union[None, str, Callable] = None,
                 bias: bool = True, w_init: Optional[I.Initializer] = None,
                 name: str = "fc"):
        super().__init__()
        self.in_dim, self.out_dim = in_dim, out_dim
        self.act = _act(act)
        self.use_bias = bias
        self.param("w", (in_dim, out_dim), w_init or I.xavier())
        if bias:
            self.param("b", (out_dim,), I.zeros)

    def __call__(self, params, x, **kw):
        x = x.reshape((x.shape[0], -1)) if x.ndim > 2 and x.shape[-1] != self.in_dim else x
        y = jnp.matmul(x, params["w"])
        if self.use_bias:
            y = y + params["b"]
        return self.act(y)


# gen-1 name
Fc = Linear


class Embedding(Module):
    """Lookup table (ref: gserver TableProjection/table_projection; fluid embedding:90;
    operators/lookup_table_op.cc — the sparse-grad path becomes SelectedRows-style
    updates in optimizer.sparse)."""

    def __init__(self, vocab_size: int, dim: int, padding_idx: Optional[int] = None,
                 w_init: Optional[I.Initializer] = None):
        super().__init__()
        self.vocab_size, self.dim = vocab_size, dim
        self.padding_idx = padding_idx
        self.param("w", (vocab_size, dim), w_init or I.normal(0.0, 0.01))

    def __call__(self, params, ids, **kw):
        out = jnp.take(params["w"], ids, axis=0)
        if self.padding_idx is not None:
            out = jnp.where((ids == self.padding_idx)[..., None], 0.0, out)
        return out


class Conv2D(Module):
    """2-D conv + bias + act, NHWC (ref: gserver/layers/ExpandConvLayer.cpp /
    CudnnConvLayer.cpp; fluid conv2d:638)."""

    def __init__(self, in_ch: int, out_ch: int, kernel: Union[int, Tuple[int, int]],
                 stride=1, padding=0, dilation=1, groups: int = 1,
                 act: Union[None, str, Callable] = None, bias: bool = True,
                 w_init: Optional[I.Initializer] = None):
        super().__init__()
        k = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.kernel, self.in_ch = k, in_ch
        self.stride, self.padding, self.dilation, self.groups = stride, padding, dilation, groups
        self.act = _act(act)
        self.use_bias = bias
        self.param("w", k + (in_ch // groups, out_ch), w_init or I.msra())
        if bias:
            self.param("b", (out_ch,), I.zeros)

    def _is_stem7s2(self):
        # only shallow inputs (ImageNet's 3 channels): the rewrite exists
        # to deepen an MXU-starved contraction; with cin already deep it
        # just adds pad/reshape HBM traffic for nothing
        return (self.kernel == (7, 7) and self.stride in (2, (2, 2))
                and self.padding in (3, (3, 3))
                and self.dilation in (1, (1, 1))
                and self.groups == 1 and self.in_ch <= 4)

    def __call__(self, params, x, **kw):
        if self._is_stem7s2():
            # the classic ImageNet stem shape: routed through the exact
            # space-to-depth rewrite (ops/conv.py conv7s2) — a direct 7x7
            # over 3 channels is the measured MXU worst case
            # (docs/design/conv_mfu.md); same params, same math
            y = conv_ops.conv7s2(x, params["w"])
        else:
            y = conv_ops.conv2d(x, params["w"], stride=self.stride,
                                padding=self.padding, dilation=self.dilation,
                                groups=self.groups)
        if self.use_bias:
            y = y + params["b"]
        return self.act(y)


class Conv2DTranspose(Module):
    """ref: operators/conv_transpose_op.cc."""

    def __init__(self, in_ch: int, out_ch: int, kernel, stride=1, padding=0,
                 act=None, bias: bool = True):
        super().__init__()
        k = (kernel, kernel) if isinstance(kernel, int) else tuple(kernel)
        self.stride, self.padding = stride, padding
        self.act = _act(act)
        self.use_bias = bias
        self.param("w", k + (in_ch, out_ch), I.msra())
        if bias:
            self.param("b", (out_ch,), I.zeros)

    def __call__(self, params, x, **kw):
        y = conv_ops.conv2d_transpose(x, params["w"], stride=self.stride,
                                      padding=self.padding)
        if self.use_bias:
            y = y + params["b"]
        return self.act(y)


class BatchNorm(Module):
    """Functional batch norm (ref: 3 BN impls in gserver + operators/batch_norm_op.cc).

    Running stats are non-trainable ``stat`` buffers (excluded from optimizer
    updates/decay). In train mode the updated stats are recorded into the
    ``mutable`` collector; merge them back with ``nn.apply_stat_updates``.
    """

    def __init__(self, channels: int, momentum: float = 0.9, eps: float = 1e-5,
                 act: Union[None, str, Callable] = None):
        super().__init__()
        self.momentum, self.eps = momentum, eps
        self.act = _act(act)
        self.param("gamma", (channels,), I.ones)
        self.param("beta", (channels,), I.zeros)
        self.stat("moving_mean", (channels,), I.zeros)
        self.stat("moving_var", (channels,), I.ones)

    def __call__(self, params, x, train: bool = False, mutable=None, **kw):
        y, nm, nv = norm_ops.batch_norm(
            x, params["gamma"], params["beta"], params["stats"]["moving_mean"],
            params["stats"]["moving_var"], train=train, momentum=self.momentum,
            eps=self.eps)
        if train:
            self.record_stats(mutable, {"moving_mean": nm, "moving_var": nv})
        return self.act(y)


class LayerNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-5):
        super().__init__()
        self.eps = eps
        self.param("gamma", (dim,), I.ones)
        self.param("beta", (dim,), I.zeros)

    def __call__(self, params, x, **kw):
        return norm_ops.layer_norm(x, params["gamma"], params["beta"], self.eps)


class Dropout(Module):
    """ref: operators/dropout_op.cc; needs rng passed at call time."""

    def __init__(self, rate: float):
        super().__init__()
        self.rate = rate

    def __call__(self, params, x, train: bool = False, rng: Optional[jax.Array] = None, **kw):
        if not train or rng is None:
            return x
        return dropout_op(x, self.rate, rng, train=True)


class MaxPool2D(Module):
    def __init__(self, kernel, stride=None, padding=0):
        super().__init__()
        self.kernel, self.stride, self.padding = kernel, stride, padding

    def __call__(self, params, x, **kw):
        return pool_ops.max_pool2d(x, self.kernel, self.stride, self.padding)


class AvgPool2D(Module):
    def __init__(self, kernel, stride=None, padding=0):
        super().__init__()
        self.kernel, self.stride, self.padding = kernel, stride, padding

    def __call__(self, params, x, **kw):
        return pool_ops.avg_pool2d(x, self.kernel, self.stride, self.padding)
