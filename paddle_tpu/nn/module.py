"""Minimal functional module system.

This replaces the reference's ``Layer`` base + ``NeuralNetwork`` topological executor
(gserver/layers/Layer.h:62, gserver/gradientmachines/NeuralNetwork.cpp:247-297) with a
TPU-idiomatic design: a Module is a *declaration* of parameters + a pure ``__call__``
over an explicit params pytree. There is no forward/backward pair per layer — JAX
autodiff derives the backward, and XLA schedules the whole graph (the reference's
per-layer timers/order bookkeeping disappears into the compiler).

Conventions:
* parameters declared in ``__init__`` via ``self.param(name, shape, init)``;
  child modules assigned as attributes are auto-registered.
* ``module.init(rng)`` -> nested dict pytree of arrays (a "ParameterMap", the analog of
  paddle.v2.parameters.Parameters).
* ``module(params, *args, train=False)`` is pure; dropout/BN take an explicit ``rng`` /
  mutable-state convention (BN returns updated stats when train=True).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from .initializer import Initializer, gen1_default


class _ParamSpec:
    __slots__ = ("shape", "init", "dtype")

    def __init__(self, shape, init, dtype):
        self.shape = tuple(shape)
        self.init = init
        self.dtype = dtype


class Module:
    """Base class; subclasses declare params/children in __init__.

    Two buffer kinds, mirroring the reference's typed parameter buffers
    (parameter/Parameter.h:60 bufs_[PARAMETER_VALUE/GRADIENT/MOMENTUM...]):
    * ``param`` — trainable; lives directly in the module's params subtree.
    * ``stat`` — non-trainable running state (e.g. BN moving stats); lives under a
      ``"stats"`` key in the subtree. Optimizers skip any leaf under ``"stats"``.
      Train-mode updates are collected through the ``mutable`` dict passed at call
      time and merged back with :func:`apply_stat_updates`.
    """

    def __init__(self):
        object.__setattr__(self, "_param_specs", {})
        object.__setattr__(self, "_stat_specs", {})
        object.__setattr__(self, "_children", {})
        object.__setattr__(self, "_path", "")

    def __setattr__(self, name, value):
        if isinstance(value, Module):
            self._children[name] = value
        elif isinstance(value, (list, tuple)) and value and all(isinstance(v, Module) for v in value):
            for i, v in enumerate(value):
                self._children[f"{name}_{i}"] = v
        object.__setattr__(self, name, value)

    def param(self, name: str, shape, init: Optional[Initializer] = None,
              dtype=jnp.float32) -> str:
        """Declare a parameter; returns its name for later lookup in the params dict."""
        if init is None:
            init = gen1_default()
        self._param_specs[name] = _ParamSpec(shape, init, dtype)
        return name

    def stat(self, name: str, shape, init: Optional[Initializer] = None,
             dtype=jnp.float32) -> str:
        """Declare non-trainable running state (BN moving stats etc.)."""
        if init is None:
            init = gen1_default()
        self._stat_specs[name] = _ParamSpec(shape, init, dtype)
        return name

    # ------------------------------------------------------------------
    def init(self, rng: jax.Array) -> Dict[str, Any]:
        """Build the nested params dict for this module tree (assigns paths)."""
        self._assign_paths("")
        return self._init(rng)

    def _assign_paths(self, path: str):
        object.__setattr__(self, "_path", path)
        for name, child in self._children.items():
            child._assign_paths(f"{path}/{name}" if path else name)

    def _init(self, rng: jax.Array) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        n = len(self._param_specs) + len(self._stat_specs) + len(self._children)
        keys = jax.random.split(rng, max(1, n))
        i = 0
        for name, spec in self._param_specs.items():
            out[name] = spec.init(keys[i], spec.shape, spec.dtype)
            i += 1
        if self._stat_specs:
            stats = {}
            for name, spec in self._stat_specs.items():
                stats[name] = spec.init(keys[i], spec.shape, spec.dtype)
                i += 1
            out["stats"] = stats
        for name, child in self._children.items():
            out[name] = child._init(keys[i])
            i += 1
        return out

    def record_stats(self, mutable, updates: Dict[str, jax.Array]):
        """Record train-mode stat updates into the caller-provided collector."""
        if mutable is not None:
            mutable[self._path] = updates

    def sublayers(self) -> Dict[str, "Module"]:
        return dict(self._children)

    def __call__(self, params, *args, **kwargs):
        raise NotImplementedError

    # convenience: iterate (path, leaf) over a params dict built by this module
    @staticmethod
    def named_parameters(params, prefix: str = "") -> List[Tuple[str, jax.Array]]:
        out = []
        for k, v in params.items():
            path = f"{prefix}.{k}" if prefix else k
            if isinstance(v, dict):
                out.extend(Module.named_parameters(v, path))
            else:
                out.append((path, v))
        return out


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def apply_stat_updates(params, mutable: Dict[str, Dict[str, jax.Array]]):
    """Merge collected stat updates (path -> {name: value}) back into params.

    Use with the train step:
        def loss_fn(p):
            mut = {}
            out = model(p, x, train=True, mutable=mut)
            return loss(out), mut
        (l, mut), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        params = apply_stat_updates(opt_params, mut)
    """
    if not mutable:
        return params
    params = dict(params)
    for path, updates in mutable.items():
        node = params
        keys = [k for k in path.split("/") if k]
        for k in keys:
            node[k] = dict(node[k])
            node = node[k]
        stats = dict(node.get("stats", {}))
        stats.update(updates)
        node["stats"] = stats
    return params


class Sequential(Module):
    """Chain of modules applied in order (topological list — the degenerate
    NeuralNetwork.cpp:259 layer loop)."""

    def __init__(self, *mods: Module):
        super().__init__()
        self.mods = list(mods)

    def __call__(self, params, x, **kw):
        for i, m in enumerate(self.mods):
            x = m(params[f"mods_{i}"], x, **kw)
        return x


class Lambda(Module):
    """Parameter-free function as a module."""

    def __init__(self, fn: Callable):
        super().__init__()
        self.fn = fn

    def __call__(self, params, x, **kw):
        return self.fn(x)
