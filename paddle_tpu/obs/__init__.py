"""paddle_tpu.obs — the unified observability plane.

PR 2 built the chaos plane (:mod:`paddle_tpu.faults`); this is its twin:
typed metrics (:class:`Counter`/:class:`Gauge`/:class:`Histogram` behind a
:class:`MetricsRegistry`), a span :class:`Tracer` with parent/child nesting
and an injectable clock, and exporters (Chrome ``trace_event`` for
Perfetto, Prometheus text, JSONL, a human summary that subsumes
``StatSet.report()``). See docs/design/observability.md for the metric and
span catalogue — the names are a public contract.

Zero cost when off — the ``faults`` no-op discipline: instrumented code
calls the module-level hooks below (``obs.count(...)``, ``obs.span(...)``)
which first check ``_SESSION is None``. With no session installed that is
one attribute load and a branch; production never pays for telemetry it
did not ask for.

Usage::

    from paddle_tpu import obs
    with obs.ObsSession().installed() as s:
        trainer.train(reader, params, num_passes=2)
        print(s.summary())
        s.save("run.jsonl")          # -> paddle_tpu obs export/summary
"""

from __future__ import annotations

from typing import Optional

from . import alerts, context, goodput, health, roofline
from .catalogue import CATALOGUE, SPANS
from .export import (chrome_trace, merge_dumps, prometheus_text, read_jsonl,
                     summary, write_jsonl)
from .flight import FlightRecorder
from .metrics import (DEFAULT_BUCKETS, METRIC_NAME_RE, Counter, Gauge,
                      Histogram, MetricsRegistry)
from .session import ObsSession
from .trace import NULL_SPAN, NullSpan, Tracer

__all__ = [
    "ObsSession", "MetricsRegistry", "Counter", "Gauge", "Histogram",
    "Tracer", "REGISTRY", "CATALOGUE", "SPANS", "METRIC_NAME_RE",
    "DEFAULT_BUCKETS", "chrome_trace", "prometheus_text", "summary",
    "read_jsonl", "write_jsonl", "merge_dumps", "is_active", "session",
    "install", "uninstall", "count", "gauge_set", "observe", "span",
    "instant", "server_span", "wire_context", "retry_observer",
    "FlightRecorder", "flight_recorder", "flight_dump", "NullSpan",
    "NULL_SPAN", "context", "goodput", "roofline", "health", "alerts",
    "req_phase", "request_ledger", "ensure_request_ledger",
]

#: process-global default registry — what an installed session reports into
#: unless the test injected its own
REGISTRY = MetricsRegistry()

#: the installed session; None = plane disabled (the fast path)
_SESSION: Optional[ObsSession] = None


def _install(s: ObsSession) -> None:
    global _SESSION
    if _SESSION is not None and _SESSION is not s:
        raise RuntimeError("another ObsSession is already installed")
    _SESSION = s
    from . import jaxhooks
    jaxhooks.ensure_registered()


def _uninstall(s: ObsSession) -> None:
    global _SESSION, _REQUESTS
    if _SESSION is s:
        _SESSION = None
        _REQUESTS = None


def install(registry: Optional[MetricsRegistry] = None, **kw) -> ObsSession:
    """Convenience: build + install a session in one call."""
    return ObsSession(registry=registry, **kw).install()


def uninstall() -> None:
    global _SESSION, _REQUESTS
    _SESSION = None
    _REQUESTS = None


def is_active() -> bool:
    return _SESSION is not None


def session() -> Optional[ObsSession]:
    return _SESSION


# -- module-level hooks (what instrumented code calls) --------------------------
# Each first checks `_SESSION is None`: one load + branch when the plane is
# off — the same contract as faults.fire/filter_* (faults/inject.py).

def count(name: str, n: float = 1, **labels) -> None:
    s = _SESSION
    if s is None:
        return
    s.registry.counter(name).inc(n, **labels)


def gauge_set(name: str, value: float, **labels) -> None:
    s = _SESSION
    if s is None:
        return
    s.registry.gauge(name).set(value, **labels)


def observe(name: str, value: float, **labels) -> None:
    s = _SESSION
    if s is None:
        return
    s.registry.histogram(name).observe(value, **labels)


def span(name: str, metric: Optional[str] = None, metric_labels=None,
         **attrs):
    """Trace span context manager; the shared :data:`NULL_SPAN` when off."""
    s = _SESSION
    if s is None:
        return NULL_SPAN
    return s.span(name, metric=metric, metric_labels=metric_labels, **attrs)


def server_span(name: str, ctx, **attrs):
    """Server-side handler span parented on a wire context (the ``trace``
    key of an RPC envelope — obs/context.py). A malformed/absent context
    degrades to a plain span; :data:`NULL_SPAN` when the plane is off."""
    s = _SESSION
    if s is None:
        return NULL_SPAN
    return s.span(name, remote=context.sanitize(ctx), **attrs)


def wire_context(sp) -> Optional[dict]:
    """The ``trace`` envelope value for a request issued inside span ``sp``
    (as returned by :func:`span`); None when the plane is off — requests
    then stay byte-identical to un-instrumented ones."""
    if _SESSION is None:
        return None
    return context.wire_context(sp)


def instant(name: str, **attrs) -> None:
    s = _SESSION
    if s is None:
        return
    s.tracer.instant(name, **attrs)


# -- flight recorder plumbing ---------------------------------------------------

#: the armed FlightRecorder; None = no tail capture (the fast path)
_FLIGHT: Optional[FlightRecorder] = None


def _set_flight(rec: Optional[FlightRecorder]) -> None:
    global _FLIGHT
    _FLIGHT = rec


# named flight_recorder, NOT flight: the bare name would shadow the
# paddle_tpu.obs.flight submodule attribute this package also exposes
def flight_recorder() -> Optional[FlightRecorder]:
    return _FLIGHT


def flight_dump(reason: str, final: bool = False) -> Optional[str]:
    """Dump the armed flight recorder's ring (no-op when none is armed) —
    what :func:`paddle_tpu.faults.fire` calls just before an injected
    raise and the trainer calls on preemption. Never raises."""
    f = _FLIGHT
    if f is None:
        return None
    return f.dump(reason, final=final)


# -- per-request timeline ledger ------------------------------------------------

#: the installed RequestLedger (obs/requests.py); None = no timeline
#: capture. Cleared alongside _SESSION so test isolation is automatic.
_REQUESTS = None


def _set_requests(led) -> None:
    global _REQUESTS
    _REQUESTS = led


# named request_ledger, NOT requests: the bare name would shadow the
# paddle_tpu.obs.requests submodule attribute this package also exposes
def request_ledger():
    return _REQUESTS


def ensure_request_ledger(ident: Optional[str] = None):
    """Install a default :class:`~paddle_tpu.obs.requests.RequestLedger`
    iff a session is installed and none is present yet — what the
    serving daemons/router call at construction so per-request timelines
    are always-on whenever the obs plane is. Returns the active ledger,
    or None when the plane is off."""
    global _REQUESTS
    if _SESSION is None:
        return None
    if _REQUESTS is None:
        from .requests import RequestLedger
        _REQUESTS = RequestLedger(ident=ident or _SESSION.process)
    return _REQUESTS


def req_phase(key, phase: str, dur: Optional[float] = None,
              **extra) -> None:
    """Record a phase on the installed request ledger. The serving fast
    path calls this per request (not per token): same `_SESSION is None`
    one-load-one-branch discipline as the metric hooks, plus a None-key
    guard so un-keyed engine use (tests, embedded) records nothing."""
    if _SESSION is None:
        return
    led = _REQUESTS
    if led is None or key is None:
        return
    led.phase(key, phase, dur=dur, **extra)


def retry_observer(subsystem: str):
    """A :class:`paddle_tpu.utils.retry.RetryPolicy` ``observer`` callback
    counting into ``<subsystem>.retries_total`` / ``.giveups_total`` /
    ``.backoff_seconds_total``. The policy stays obs-agnostic (no import
    cycle): it calls a plain callable; the callable checks the session."""

    def observer(event: str, **info) -> None:
        s = _SESSION
        if s is None:
            return
        if event == "attempt":
            s.registry.counter(f"{subsystem}.retries_total").inc()
            s.registry.counter(f"{subsystem}.backoff_seconds_total").inc(
                max(0.0, float(info.get("delay", 0.0))))
        elif event == "giveup":
            s.registry.counter(f"{subsystem}.giveups_total").inc()

    return observer
