"""Cluster metrics aggregation: workers push, the master merges and serves.

The missing multi-process half of PR 3: each process had its own registry
and dump, but the system the paper describes is a trainer fleet plus a
master — fleet-level telemetry (the Ascend field-study lesson, PAPERS.md
arXiv 2607.08215) needs ONE merged view. Three pieces:

* :class:`ClusterAggregator` — the master-side store. Workers push their
  registry snapshots over the new ``obs_push`` RPC
  (:meth:`MasterClient.obs_push`); the aggregator keeps the latest
  snapshot per worker and serves the merged sample list with every series
  label-tagged ``worker=<id>`` (the merged-registry label contract:
  same-named series from different workers stay distinct series).
* :class:`ObsPusher` — the worker-side background thread: every
  ``interval`` seconds (and once at stop) it pushes the current registry
  snapshot. Push failures are counted, never raised — telemetry must not
  take down the training loop it observes.
* :class:`ObsHttpServer` — a read-only HTTP endpoint (``paddle_tpu obs
  serve``) exposing ``/metrics`` (Prometheus text), ``/trace`` (Chrome
  JSON) and ``/summary`` over any dump provider — merged files on disk or
  a live master's ``obs_stats``.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from . import count as _count
from . import gauge_set as _gauge_set

#: sample fields the aggregator accepts off the wire — anything else is
#: dropped (a worker running newer code must not smuggle unbounded junk
#: into the master's memory)
_SAMPLE_KEYS = frozenset((
    "type", "name", "help", "labels", "value", "high_water",
    "buckets", "sum", "count", "max", "delta"))
_MAX_SAMPLES_PER_PUSH = 10_000


def _clean_sample(s: Any) -> Optional[Dict[str, Any]]:
    if not isinstance(s, dict) or not isinstance(s.get("name"), str):
        return None
    # every exporter keys on "type" and does arithmetic on the numeric
    # fields — a sample that would crash a later /metrics render is
    # dropped HERE, not stored (one bad push must not poison every scrape)
    if s.get("type") not in ("counter", "gauge", "histogram"):
        return None
    out = {k: v for k, v in s.items() if k in _SAMPLE_KEYS}
    for k in ("value", "high_water", "sum", "max", "delta"):
        if k in out:
            try:
                out[k] = float(out[k])
            except (TypeError, ValueError):
                return None
    if "count" in out:
        try:
            out["count"] = int(out["count"])
        except (TypeError, ValueError):
            return None
    if "buckets" in out:
        # exporters iterate [le, cumulative] pairs and do arithmetic on
        # both; anything else would 500 every later scrape
        try:
            out["buckets"] = [
                [le if le == "+Inf" else float(le), int(cum)]
                for le, cum in out["buckets"]]
        except (TypeError, ValueError):
            return None
    labels = out.get("labels")
    out["labels"] = ({str(k): str(v) for k, v in labels.items()}
                     if isinstance(labels, dict) else {})
    return out


def telemetry_client(host: str, port: int):
    """Fail-fast MasterClient for telemetry traffic (pushes and scrapes):
    ONE attempt, short socket deadline. Telemetry must never inherit the
    data plane's 5-attempt backoff budget — a down master should cost a
    scrape a few seconds, not wedge it (or a lock-sharing caller) for the
    full retry window."""
    from ..runtime.master_service import MasterClient
    return MasterClient(host, int(port), retries=1, call_timeout=3.0)


def wire_safe_samples(samples: Any) -> List[Any]:
    """JSON-frame-safe copy of collect() samples: nonfinite floats become
    the strings ``"NaN"``/``"+Inf"``/``"-Inf"`` — ``json.dumps`` would
    otherwise emit bare ``NaN``/``Infinity`` tokens, which are not legal
    JSON and which the native frame parser rejects (one inf gauge would
    permanently fail a worker's pushes). The strings round-trip on the
    receiving side: ``float("+Inf")``/``float("NaN")`` in
    :func:`_clean_sample` restore the values."""
    import math

    def fix(v):
        if isinstance(v, float) and not math.isfinite(v):
            return "NaN" if math.isnan(v) else ("+Inf" if v > 0 else "-Inf")
        return v

    out: List[Any] = []
    for s in samples:
        if not isinstance(s, dict):
            out.append(s)
            continue
        s = {k: fix(v) for k, v in s.items()}
        try:
            if isinstance(s.get("buckets"), list):
                s["buckets"] = [[fix(le), cum] for le, cum in s["buckets"]]
        except (TypeError, ValueError):
            pass                      # malformed: the server will drop it
        out.append(s)
    return out


class ClusterAggregator:
    """Latest-snapshot-per-worker store behind the master's ``obs_push``
    — plus, since ISSUE 15, the fleet health plane: every push also lands
    in a bounded windowed :class:`~paddle_tpu.obs.health.TimeSeriesStore`
    (``history``), and a rate-limited evaluation pass derives per-worker
    health (``health`` — straggler score, heartbeat jitter, goodput EWMA;
    emitted as ``cluster.health_*`` gauges and recorded back into the
    store) and runs the declarative ``alerts`` engine over it.

    ``ttl`` bounds both memory and staleness: worker ids embed pids, so a
    chaos-churned fleet (preempt, restart, repeat for days) would
    otherwise accumulate one frozen snapshot per dead incarnation forever.
    A worker that stops pushing for ``ttl`` seconds ages out of the
    merged view (and out of memory) on the next push or read; its history
    series age out with it.
    """

    def __init__(self, ttl: float = 900.0,
                 clock: Optional[Callable[[], float]] = None,
                 window_s: float = 300.0, max_points: int = 240,
                 rules: Any = None, eval_interval_s: float = 2.0):
        import time
        from .alerts import AlertEngine, default_rules
        from .health import FleetHealth, TimeSeriesStore
        self.ttl = ttl
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        # worker -> (last_push_monotonic, cleaned samples)
        self._snaps: Dict[str, Any] = {}
        self.history = TimeSeriesStore(window_s=window_s,
                                       max_points=max_points,
                                       clock=self._clock)
        self.health = FleetHealth(clock=self._clock)
        self.alerts = AlertEngine(
            default_rules() if rules is None else rules, self.history)
        self.eval_interval_s = float(eval_interval_s)
        self._last_eval = float("-inf")
        self._health_snapshot: Dict[str, Dict[str, Any]] = {}
        # per-request timeline aggregation (obs/requests.py): workers'
        # ledger exports land here; burn-rate alert transitions get the
        # slowest-K exemplars attached at evaluation time
        from .requests import RequestStore
        self.requests = RequestStore(clock=self._clock)
        #: committed fleet-actor actions (ISSUE 18), newest last — what
        #: lets an operator tell "recommendation held" from "actor acted"
        self.actions: deque = deque(maxlen=64)

    def _prune_locked(self) -> None:
        cutoff = self._clock() - self.ttl
        dead = [w for w, (ts, _) in self._snaps.items() if ts < cutoff]
        for wid in dead:
            del self._snaps[wid]
        if dead:
            # prune history to workers still alive by EITHER signal:
            # pushing snapshots, or feeding the health plane (elastic
            # workers feed shard timings/heartbeats without ever
            # obs_pushing — membership leave/evict forget()s them, which
            # is what lets their series age out here)
            self.history.prune(set(self._snaps)
                               | self.health.known_workers())

    def push(self, worker: str, samples: Any) -> int:
        """Replace ``worker``'s snapshot; returns the accepted count. The
        cleaned samples also append to the windowed history, and (rate-
        limited by ``eval_interval_s``) the health/alert pass runs."""
        if not isinstance(samples, (list, tuple)):
            samples = []
        cleaned = []
        for s in samples[:_MAX_SAMPLES_PER_PUSH]:
            c = _clean_sample(s)
            if c is not None:
                cleaned.append(c)
        now = self._clock()
        with self._lock:
            self._snaps[str(worker)] = (now, cleaned)
            self._prune_locked()
            n_workers = len(self._snaps)
        self.history.record(worker, cleaned, ts=now)
        _gauge_set("master.obs_workers", n_workers)
        self.maybe_evaluate(now)
        return len(cleaned)

    # -- the health/alert evaluation pass -----------------------------------
    def maybe_evaluate(self, now: Optional[float] = None) -> bool:
        """Run the derivation + alert pass if ``eval_interval_s`` elapsed
        since the last one (the push path's rate limit); tests drive
        :meth:`evaluate` directly."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            if now - self._last_eval < self.eval_interval_s:
                return False
            self._last_eval = now
        self.evaluate(now)
        return True

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Derive per-worker health, emit/record the ``cluster.health_*``
        gauges, then evaluate the alert rules. Returns the health
        snapshot."""
        now = self._clock() if now is None else float(now)
        snap = self.health.snapshot(self.history, now=now)

        def record(metric: str, v: float, w: str) -> None:
            # back into the store: alert rules threshold derived health
            # exactly like any pushed series
            self.history.record_value(w, metric, v,
                                      labels={"worker": w}, ts=now)

        for w, h in snap.items():
            v = h.get("straggler_score")
            if v is not None:
                _gauge_set("cluster.health_straggler_score", v, worker=w)
                record("cluster.health_straggler_score", v, w)
            v = h.get("goodput_ewma")
            if v is not None:
                _gauge_set("cluster.health_goodput_ewma", v, worker=w)
                record("cluster.health_goodput_ewma", v, w)
            v = h.get("heartbeat_jitter")
            if v is not None:
                _gauge_set("cluster.health_heartbeat_jitter", v, worker=w)
                record("cluster.health_heartbeat_jitter", v, w)
        with self._lock:
            self._health_snapshot = snap
        transitions = self.alerts.evaluate(now)
        if transitions:
            # answer "burn driven by WHAT" at the moment it fires: the
            # slowest-K stitched timelines decorate each serving SLO
            # transition IN PLACE — the same dicts live in the engine's
            # bounded events deque, so /alerts and the flight ring see
            # the exemplars for free
            ex = None
            for ev in transitions:
                args = ev.get("args") or {}
                if args.get("state") != "fired" or not str(
                        args.get("metric", "")).startswith("serving."):
                    continue
                if ex is None:
                    ex = self.requests.exemplars()
                if ex:
                    args["exemplars"] = ex
        return snap

    def forget_worker(self, worker: str) -> None:
        """A worker authoritatively departed (membership leave/eviction):
        drop its health feeds AND its history series now — the next alert
        evaluation then resolves anything firing on it (series_gone)
        instead of freezing a dead incarnation's alert as active."""
        self.health.forget(worker)
        self.history.drop_worker(worker)
        # completed requests lose the departed worker's legs; in-flight
        # ones keep them — their re-routed remainder still needs to
        # stitch against what this worker recorded before it died
        self.requests.forget_worker(worker)

    def push_requests(self, worker: str, timelines: Any) -> int:
        """Absorb one worker's request-timeline export (the scrape pump
        and the daemons' loopback push land here); wire-tolerant."""
        return self.requests.push(str(worker), timelines)

    def note_action(self, entry: Dict[str, Any]) -> Dict[str, Any]:
        """Journal one COMMITTED autoscale action (the ``act_report``
        ext-op lands here): stamps the aggregator clock, appends to the
        bounded journal, and emits/records the committed-action signal —
        ``cluster.autoscale_committed`` is the acted-on twin of the
        tentative ``cluster.autoscale_signal`` gauge, and diverges from
        it exactly while hysteresis/cooldowns hold the fleet still."""
        from .health import MASTER_WORKER
        now = self._clock()
        e = {"ts": now,
             "actor": str(entry.get("actor", "")),
             "action": str(entry.get("action", "")),
             "population": str(entry.get("population", "")),
             "worker": str(entry.get("worker", "")),
             "reason": str(entry.get("reason", ""))[:400],
             "signal": float(entry.get("signal", 0.0) or 0.0)}
        with self._lock:
            self.actions.append(e)
        _gauge_set("cluster.autoscale_committed", e["signal"])
        _count("cluster.actor_actions_total",
               population=e["population"] or "unknown",
               action=e["action"] or "unknown")
        self.history.record_value(MASTER_WORKER,
                                  "cluster.autoscale_committed",
                                  e["signal"], ts=now)
        return e

    def recent_actions(self, n: int = 32) -> List[Dict[str, Any]]:
        """The newest ``n`` committed actions, oldest first (the
        ``obs_health`` reply's ``actions`` field)."""
        with self._lock:
            return list(self.actions)[-n:]

    def health_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """The last evaluated per-worker health (the ``obs_health`` op's
        payload); empty before the first evaluation."""
        with self._lock:
            return {w: dict(h) for w, h in self._health_snapshot.items()}

    def workers(self) -> List[str]:
        with self._lock:
            self._prune_locked()
            return sorted(self._snaps)

    def merged_samples(self) -> List[Dict[str, Any]]:
        """Live workers' samples, each tagged ``worker=<id>`` (an existing
        worker label — a relayed merge — wins)."""
        with self._lock:
            self._prune_locked()
            items = sorted((w, s) for w, (_, s) in self._snaps.items())
        out: List[Dict[str, Any]] = []
        for wid, samples in items:
            for s in samples:
                s = dict(s)
                labels = dict(s.get("labels") or {})
                labels.setdefault("worker", wid)
                s["labels"] = labels
                out.append(s)
        return out


class ObsPusher:
    """Background worker->master snapshot pusher.

    Args:
      client: a :class:`~paddle_tpu.runtime.master_service.MasterClient`
        (or anything with ``obs_push(worker, samples)``).
      worker: this worker's id in the merged view.
      registry: snapshot source; defaults to the installed session's
        registry at each push (so a late-installed session still reports).
      interval: seconds between pushes; the stop path pushes once more so
        short runs still land their final counts.
    """

    def __init__(self, client, worker: str, registry=None,
                 interval: float = 2.0):
        self.client = client
        self.worker = str(worker)
        self.registry = registry
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _samples(self) -> Optional[List[Dict[str, Any]]]:
        reg = self.registry
        if reg is None:
            from . import _SESSION   # read the live value at call time
            reg = _SESSION.registry if _SESSION is not None else None
        return reg.collect() if reg is not None else None

    def push_once(self) -> bool:
        samples = self._samples()
        if samples is None:
            return False
        try:
            self.client.obs_push(self.worker, samples)
        except (OSError, ConnectionError):
            # the master being down is a data-plane problem the retry
            # layers already surface; telemetry just counts and moves on
            _count("obs.push_failures_total")
            return False
        _count("obs.pushes_total")
        return True

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.push_once()

    def start(self) -> "ObsPusher":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="obs-pusher")
            self._thread.start()
        return self

    def stop(self, final_push: bool = True) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if final_push:
            self.push_once()


class ObsHttpServer:
    """Read-only HTTP view over a dump provider (``paddle_tpu obs serve``).

    ``provider`` is called per request so the served view is always
    current (re-reading dump files, or re-polling a live master). GET
    only; any other method is 405; unknown paths 404.
    """

    ROUTES = ("/metrics", "/trace", "/summary", "/alerts", "/requests",
              "/")

    def __init__(self, provider: Callable[[], Dict[str, Any]],
                 host: str = "127.0.0.1", port: int = 0):
        import http.server

        from .export import chrome_trace, prometheus_text, summary
        from .health import health_table
        from .requests import group_legs, stitch
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # tests stay quiet
                pass

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = prometheus_text(outer.provider()).encode()
                        ctype = "text/plain; version=0.0.4"
                    elif path == "/trace":
                        body = json.dumps(
                            chrome_trace(outer.provider())).encode()
                        ctype = "application/json"
                    elif path == "/alerts":
                        # alert transitions are dump EVENTS (name="alert")
                        # plus whatever live state the provider attached
                        # ("alerts" key, master mode) — file mode works
                        # from the events alone
                        dump = outer.provider()
                        events = [e for e in dump.get("events", ())
                                  if e.get("name") == "alert"]
                        body = json.dumps(
                            {"active": dump.get("alerts") or [],
                             "events": events,
                             "actions": dump.get("actions") or []},
                            indent=1).encode()
                        ctype = "application/json"
                    elif path == "/requests":
                        # raw leg timelines ride the dump ("requests"
                        # key: session dumps, merged files, or the
                        # master's store) — stitched here so every
                        # consumer sees one timeline per request
                        dump = outer.provider()
                        reqs = []
                        for legs in group_legs(
                                dump.get("requests")).values():
                            st = stitch(legs)
                            if st is not None:
                                reqs.append(st)
                        reqs.sort(key=lambda s: s.get("t0_unix", 0.0))
                        body = json.dumps(
                            {"requests": reqs,
                             "exemplars": dump.get("exemplars") or []},
                            indent=1).encode()
                        ctype = "application/json"
                    elif path in ("/summary", "/"):
                        dump = outer.provider()
                        text = summary(dump)
                        table = health_table(
                            dump.get("metrics", ()),
                            alerts=[e for e in dump.get("events", ())
                                    if e.get("name") == "alert"]
                            + (dump.get("alerts") or []),
                            health=dump.get("health"),
                            actions=dump.get("actions"))
                        if table:
                            text += "\n== fleet health ==\n" + table
                        body = (text + "\n").encode()
                        ctype = "text/plain"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:    # a torn dump must not kill serve
                    # control chars stripped: the message lands in the
                    # HTTP status line, and a hostile upstream error
                    # string with CRLF would otherwise inject headers
                    detail = "".join(
                        ch for ch in f"{type(e).__name__}: {e}"[:200]
                        if ch.isprintable())
                    self.send_error(500, detail)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        class Server(http.server.ThreadingHTTPServer):
            daemon_threads = True

        self.provider = provider
        self._server = Server((host, port), Handler)
        self.address = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObsHttpServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="obs-serve")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
