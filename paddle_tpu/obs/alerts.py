"""Declarative alert rules over the fleet's windowed time-series.

The rules half of the fleet health plane (:mod:`paddle_tpu.obs.health` is
storage + derivation). Three rule kinds, all evaluated against a
:class:`~paddle_tpu.obs.health.TimeSeriesStore` with an injectable clock
(no rule ever sleeps):

* **threshold** — the newest in-window value of every matching series
  compared against a bound (``op`` in ``> < >= <=``). Fires per SERIES
  (a straggler alert names its worker), after ``for_windows`` consecutive
  true evaluations, and resolves only after ``for_windows`` consecutive
  false ones — hysteresis both ways, so one noisy sample neither fires
  nor clears an alert.
* **absence** — the series family has no point newer than ``window_s``:
  a worker that stopped pushing, a heartbeat stream gone quiet. Evaluated
  per known series; a store that never saw the metric stays silent
  (absence of a series ≠ absence of data).
* **burn_rate** — the SLO rule for histogram series (serving ``ttft`` /
  ``tpot``): over a SHORT and a LONG window, the fraction of observations
  above ``slo_le`` (bad fraction) divided by the error ``budget`` is the
  burn rate; the rule is true only when BOTH windows burn faster than
  ``burn_factor`` — the classic multi-window discipline: the short window
  makes detection fast, the long window stops a single bad second from
  paging. ``slo_le`` must sit on (or below) an actual bucket boundary of
  the histogram; the math uses the nearest boundary <= slo_le and says so
  in the event.

Firing/resolving transitions are **structured events** shaped exactly
like Tracer instants (``name="alert"``), so every existing consumer gets
them for free: ``obs.instant`` puts them in the live Tracer (hence the
flight-recorder ring and every ``obs export`` chrome trace), the engine
keeps its own bounded deque for ``obs serve /alerts`` and the master's
``obs_health`` op, and ``alerts.fired_total``/``alerts.active`` make the
alert stream itself observable.

Rules must reference CATALOGUED metric names and declared label keys —
the ``L009`` lint (analysis/lints.py) enforces it over the shipped
defaults in ``paddle_tpu lint`` and the tree-clean suite test.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from .health import TimeSeriesStore

KINDS = ("threshold", "absence", "burn_rate")
_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda v, t: v > t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    "<=": lambda v, t: v <= t,
}


class AlertRule:
    """One declarative rule. Authoring errors (unknown kind/op, a
    burn-rate rule without an SLO bound) raise HERE — a malformed rule
    must fail at definition, not silently never fire."""

    __slots__ = ("name", "metric", "kind", "labels", "op", "threshold",
                 "for_windows", "window_s", "short_s", "long_s", "slo_le",
                 "budget", "burn_factor", "severity", "description")

    def __init__(self, name: str, metric: str, *, kind: str = "threshold",
                 labels: Optional[Dict[str, str]] = None, op: str = ">",
                 threshold: Optional[float] = None, for_windows: int = 2,
                 window_s: float = 60.0, short_s: float = 60.0,
                 long_s: float = 300.0, slo_le: Optional[float] = None,
                 budget: float = 0.1, burn_factor: float = 1.0,
                 severity: str = "warning", description: str = ""):
        if kind not in KINDS:
            raise ValueError(f"unknown alert kind {kind!r} (one of {KINDS})")
        if op not in _OPS:
            raise ValueError(f"unknown alert op {op!r} "
                             f"(one of {sorted(_OPS)})")
        if kind == "threshold" and threshold is None:
            raise ValueError(f"threshold rule {name!r} needs threshold=")
        if kind == "burn_rate":
            if slo_le is None:
                raise ValueError(f"burn_rate rule {name!r} needs slo_le=")
            if not (0.0 < budget < 1.0):
                raise ValueError(f"burn_rate rule {name!r}: budget must be "
                                 f"in (0, 1), got {budget!r}")
            if short_s >= long_s:
                raise ValueError(f"burn_rate rule {name!r}: short_s must "
                                 "be < long_s (multi-window contract)")
        if for_windows < 1:
            raise ValueError(f"rule {name!r}: for_windows must be >= 1")
        self.name = str(name)
        self.metric = str(metric)
        self.kind = kind
        self.labels = dict(labels or {})
        self.op = op
        self.threshold = threshold
        self.for_windows = int(for_windows)
        self.window_s = float(window_s)
        self.short_s = float(short_s)
        self.long_s = float(long_s)
        self.slo_le = slo_le
        self.budget = float(budget)
        self.burn_factor = float(burn_factor)
        self.severity = str(severity)
        self.description = str(description)

    def to_dict(self) -> Dict[str, Any]:
        d = {"name": self.name, "metric": self.metric, "kind": self.kind,
             "severity": self.severity}
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.kind == "threshold":
            d.update(op=self.op, threshold=self.threshold)
        if self.kind == "burn_rate":
            d.update(slo_le=self.slo_le, budget=self.budget,
                     short_s=self.short_s, long_s=self.long_s,
                     burn_factor=self.burn_factor)
        return d


class _RuleState:
    __slots__ = ("true_streak", "false_streak", "firing", "since", "value")

    def __init__(self):
        self.true_streak = 0
        self.false_streak = 0
        self.firing = False
        self.since: Optional[float] = None
        self.value: Optional[float] = None


def _bad_fraction(points, slo_le: float) -> Optional[Tuple[float, int]]:
    """(fraction of window observations above slo_le, window count) from
    a histogram series' cumulative snapshots; None without new traffic."""
    snaps = [(t, v) for t, v in points if isinstance(v, dict)]
    if len(snaps) < 2:
        return None
    first, last = snaps[0][1], snaps[-1][1]
    dn = last.get("count", 0) - first.get("count", 0)
    if dn <= 0:
        return None

    def good(snap):
        best = 0
        for le, cum in snap.get("buckets", ()):
            if le == "+Inf":
                continue
            try:
                if float(le) <= slo_le:
                    best = cum
            except (TypeError, ValueError):
                continue
        return best

    dgood = good(last) - good(first)
    bad = max(dn - max(dgood, 0), 0)
    return bad / dn, dn


class AlertEngine:
    """Evaluates rules over a store; owns the firing state machine.

    One engine per aggregator (the master's). ``evaluate()`` is driven by
    the aggregator's push path (rate-limited there) or directly by tests;
    the clock is the store's unless overridden, so a fake-clock test
    controls both with one counter.
    """

    def __init__(self, rules, store: TimeSeriesStore, *,
                 clock: Optional[Callable[[], float]] = None,
                 max_events: int = 256):
        self.rules: List[AlertRule] = list(rules or ())
        self.store = store
        self._clock = clock or store._clock
        self._lock = threading.Lock()
        # (rule name, series-identity tuple) -> state
        self._state: Dict[Tuple[str, Tuple], _RuleState] = {}
        #: bounded transition log, newest last (the /alerts payload)
        self.events: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max_events)

    def add_rules(self, rules) -> None:
        """Append rules, REPLACING any same-named one — a serving daemon
        registering its engine's configured SLO targets must override the
        aggregator's same-named defaults, not be silently dropped (an
        operator-set slo_le evaluated at the default would be exactly the
        silent-alerting failure L009 exists to stop). Replaced rules'
        firing state resets (old streaks were judged under old params)."""
        with self._lock:
            by_name = {r.name: i for i, r in enumerate(self.rules)}
            for r in rules:
                i = by_name.get(r.name)
                if i is None:
                    by_name[r.name] = len(self.rules)
                    self.rules.append(r)
                else:
                    self.rules[i] = r
                    for k in [k for k in self._state if k[0] == r.name]:
                        del self._state[k]

    # -- evaluation ---------------------------------------------------------
    def _series_matching(self, rule: AlertRule):
        """(worker, labels, points) for every stored series of the rule's
        metric whose labels are a superset of the rule's filter."""
        out = []
        for worker, labels, pts in self.store.series_for(rule.metric):
            if all(labels.get(k) == v for k, v in rule.labels.items()):
                out.append((worker, labels, pts))
        return out

    def _condition(self, rule: AlertRule, worker, labels, pts,
                   now: float) -> Tuple[Optional[bool], Optional[float],
                                        Dict[str, Any]]:
        """(condition, representative value, extra event args); condition
        None = not evaluable this round (no streak movement either way)."""
        if rule.kind == "threshold":
            vals = [(t, v) for t, v in pts
                    if isinstance(v, (int, float))
                    and t >= now - rule.window_s]
            if not vals:
                return None, None, {}
            v = float(vals[-1][1])
            return _OPS[rule.op](v, rule.threshold), v, {}
        if rule.kind == "absence":
            newest = max((t for t, _ in pts), default=None)
            if newest is None:
                return None, None, {}
            silent = now - newest
            return silent > rule.window_s, silent, {"silent_s": silent}
        # burn_rate
        short = _bad_fraction([(t, v) for t, v in pts
                               if t >= now - rule.short_s], rule.slo_le)
        long_ = _bad_fraction([(t, v) for t, v in pts
                               if t >= now - rule.long_s], rule.slo_le)
        if short is None or long_ is None:
            return None, None, {}
        burn_s = short[0] / rule.budget
        burn_l = long_[0] / rule.budget
        cond = (burn_s > rule.burn_factor and burn_l > rule.burn_factor)
        return cond, burn_s, {"burn_short": burn_s, "burn_long": burn_l,
                              "slo_le": rule.slo_le}

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation round; returns this round's TRANSITION events
        (fired / resolved), each already recorded and emitted."""
        from . import count as _count
        from . import gauge_set as _gauge_set
        from . import instant as _instant
        now = self._clock() if now is None else float(now)
        transitions: List[Dict[str, Any]] = []
        #: every (rule, series) whose series still EXISTS this round —
        #: state for anything else belongs to a vanished series (worker
        #: TTL'd/evicted out of the store) and is resolved+dropped below,
        #: so a dead incarnation can neither alert forever nor leak state
        seen: set = set()
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            for worker, labels, pts in self._series_matching(rule):
                key = (rule.name, (worker,) + tuple(sorted(labels.items())))
                seen.add(key)
                cond, value, extra = self._condition(
                    rule, worker, labels, pts, now)
                if cond is None:
                    continue
                with self._lock:
                    st = self._state.get(key)
                    if st is None:
                        st = self._state[key] = _RuleState()
                    st.value = value
                    if cond:
                        st.true_streak += 1
                        st.false_streak = 0
                    else:
                        st.false_streak += 1
                        st.true_streak = 0
                    fire = (not st.firing
                            and st.true_streak >= rule.for_windows)
                    resolve = (st.firing
                               and st.false_streak >= rule.for_windows)
                    if fire:
                        st.firing, st.since = True, now
                    elif resolve:
                        st.firing, st.since = False, None
                if not (fire or resolve):
                    continue
                state = "fired" if fire else "resolved"
                args: Dict[str, Any] = {
                    "rule": rule.name, "state": state,
                    "metric": rule.metric, "severity": rule.severity,
                    "worker": worker, "value": value}
                args.update(extra)
                if labels:
                    args["labels"] = dict(labels)
                ev = {"kind": "instant", "name": "alert", "ts": now,
                      "tid": 0, "parent": None, "args": args}
                with self._lock:
                    self.events.append(ev)
                transitions.append(ev)
                # the live tracer (-> flight ring -> chrome export) and
                # the metric stream see every transition
                _instant("alert", **args)
                if fire:
                    _count("alerts.fired_total", rule=rule.name)
                else:
                    _count("alerts.resolved_total", rule=rule.name)
        # series-gone reaping: state whose series vanished from the store
        with self._lock:
            gone = [(k, st) for k, st in self._state.items()
                    if k not in seen]
            for k, _ in gone:
                del self._state[k]
        for (name, ident), st in gone:
            if not st.firing:
                continue
            args = {"rule": name, "state": "resolved", "reason":
                    "series_gone", "worker": ident[0], "value": st.value}
            ev = {"kind": "instant", "name": "alert", "ts": now,
                  "tid": 0, "parent": None, "args": args}
            with self._lock:
                self.events.append(ev)
            transitions.append(ev)
            _instant("alert", **args)
            _count("alerts.resolved_total", rule=name)
        _gauge_set("alerts.active", float(len(self.active())))
        return transitions

    # -- reading ------------------------------------------------------------
    def active(self) -> List[Dict[str, Any]]:
        """Currently-firing alerts: rule, series identity, value, since."""
        with self._lock:
            out = []
            for (name, ident), st in sorted(self._state.items()):
                if st.firing:
                    out.append({"rule": name, "worker": ident[0],
                                "labels": dict(ident[1:]),
                                "value": st.value, "since": st.since,
                                "state": "firing"})
            return out

    def recent_events(self, n: int = 64) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.events)[-n:]


# -- shipped rule sets ----------------------------------------------------------

def serving_slo_rules(ttft_slo_s: float = 1.0, tpot_slo_s: float = 0.25,
                      budget: float = 0.1, *, short_s: float = 60.0,
                      long_s: float = 300.0) -> List[AlertRule]:
    """Default multi-window burn-rate rules for the serving SLO pair.
    ``ServingEngine.alert_rules()`` parameterizes these with its
    configured targets; the bare defaults keep ``paddle_tpu lint`` and
    file-mode ``obs serve`` meaningful without an engine."""
    return [
        AlertRule("serving_ttft_slo_burn", "serving.ttft_seconds",
                  kind="burn_rate", slo_le=ttft_slo_s, budget=budget,
                  short_s=short_s, long_s=long_s, severity="page",
                  description="TTFT error-budget burn over both windows"),
        AlertRule("serving_tpot_slo_burn", "serving.tpot_seconds",
                  kind="burn_rate", slo_le=tpot_slo_s, budget=budget,
                  short_s=short_s, long_s=long_s, severity="page",
                  description="TPOT error-budget burn over both windows"),
    ]


def default_rules() -> List[AlertRule]:
    """The shipped rule set every master aggregator starts with: the
    derived-health detectors (thresholds match FleetHealth's constants —
    one owner) plus the serving SLO burn rates at their default targets.
    ``paddle_tpu lint`` runs L009 over exactly this list."""
    from .health import FleetHealth
    return [
        AlertRule("worker_straggler", "cluster.health_straggler_score",
                  kind="threshold", op=">",
                  threshold=FleetHealth.STRAGGLER_RATIO, for_windows=2,
                  description="worker shard latency over the fleet median"),
        AlertRule("worker_heartbeat_jitter",
                  "cluster.health_heartbeat_jitter",
                  kind="threshold", op=">", threshold=2.0, for_windows=2,
                  description="heartbeat arrival stddev (seconds)"),
        AlertRule("worker_goodput_collapse", "cluster.health_goodput_ewma",
                  kind="threshold", op="<", threshold=0.05, for_windows=3,
                  description="smoothed goodput ratio collapsed"),
        AlertRule("worker_telemetry_absent", "goodput.ratio",
                  kind="absence", window_s=60.0,
                  description="a worker's pushes went quiet"),
    ] + serving_slo_rules()
