"""The metric & span catalogue — the observability plane's public contract.

Every metric the built-in instrumentation emits is declared here with its
kind and meaning. Names are API: dashboards, alerts and tests key on them,
so renaming one is a breaking change. ``paddle_tpu lint`` runs the ``L005``
metric-naming lint (analysis/lints.py) over this table, and
tests/test_obs.py asserts the table itself stays convention-clean.

Kinds: ``counter`` (monotonic, suffix ``_total``), ``gauge`` (point-in-time,
no reserved suffix), ``histogram`` (distributions, suffix ``_seconds`` /
``_bytes``). Metrics whose emitter attaches labels declare them as a third
tuple element — the ``L005`` lint checks those label keys for unbounded
cardinality (a raw path or task payload as a label value would explode the
series space); the merged cluster view additionally tags every pushed
series ``worker=<id>``.

Span names (exported to Chrome trace_event; nesting by same-thread
containment, cross-process parenting by the spans' ``remote`` wire
context) are catalogued in :data:`SPANS`.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: name -> (kind, help[, labels]). Keep sorted by subsystem;
#: docs/design/observability.md renders this table verbatim.
CATALOGUE: Dict[str, Tuple[str, ...]] = {
    # -- ckpt: trainer/checkpoint.py ------------------------------------
    "ckpt.saves_total": ("counter", "checkpoint pass dirs published"),
    "ckpt.bytes_total": ("counter", "member payload bytes written"),
    "ckpt.write_seconds": ("histogram", "per-member write (incl. fsync)"),
    "ckpt.fsync_seconds": ("histogram", "per-fsync (file + dir) duration"),
    "ckpt.rename_seconds": ("histogram", "atomic publish rename duration"),
    # -- data: data/reader.py, data/prefetch.py, data/chunks.py ---------
    "data.queue_depth": ("gauge", "prefetch queue occupancy at consume; "
                                  "with several concurrent streams the "
                                  "value is the last-sampled stream's and "
                                  "high_water is the process-wide peak"),
    "data.starved_total": ("counter", "consumer found the prefetch queue "
                                      "empty after warm-up (producer "
                                      "behind)"),
    "data.timeouts_total": ("counter", "prefetch watchdog timeouts raised"),
    "data.prefetch_iters_total": ("counter", "DoubleBuffer iterations "
                                            "started"),
    "data.tasks_total": ("counter", "cloud_reader chunk tasks streamed"),
    "data.task_failures_total": ("counter", "chunk tasks reported failed "
                                            "to the master"),
    "data.retries_total": ("counter", "cloud_reader idle-poll retries"),
    "data.giveups_total": ("counter", "cloud_reader starvation deadlines"),
    "data.backoff_seconds_total": ("counter", "total poll backoff slept"),
    # -- decode: models/transformer.py generate_fused, serving/ -------
    "decode.dispatches_total": ("counter", "compiled decode-step programs "
                                           "dispatched from the host (ONE "
                                           "serves a whole token / segment "
                                           "/ verify span — the fused-"
                                           "decode contract), labels: "
                                           "route", ("route",)),
    "decode.tokens_total": ("counter", "tokens emitted by decode loops "
                                       "(generate_fused / continuous "
                                       "batching / speculative), labels: "
                                       "route", ("route",)),
    "decode.spec_proposed_total": ("counter", "draft tokens proposed to "
                                              "speculative verify"),
    "decode.spec_accepted_total": ("counter", "proposed tokens the "
                                              "target's verify accepted "
                                              "(acceptance rate = "
                                              "accepted/proposed)"),
    # -- faults: faults/inject.py ---------------------------------------
    "faults.injected_total": ("counter", "faults fired, labels: site, "
                                         "action — a chaos run is "
                                         "self-describing",
                              ("site", "action")),
    # -- fluid: fluid/executor.py ---------------------------------------
    "fluid.runs_total": ("counter", "Executor.run invocations"),
    "fluid.cache_hits_total": ("counter", "compiled-fn cache hits, labels: "
                                          "bucketed (was the feed padded "
                                          "by a BucketSpec)", ("bucketed",)),
    "fluid.cache_misses_total": ("counter", "compiled-fn cache misses "
                                            "(trace+compile paid), labels: "
                                            "bucketed", ("bucketed",)),
    "fluid.cache_evictions_total": ("counter", "LRU evictions from the "
                                               "bounded compiled-fn cache"),
    "fluid.cache_size": ("gauge", "live entries in the compiled-fn cache "
                                  "(bounded by Executor cache_capacity)"),
    "fluid.donated_bytes_total": ("counter", "persistable bytes handed to "
                                             "XLA as donated buffers "
                                             "(updated in place, no second "
                                             "HBM copy)"),
    "fluid.placed_bytes_total": ("counter", "persistable bytes device_put "
                                            "onto the executor's mesh per "
                                            "the resolved layout (init / "
                                            "load / restore placement)"),
    "fluid.param_bytes_per_device": ("gauge", "per-device share of the "
                                              "persistable footprint under "
                                              "the resolved shardings "
                                              "(replicated would equal "
                                              "param_bytes_global)"),
    "fluid.param_bytes_global": ("gauge", "total persistable bytes the "
                                          "mesh executor holds (the "
                                          "replicated footprint)"),
    "fluid.fused_regions_total": ("counter", "certified fusion groups "
                                             "activated into single fused "
                                             "dispatch regions (counted "
                                             "per plan decision, not per "
                                             "run), labels: source (tuned "
                                             "| forced)", ("source",)),
    "fluid.fusion_rejected_total": ("counter", "certified fusion groups "
                                               "REFUSED by the measured-"
                                               "only consult chain "
                                               "(tune/fusion.py), labels: "
                                               "reason (no_entry | stale | "
                                               "invalid_plan | cert_invalid"
                                               " | measured_slower | "
                                               "not_schedulable)",
                                   ("reason",)),
    "fluid.run_seconds": ("histogram", "whole Executor.run duration"),
    "fluid.verify_seconds": ("histogram", "static pre-flight "
                                          "(analysis.check_or_raise)"),
    "fluid.device_flops_total": ("counter", "FLOPs dispatched through "
                                            "cost-instrumented executables "
                                            "(fluid Executor, trainer step, "
                                            "fused decode) per XLA "
                                            "cost_analysis — the numerator "
                                            "of the derived roofline.mfu"),
    "fluid.device_bytes_total": ("counter", "HBM bytes streamed by cost-"
                                            "instrumented executables: XLA "
                                            "'bytes accessed' plus "
                                            "registered Pallas kernel "
                                            "models (custom calls report "
                                            "zero to XLA) — the numerator "
                                            "of roofline.hbm_bw_util"),
    # -- goodput: obs/goodput.py (trainer / v2 SGD / serving drivers) ----
    "goodput.compile_seconds_total": ("counter", "wall seconds inside XLA "
                                                 "backend compiles (stolen "
                                                 "from the enclosing "
                                                 "bucket), labels: "
                                                 "component", ("component",)),
    "goodput.host_input_seconds_total": ("counter", "wall seconds waiting "
                                                    "on readers/feeders/"
                                                    "admission assembly, "
                                                    "labels: component",
                                         ("component",)),
    "goodput.device_seconds_total": ("counter", "wall seconds dispatching "
                                                "device work and blocking "
                                                "on its results — the "
                                                "goodput numerator, "
                                                "labels: component",
                                     ("component",)),
    "goodput.host_sync_seconds_total": ("counter", "wall seconds in host-"
                                                   "side result handling "
                                                   "(loss reads, token "
                                                   "collection), labels: "
                                                   "component",
                                        ("component",)),
    "goodput.idle_seconds_total": ("counter", "window wall time no bucket "
                                              "claimed (event handlers, "
                                              "logging, scheduler waits), "
                                              "labels: component",
                                   ("component",)),
    "goodput.ratio": ("gauge", "device_seconds / wall over the open "
                               "window — the goodput fraction, labels: "
                               "component", ("component",)),
    # -- jax: obs/jaxhooks.py (jax.monitoring bridge) -------------------
    "jax.compiles_total": ("counter", "XLA backend compiles observed "
                                      "(one per executable built)"),
    "jax.compile_seconds": ("histogram", "XLA backend-compile durations"),
    # -- kernels: ops/pallas_kernels.py, ops/rnn.py entry points --------
    "kernels.bytes_total": ("counter", "modeled HBM bytes streamed by "
                                       "Pallas-kernel reads, one increment "
                                       "per dispatch (host decode loops "
                                       "count directly; launches inside a "
                                       "traced program are collected at "
                                       "trace time and re-emitted per run; "
                                       "decode: live cache rows, halved "
                                       "under int8 KV), labels: kernel",
                            ("kernel",)),
    "kernels.routes_total": ("counter", "auto-route decisions at the "
                                        "kernel entry points; counted when "
                                        "the routing Python runs — once "
                                        "per TRACE for in-jit sites, not "
                                        "per executed step, labels: "
                                        "kernel, route",
                             ("kernel", "route")),
    # -- lease: runtime/coord.py, runtime/lease.py ----------------------
    "lease.renews_total": ("counter", "lease renewals attempted"),
    "lease.renew_failures_total": ("counter", "renewals the server "
                                              "refused (lost lease)"),
    # -- master: runtime/master_service.py (MasterServer._dispatch) -----
    "master.requests_total": ("counter", "master RPCs dispatched through "
                                         "the PYTHON control plane (obs "
                                         "ops via the native fallback + "
                                         "in-process calls; the C++ data "
                                         "plane serves get_task et al. "
                                         "uncounted), labels: type",
                              ("type",)),
    "master.request_errors_total": ("counter", "Python-dispatched master "
                                               "RPCs answered with an "
                                               "error (or raising), "
                                               "labels: type", ("type",)),
    "master.obs_workers": ("gauge", "distinct workers whose metric "
                                    "snapshots the master currently holds"),
    # -- cluster: runtime/membership.py, trainer/elastic.py -------------
    "cluster.members": ("gauge", "workers currently registered under a "
                                 "live heartbeat lease (the elastic "
                                 "fleet size)"),
    "cluster.epoch": ("gauge", "membership view epoch — bumps on every "
                               "join / graceful leave / eviction; elastic "
                               "submissions stamped with an older epoch "
                               "are fence-refused"),
    "cluster.joins_total": ("counter", "mbr_join registrations accepted "
                                       "(incl. re-joins after eviction or "
                                       "a master restart)"),
    "cluster.leaves_total": ("counter", "members removed from the view, "
                                        "labels: reason (graceful = "
                                        "mbr_leave; evicted = missed "
                                        "heartbeat window; replaced = a "
                                        "newer same-name incarnation "
                                        "joined over a live one)",
                             ("reason",)),
    "cluster.heartbeats_total": ("counter", "membership heartbeats "
                                            "accepted (lease extended)"),
    "cluster.stale_rpcs_total": ("counter", "membership/elastic RPCs "
                                            "fence-refused with a "
                                            "structured code, labels: "
                                            "code (stale_epoch | "
                                            "stale_member | "
                                            "unknown_member | "
                                            "stale_step)", ("code",)),
    "cluster.resyncs_total": ("counter", "elastic-worker state refetches "
                                         "(+ re-placement onto the local "
                                         "mesh/layout) at an epoch or "
                                         "step barrier"),
    "cluster.rebucket_tasks_total": ("counter", "in-flight shard tasks "
                                                "requeued off a departed "
                                                "member at an epoch bump "
                                                "(ahead of the timeout "
                                                "re-dispatch)"),
    # worker labels below are BOUNDED by the fleet size (membership-leased
    # worker names), the same contract as the merged-registry worker tag
    "cluster.shard_seconds": ("histogram", "worker-reported shard gradient "
                                           "wall time per accepted "
                                           "ela_grad (the straggler "
                                           "score's raw feed), labels: "
                                           "worker (bounded: fleet size)",
                              ("worker",)),
    "cluster.health_straggler_score": ("gauge", "derived: worker median "
                                                "shard latency / the OTHER "
                                                "workers' median (leave-"
                                                "one-out) over the health "
                                                "window (>2 for 2+ "
                                                "evaluations = straggler), "
                                                "labels: worker (bounded)",
                                       ("worker",)),
    "cluster.health_goodput_ewma": ("gauge", "derived: exponentially-"
                                             "weighted goodput.ratio over "
                                             "the worker's windowed "
                                             "history, labels: worker "
                                             "(bounded)", ("worker",)),
    "cluster.health_heartbeat_jitter": ("gauge", "derived: stddev of the "
                                                 "worker's heartbeat "
                                                 "arrival intervals "
                                                 "(seconds) over the "
                                                 "health window, labels: "
                                                 "worker (bounded)",
                                        ("worker",)),
    "cluster.backlog_per_worker": ("gauge", "autoscale input at each "
                                            "mbr_view: (todo + pending "
                                            "tasks) / live members — the "
                                            "windowed series hysteresis "
                                            "reads"),
    "cluster.autoscale_signal": ("gauge", "the tentative autoscale action "
                                          "recorded per mbr_view "
                                          "(join=1, hold=0, leave=-1); a "
                                          "recommendation only commits "
                                          "when the signal held for the "
                                          "whole hysteresis window"),
    "cluster.autoscale_committed": ("gauge", "the last autoscale action "
                                             "the fleet actor COMMITTED "
                                             "(spawn=1, drain/evict=-1) — "
                                             "diverges from "
                                             "cluster.autoscale_signal "
                                             "exactly while hysteresis or "
                                             "cooldowns hold the fleet "
                                             "still"),
    "cluster.actor_actions_total": ("counter", "committed fleet-actor "
                                               "actions journaled via "
                                               "act_report, labels: "
                                               "population, action (both "
                                               "bounded)",
                                    ("population", "action")),
    "cluster.actor_failures_total": ("counter", "fleet-actor actions that "
                                                "failed: spawns that died "
                                                "or never joined within "
                                                "grace, drains escalated "
                                                "to kill, labels: action "
                                                "(bounded)", ("action",)),
    # -- alerts: obs/alerts.py (the fleet alert engine) ------------------
    "alerts.fired_total": ("counter", "alert rules transitioning to "
                                      "firing, labels: rule (bounded: "
                                      "the declared rule set)", ("rule",)),
    "alerts.resolved_total": ("counter", "alert rules transitioning back "
                                         "to resolved, labels: rule "
                                         "(bounded)", ("rule",)),
    "alerts.active": ("gauge", "alert series currently firing across "
                               "the whole rule set"),
    # -- coord: runtime/coord.py (CoordServer._dispatch) ----------------
    "coord.requests_total": ("counter", "coord RPCs dispatched, "
                                        "labels: type", ("type",)),
    "coord.request_errors_total": ("counter", "coord RPCs answered with "
                                              "an error (or raising), "
                                              "labels: type", ("type",)),
    # -- mesh: fluid/executor.py (GSPMD sharding plane) -----------------
    "mesh.axis_size": ("gauge", "devices along each mesh axis, "
                                "labels: axis", ("axis",)),
    "mesh.axis_utilization": ("gauge", "fraction of the persistable "
                                       "footprint actually sharded over "
                                       "each axis (1.0 = every parameter "
                                       "byte divides along it), labels: "
                                       "axis", ("axis",)),
    # -- obs: obs/aggregate.py (worker-side pusher) ---------------------
    "obs.pushes_total": ("counter", "registry snapshots pushed to the "
                                    "master (obs_push RPC)"),
    "obs.push_failures_total": ("counter", "obs_push RPCs that failed "
                                           "(master unreachable)"),
    # -- roofline: obs/roofline.py (the device cost ledger) --------------
    "roofline.mfu": ("gauge", "derived model-FLOPs utilization over the "
                              "most recent accounting window: "
                              "fluid.device_flops_total delta / elapsed / "
                              "chip dense peak (set only when the peak is "
                              "known — on TPU or under "
                              "PADDLE_TPU_PEAK_TFLOPS; updated on "
                              "dispatch, so an idle chip HOLDS its last "
                              "busy window's value — cross-check the "
                              "counter deltas for liveness)"),
    "roofline.hbm_bw_util": ("gauge", "derived HBM-bandwidth utilization "
                                      "over the most recent accounting "
                                      "window: fluid.device_bytes_total "
                                      "delta / elapsed / chip HBM peak "
                                      "(null + staleness semantics as "
                                      "roofline.mfu)"),
    "roofline.cost_analysis_failures_total": ("counter", "XLA cost/memory "
                                                         "analyses that "
                                                         "raised — derived "
                                                         "FLOPs/bytes for "
                                                         "those executables "
                                                         "are honest "
                                                         "unknowns, not "
                                                         "quiet nulls"),
    # -- rpc: runtime/master_service.py (_RpcClient, shared by coord) ---
    "rpc.calls_total": ("counter", "RPC calls issued, labels: rpc, op",
                        ("rpc", "op")),
    "rpc.call_seconds": ("histogram", "end-to-end call latency incl. "
                                      "retries, labels: rpc", ("rpc",)),
    "rpc.retries_total": ("counter", "retry attempts across clients"),
    "rpc.giveups_total": ("counter", "retry budgets exhausted"),
    "rpc.backoff_seconds_total": ("counter", "total backoff delay slept"),
    # -- serving: serving/engine.py, serving/paged.py -------------------
    # tenant labels are BOUNDED by contract: values are charset-validated
    # at submit (serving/batcher.py TENANT_RE) and the engine caps the
    # number of distinct tenants it mints series for (max_tenants,
    # default 32 — the L005 live-sample cardinality ceiling)
    "serving.requests_total": ("counter", "requests finished, labels: "
                                          "outcome (length | eos | "
                                          "cancelled | timeout | error — "
                                          "error = the engine failed and "
                                          "abandoned it), tenant "
                                          "(bounded; see above)",
                               ("outcome", "tenant")),
    "serving.rejected_total": ("counter", "submissions refused structured "
                                          "at admission, labels: reason "
                                          "(overloaded = queue cap; "
                                          "draining = shutdown gate)",
                               ("reason",)),
    "serving.queue_depth": ("gauge", "requests waiting for a slot (the "
                                     "admission queue)"),
    "serving.slots_live": ("gauge", "slots holding an in-flight request"),
    "serving.pages_used": ("gauge", "KV-cache pages currently allocated "
                                    "out of the pool"),
    "serving.pages_reserved": ("gauge", "pages reserved by admitted "
                                        "requests (worst-case; >= used)"),
    "serving.page_occupancy": ("gauge", "live tokens / allocated page "
                                        "capacity — 1.0 means HBM holds "
                                        "only live tokens (the paged-"
                                        "cache residency win). The "
                                        "prefix cache moves it BOTH "
                                        "ways: N readers over one "
                                        "shared page push it past 1.0, "
                                        "while retained COLD cache "
                                        "pages sit in the denominator "
                                        "and drag a lightly-loaded "
                                        "warm daemon toward 0 — low "
                                        "occupancy + high prefix_pages "
                                        "is healthy retention, not a "
                                        "leak"),
    "serving.prefix_hits_total": ("counter", "admissions that matched the "
                                             "prefix radix index and "
                                             "prefilled only their "
                                             "non-shared suffix, labels: "
                                             "tenant (bounded; see above)",
                                  ("tenant",)),
    "serving.prefix_misses_total": ("counter", "admissions that found no "
                                               "shared prefix and ran the "
                                               "full prefill, labels: "
                                               "tenant (bounded)",
                                    ("tenant",)),
    "serving.prefix_pages_shared": ("gauge", "prefix-index pages pinned "
                                             "by >= 1 live request (a "
                                             "page read by N requests "
                                             "counts once — the "
                                             "refcounted-sharing win)"),
    "serving.prefix_evictions_total": ("counter", "cold prefix-cache "
                                                  "entries evicted back "
                                                  "to the free list "
                                                  "(lowest decayed "
                                                  "measured-reuse score "
                                                  "first)"),
    "serving.ttft_seconds": ("histogram", "submit -> first token (queueing "
                                          "+ prefill) — the SLO pair's "
                                          "first half, labels: tenant "
                                          "(bounded)", ("tenant",)),
    "serving.tpot_seconds": ("histogram", "per-output-token time after "
                                          "the first (completion - first "
                                          "token) / (n - 1), labels: "
                                          "tenant (bounded)", ("tenant",)),
    # disaggregation: KV-page shipping (serving/ship.py wire contract)
    "serving.ship_pages_total": ("counter", "KV pages exported for "
                                            "shipping to a decode worker "
                                            "(prefill side, "
                                            "PagePool.export_slot)"),
    "serving.ship_bytes_total": ("counter", "payload bytes exported for "
                                            "shipping (pre-chunking, "
                                            "pre-base64)"),
    "serving.ship_chunks_total": ("counter", "wire chunks emitted on the "
                                             "ship send edge (post-"
                                             "chunking; what the ship "
                                             "phase's timeline duration "
                                             "is spent on)"),
    "serving.ship_chunk_bytes_total": ("counter", "raw chunk bytes on the "
                                                  "ship send edge (post "
                                                  "srv.ship fault filter, "
                                                  "pre-base64)"),
    # per-request timelines (obs/requests.py): the phase label is the
    # BOUNDED attributed-phase enum (queued/scheduled/prefill/ship/adopt/
    # decode), never a request key — L005-safe by construction
    "serving.phase_seconds": ("histogram", "per-request phase durations "
                                           "from the timeline ledger; the "
                                           "per-request phase sum "
                                           "reconciles with observed "
                                           "TTFT + decode wall (docs/"
                                           "design/observability.md "
                                           "'Request timelines'), labels: "
                                           "phase (bounded enum)",
                              ("phase",)),
    "serving.exemplars_total": ("counter", "slowest-K timeline exemplars "
                                           "captured by the aggregator's "
                                           "request store, labels: phase "
                                           "(the exemplar's dominant "
                                           "phase, bounded enum)",
                                ("phase",)),
    "serving.adopted_total": ("counter", "shipped slots adopted into this "
                                         "pool (decode side, "
                                         "PagePool.adopt_slot) — each is "
                                         "one cross-worker request "
                                         "landing"),
    "serving.adopt_refused_total": ("counter", "shipments refused instead "
                                               "of adopted, labels: reason "
                                               "(chunk = per-chunk CRC/"
                                               "base64 damage; data_loss "
                                               "= reassembled payload "
                                               "failed verification; "
                                               "no_chunks = adopt with no "
                                               "chunks held; geometry = "
                                               "pool page_block/kv_dtype "
                                               "mismatch; evicted = half-"
                                               "shipment evicted by the "
                                               "reassembly cap)",
                                    ("reason",)),
    # -- router: serving/router.py (`paddle_tpu route`) ------------------
    "router.requests_total": ("counter", "client submits the router "
                                         "resolved, labels: outcome (ok | "
                                         "overloaded = every decode pool "
                                         "refused | unavailable = no "
                                         "worker reachable | "
                                         "invalid_argument)",
                              ("outcome",)),
    "router.reroutes_total": ("counter", "in-flight requests re-placed "
                                         "on another worker, labels: "
                                         "reason (evicted = membership "
                                         "TTL eviction; left = graceful "
                                         "leave; unreachable = poll "
                                         "transport failure; not_found = "
                                         "worker restarted and forgot "
                                         "the stream; error = engine "
                                         "failed mid-stream; lost; "
                                         "prefill_fallback = every "
                                         "prefill worker down, decode-"
                                         "side prefill served instead)",
                              ("reason",)),
    "router.inflight": ("gauge", "router-tracked requests not yet done "
                                 "(buffers still growing or awaiting "
                                 "collection)"),
    "router.workers": ("gauge", "serving workers live in the router's "
                                "membership table, labels: role (decode "
                                "| prefill)", ("role",)),
    # -- tune: tune/driver.py (`paddle_tpu tune`) -----------------------
    "tune.measurements_total": ("counter", "candidate-plan timings taken "
                                           "by the autotune driver (one "
                                           "per timed dispatch), labels: "
                                           "space",
                                ("space",)),
    "tune.ledger_seeded_families_total": ("counter",
                                          "plan families swept because a "
                                          "profile ledger implicated "
                                          "their space (`paddle_tpu tune "
                                          "--from-ledger`)"),
    # -- trainer: trainer/trainer.py ------------------------------------
    "trainer.steps_total": ("counter", "train batches executed"),
    "trainer.examples_total": ("counter", "samples consumed (leading dim "
                                          "of the first batch array)"),
    "trainer.step_seconds": ("histogram", "batch step: device dispatch + "
                                          "host block on the result"),
    "trainer.sync_seconds": ("histogram", "host block on the step result "
                                          "(device time shows up here "
                                          "under async dispatch)"),
    "trainer.nonfinite_total": ("counter", "non-finite losses observed"),
    "trainer.skipped_total": ("counter", "batches dropped by "
                                         "on_nonfinite=skip"),
    "trainer.preemptions_total": ("counter", "preemption checkpoints "
                                             "taken (SIGTERM/SIGINT)"),
}

#: span names the built-in instrumentation emits (Chrome trace contract)
SPANS: Dict[str, str] = {
    "trainer.pass": "one pass of the train loop (args: pass_id)",
    "trainer.step": "one batch step (device dispatch + host sync)",
    "trainer.device_step": "the jitted step call (dispatch)",
    "trainer.host_sync": "host block on the loss value",
    "trainer.checkpoint": "pass/preemption/halt checkpoint save "
                          "(args: pass_id, reason)",
    "fluid.run": "Executor.run",
    "fluid.verify": "static pre-flight over the Program",
    "rpc.call": "one RPC incl. retries (args: rpc, op); its (trace_id, "
                "span_id) rides the request envelope as wire context",
    "master.dispatch": "server-side handling of one master RPC (args: op; "
                       "remote = the client's rpc.call span)",
    "coord.dispatch": "server-side handling of one coord RPC (args: op; "
                      "remote = the client's rpc.call span)",
    "serving.prefill": "one admission batch: ragged prefill + page "
                       "placement (args: batch)",
    "serving.segment": "one batched decode segment across live slots "
                       "(args: live)",
    "serving.ship": "client side of one KV shipment: every srv_ship chunk "
                    "RPC for one request (args: xid, bytes, key)",
    "srv_ship": "decode-side landing of one ship chunk (args: xid, seq; "
                "remote = the prefill worker's rpc.call span — the "
                "prefill->decode hop's flow arrow)",
    "srv_adopt": "decode-side adoption of a reassembled shipment into the "
                 "engine (args: xid, key; remote = the prefill worker's "
                 "rpc.call span)",
    "ckpt.publish": "atomic pass-dir publication (args: pass_id)",
    "ckpt.member": "one member write+fsync (args: member, bytes)",
    "ckpt.fsync": "file or directory fsync",
    "ckpt.rename": "tmp -> final rename swap",
}
