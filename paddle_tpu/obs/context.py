"""Wire trace context — the cross-process half of distributed tracing.

One training job is many processes (trainer workers, the master, the coord
server); a span tracer (:mod:`paddle_tpu.obs.trace`) only sees its own. The
context defined here is what crosses the wire: every RPC request envelope
carries a ``"trace"`` key

    {"id": "<hex trace id>", "span": <client span id>, "pid": <client pid>}

attached by :meth:`_RpcClient._call` from inside its live ``rpc.call`` span,
and the serving side (``MasterServer._dispatch`` / ``CoordServer``) opens
its handler span with that context recorded as ``remote``. When the
per-process dumps are merged (:func:`paddle_tpu.obs.export.merge_dumps`)
the ``remote`` field is the cross-process parent edge: the Chrome exporter
turns it into flow arrows from the client's ``rpc.call`` slice to the
server's dispatch slice, and tests assert the parenting directly.

The format is a **public contract** (docs/design/observability.md
"Distributed tracing"): the key names above and the sanitation limits in
:func:`sanitize` are what foreign emitters must produce.

Trace id: every process in one job should share it so a stitched timeline
is self-identifying. It is inherited from ``PADDLE_TPU_TRACE_ID`` when the
launcher exports one (``cluster_train`` and the test harness do), otherwise
minted per process — the ``remote`` edges still stitch either way, since
they key on (pid, span id).
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, Optional

#: env var a launcher sets so every process of one job shares a trace id
TRACE_ID_ENV = "PADDLE_TPU_TRACE_ID"

_MAX_ID_LEN = 64

_trace_id: Optional[str] = None


def trace_id() -> str:
    """This process's trace id: inherited from the launcher's env var, or
    minted once and cached. A forked child inherits the cached value —
    one job, one trace, which is what the stitched view wants (per-process
    identity lives in (pid, span id), not the trace id).
    """
    global _trace_id
    if _trace_id is None:
        _trace_id = os.environ.get(TRACE_ID_ENV) or uuid.uuid4().hex[:16]
    return _trace_id


def wire_context(span) -> Optional[Dict[str, Any]]:
    """The envelope dict for a request issued inside ``span``; None when
    the span is the shared NULL_SPAN (no session installed) — the wire
    format then stays byte-identical to the un-instrumented one."""
    sid = getattr(span, "id", None)
    if sid is None:
        return None
    return {"id": trace_id(), "span": int(sid), "pid": os.getpid()}


def sanitize(ctx) -> Optional[Dict[str, Any]]:
    """Validate a context received off the wire.

    Servers parse frames from arbitrary peers: a malformed or hostile
    ``trace`` value must degrade to "no context", never corrupt the trace
    or raise out of a handler. Returns a clean copy or None.
    """
    if not isinstance(ctx, dict):
        return None
    try:
        tid = str(ctx["id"])[:_MAX_ID_LEN]
        span = int(ctx["span"])
        pid = int(ctx["pid"])
    except (KeyError, TypeError, ValueError):
        return None
    if span < 0 or pid < 0:
        return None
    return {"id": tid, "span": span, "pid": pid}
