"""Exporters: Chrome trace_event JSON, Prometheus text, JSONL, summary table.

All four read ONE shape — the ``dump`` dict produced by
:meth:`ObsSession.dump` and round-tripped through the JSONL sink::

    {"meta":    {...},
     "metrics":  [MetricsRegistry.collect() samples],
     "events":   [Tracer events (spans + instants)],
     "requests": [request timelines (obs/requests.py), when a ledger ran]}

so the in-process path (``session.export_chrome()``) and the offline path
(``paddle_tpu obs export --input run.jsonl``) are the same code.

* :func:`chrome_trace` — ``{"traceEvents": [...]}`` for Perfetto /
  chrome://tracing: spans as complete (``ph:"X"``) events in µs, instants
  as ``ph:"i"``, counters as ``ph:"C"`` counter tracks, thread metadata.
* :func:`prometheus_text` — the text exposition format (``# TYPE`` lines,
  ``_bucket{le=...}``/``_sum``/``_count`` for histograms); names mangled
  ``subsystem.noun`` -> ``paddle_tpu_subsystem_noun``.
* :func:`write_jsonl` / :func:`read_jsonl` — the durable event stream.
* :func:`summary` — the human table; subsumes ``StatSet.report()`` by
  accepting stat snapshots alongside typed metrics.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

JSONL_VERSION = 1


# -- JSONL sink -----------------------------------------------------------------

def jsonl_lines(dump: Dict[str, Any]):
    """The dump as kind-tagged JSON lines (meta, then metrics, then
    events) — the single serialization both :func:`write_jsonl` and the
    CLI's stdout path emit."""
    meta = {"kind": "meta", "version": JSONL_VERSION}
    meta.update(dump.get("meta") or {})
    yield json.dumps(meta)
    for s in dump.get("metrics", ()):
        yield json.dumps({"kind": "metric", **s})
    for e in dump.get("events", ()):
        yield json.dumps(e)
    for tl in dump.get("requests", ()):
        yield json.dumps({"kind": "request", **tl})


def write_jsonl(path: str, dump: Dict[str, Any]) -> str:
    """Persist a session dump as line-delimited JSON: one ``meta`` line,
    one line per metric sample, one per trace event. Append-friendly and
    greppable — the chaos/CI artifact format."""
    with open(path, "w") as f:
        for line in jsonl_lines(dump):
            f.write(line + "\n")
    return path


def read_jsonl(path: str) -> Dict[str, Any]:
    """Inverse of :func:`write_jsonl`; tolerant of missing meta AND of
    torn/corrupt lines — a process killed mid-``save`` leaves a partial
    final line, and the dump of exactly that crashed run must still
    export whatever landed (malformed lines are skipped)."""
    meta: Dict[str, Any] = {}
    metrics: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    requests: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                 # torn tail / corrupt line
            if not isinstance(rec, dict):
                continue
            kind = rec.pop("kind", None)
            if kind == "meta":
                meta = rec
            elif kind == "metric":
                metrics.append(rec)
            elif kind in ("span", "instant"):
                events.append({"kind": kind, **rec})
            elif kind == "request":
                requests.append(rec)
    out = {"meta": meta, "metrics": metrics, "events": events}
    if requests:
        out["requests"] = requests
    return out


# -- multi-process merge --------------------------------------------------------

def merge_dumps(dumps: Iterable[Dict[str, Any]],
                workers: Optional[List[str]] = None) -> Dict[str, Any]:
    """Stitch per-process dumps into one cluster dump.

    * events concatenate unchanged — each already carries its pid, and
      cross-process edges ride the spans' ``remote`` fields;
    * metric samples get a ``worker=<id>`` label (the merged-registry
      label contract, docs/design/observability.md) so same-named series
      from different processes stay distinct series. A sample that already
      carries a ``worker`` label (e.g. the master re-exporting pushed
      snapshots) keeps it.
    * meta records the per-pid process names the Chrome exporter renders
      as ``process_name`` lanes.

    ``workers`` overrides the per-dump worker ids (default: the dump's
    ``meta.process``, falling back to ``proc<N>``).

    Known limitation: processes are keyed by OS pid (events and the wire
    context's ``remote`` edges both carry bare pids), so merging dumps
    from DIFFERENT HOSTS whose pids collide conflates those two lanes and
    can mis-resolve a remote edge. Single-host jobs (and any set of dumps
    with distinct pids) are unaffected; a cross-host deployment should
    launch workers with distinct pid namespaces or merge per host.
    """
    dumps = list(dumps)
    meta: Dict[str, Any] = {"merged": len(dumps), "processes": {}}
    metrics: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    requests: List[Dict[str, Any]] = []
    # per-process tracer clocks have private epochs; when EVERY dump maps
    # its epoch to the wall clock (meta.clock_origin_unix), shift events
    # onto one shared timeline so the stitched trace interleaves
    # correctly. If any dump lacks the field (pre-ISSUE-4 artifact), no
    # dump is shifted — mixing shifted and raw-epoch timestamps would
    # interleave incomparable timebases — and the meta says so.
    origins = [(d.get("meta") or {}).get("clock_origin_unix") for d in dumps]
    if any(o is None for o in origins):
        base = None
        if len(dumps) > 1:
            meta["clocks_unaligned"] = True
    else:
        base = min(origins)
    for i, d in enumerate(dumps):
        m = d.get("meta") or {}
        shift = (origins[i] - base
                 if base is not None and origins[i] is not None else 0.0)
        wid = (workers[i] if workers is not None and i < len(workers)
               else None) or m.get("process") or f"proc{i}"
        wid = str(wid)
        # a dump that is ITSELF a merge carries a processes map — keep
        # those identities so re-merging a persisted merge (export
        # --format=jsonl) doesn't collapse its lanes to "proc<N>"
        inner = m.get("processes") or {}
        for k, v in inner.items():
            meta["processes"].setdefault(str(k), str(v))
        if m.get("pid") is not None:
            meta["processes"].setdefault(str(m["pid"]), wid)
        if m.get("trace_id") and "trace_id" not in meta:
            meta["trace_id"] = m["trace_id"]
        for s in d.get("metrics", ()):
            s = dict(s)
            labels = dict(s.get("labels") or {})
            labels.setdefault("worker", wid)
            s["labels"] = labels
            metrics.append(s)
        for e in d.get("events", ()):
            if shift:
                e = dict(e, ts=e.get("ts", 0.0) + shift)
            events.append(e)
            p = e.get("pid")
            if p is not None and str(p) not in meta["processes"]:
                meta["processes"][str(p)] = wid
        for tl in d.get("requests", ()):
            if isinstance(tl, dict):
                # stamp the recording process so stitch() can name which
                # worker ran each leg; a timeline a router aggregated on a
                # worker's behalf keeps the id the router stamped
                if not tl.get("worker"):
                    tl = dict(tl, worker=wid)
                requests.append(tl)
    events.sort(key=lambda e: e.get("ts", 0.0))
    out = {"meta": meta, "metrics": metrics, "events": events}
    if requests:
        out["requests"] = requests
    return out


# -- Chrome trace_event ---------------------------------------------------------

def chrome_trace(dump: Dict[str, Any]) -> Dict[str, Any]:
    """Convert a dump to Chrome's trace_event JSON object format.

    Spans become ``ph:"X"`` complete events (ts/dur in µs); Perfetto nests
    same-tid events by containment, which matches the tracer's per-thread
    parent stacks. Counters ride as ``ph:"C"`` tracks stamped at the trace
    end so the final tally is visible on the timeline.

    Multi-process dumps (see :func:`merge_dumps`) get one ``process_name``
    metadata row per distinct pid (named from ``meta.processes`` /
    ``meta.process``) and a flow arrow (``ph:"s"``/``"f"``) for every span
    carrying a ``remote`` cross-process parent whose client span is also
    in the dump — the trainer→wire→master stitch in Perfetto.
    """
    events = dump.get("events", [])
    meta = dump.get("meta") or {}
    pid = None
    t_end = 0.0
    out: List[Dict[str, Any]] = []
    seen_pids: List[int] = []
    # (pid, span id) -> span event, for resolving remote parent edges
    by_id: Dict[Any, Dict[str, Any]] = {}
    flows: List[Dict[str, Any]] = []
    for e in events:
        pid = e.get("pid", pid)
        if e.get("pid") is not None and e["pid"] not in seen_pids:
            seen_pids.append(e["pid"])
        ts_us = e["ts"] * 1e6
        if e["kind"] == "span":
            dur_us = e.get("dur", 0.0) * 1e6
            t_end = max(t_end, ts_us + dur_us)
            args = dict(e.get("args") or {})
            if e.get("remote"):
                args["remote_parent"] = e["remote"]
                flows.append(e)
            if e.get("id") is not None:
                by_id[(e.get("pid", 0), e["id"])] = e
            out.append({"name": e["name"], "ph": "X", "ts": ts_us,
                        "dur": dur_us, "pid": e.get("pid", 0),
                        "tid": e.get("tid", 0),
                        "cat": e["name"].split(".", 1)[0],
                        "args": args})
        else:
            t_end = max(t_end, ts_us)
            out.append({"name": e["name"], "ph": "i", "ts": ts_us, "s": "t",
                        "pid": e.get("pid", 0), "tid": e.get("tid", 0),
                        "cat": e["name"].split(".", 1)[0],
                        "args": e.get("args") or {}})
    # flow arrows: client rpc.call slice -> server dispatch slice. Emitted
    # only when BOTH ends are present (a single-process dump has no arrow
    # to draw; the remote_parent arg above still names the edge).
    for e in flows:
        r = e["remote"]
        src = by_id.get((r.get("pid"), r.get("span")))
        if src is None:
            continue
        fid = f"{r.get('pid')}:{r.get('span')}:{e.get('pid', 0)}:{e['id']}"
        # bind the start step just inside the client slice so Chrome
        # attaches it to that slice, and the finish to the server slice.
        # Named serving hops (srv_ship, srv_adopt) keep their span name so
        # the prefill→decode handoff arrows read as what they are; generic
        # dispatch edges stay "rpc".
        fname = e["name"] if str(e["name"]).startswith("srv_") else "rpc"
        flow_common = {"name": fname, "cat": "rpc", "id": fid}
        flows_ts = src["ts"] * 1e6 + min(1.0, src.get("dur", 0.0) * 1e6 / 2)
        out.append({**flow_common, "ph": "s", "ts": flows_ts,
                    "pid": src.get("pid", 0), "tid": src.get("tid", 0)})
        out.append({**flow_common, "ph": "f", "bp": "e",
                    "ts": e["ts"] * 1e6 + min(1.0, e.get("dur", 0.0) * 1e6 / 2),
                    "pid": e.get("pid", 0), "tid": e.get("tid", 0)})
    pid = pid if pid is not None else meta.get("pid", 0)
    # merged dumps: land each worker's counter tracks in that worker's OWN
    # process lane (meta.processes maps pid -> worker name; invert it)
    worker_pid = {str(v): int(k)
                  for k, v in (meta.get("processes") or {}).items()
                  if str(k).isdigit()}
    for s in dump.get("metrics", ()):
        if s.get("type") != "counter":
            continue
        label = s["name"]
        if s.get("labels"):
            inner = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
            label += f"{{{inner}}}"
        c_pid = worker_pid.get(str((s.get("labels") or {}).get("worker")),
                               pid)
        out.append({"name": label, "ph": "C", "ts": t_end, "pid": c_pid,
                    "tid": 0, "args": {"value": s.get("value", 0)}})
    # one process_name lane per pid — the single-pid case keeps its row too
    names = {str(k): str(v)
             for k, v in (meta.get("processes") or {}).items()}
    if not seen_pids:
        seen_pids = [pid]
    for p in seen_pids:
        name = names.get(str(p)) or (
            meta.get("process") if len(seen_pids) == 1 else None) or \
            f"paddle_tpu pid {p}"
        out.append({"name": "process_name", "ph": "M", "pid": p, "tid": 0,
                    "args": {"name": name}})
        rank = _role_sort_index(name)
        if rank is not None:
            # serving-role lanes read top-to-bottom in request order:
            # router above the prefill tier above the decode tier
            out.append({"name": "process_sort_index", "ph": "M", "pid": p,
                        "tid": 0, "args": {"sort_index": rank}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": meta}


def _role_sort_index(process_name: str) -> Optional[int]:
    """Lane rank for serving-role process names (``router``,
    ``prefill:<id>``, ``decode:<id>``) — None for everything else so
    non-serving dumps keep Chrome's default (pid-ordered) layout."""
    role = str(process_name).split(":", 1)[0]
    return {"router": 0, "prefill": 1, "decode": 2}.get(role)


# -- Prometheus text format -----------------------------------------------------

def _prom_name(name: str) -> str:
    return "paddle_tpu_" + name.replace(".", "_")


def _prom_escape(value: Any) -> str:
    """Label-value escaping per the Prometheus exposition spec: backslash,
    double-quote and newline must be escaped or the line is unparseable
    (a label value holding a path with a quote silently corrupted the
    whole scrape before this)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: Dict[str, Any], extra: Optional[str] = None) -> str:
    parts = [f'{k}="{_prom_escape(v)}"'
             for k, v in sorted((labels or {}).items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(dump: Dict[str, Any]) -> str:
    """Text exposition format — what a ``/metrics`` endpoint (or a node
    textfile collector picking up the dump) serves."""
    lines: List[str] = []
    seen_type = set()
    for s in dump.get("metrics", ()):
        name = _prom_name(s["name"])
        if name not in seen_type:
            if s.get("help"):
                lines.append(f"# HELP {name} {s['help']}")
            lines.append(f"# TYPE {name} {s['type']}")
            seen_type.add(name)
        if s["type"] == "histogram":
            for le, cum in s.get("buckets", ()):
                le_s = "+Inf" if le == "+Inf" else repr(float(le))
                labels = _prom_labels(s.get("labels"), f'le="{le_s}"')
                lines.append(f"{name}_bucket{labels} {cum}")
            lines.append(f"{name}_sum{_prom_labels(s.get('labels'))} "
                         f"{s.get('sum', 0.0)}")
            lines.append(f"{name}_count{_prom_labels(s.get('labels'))} "
                         f"{s.get('count', 0)}")
        else:
            lines.append(f"{name}{_prom_labels(s.get('labels'))} "
                         f"{s.get('value', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- human summary --------------------------------------------------------------

def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _hist_quantile(snap: Dict[str, Any], q: float) -> float:
    """Upper-bound estimate of quantile ``q`` from cumulative buckets,
    clamped to the observed max — a 0.03ms sample in the le=0.5ms bucket
    must not report p50=0.5ms > max."""
    count = snap.get("count", 0)
    if not count:
        return 0.0
    mx = snap.get("max", 0.0)
    rank = q * count
    for le, cum in snap.get("buckets", ()):
        if cum >= rank:
            return mx if le == "+Inf" else min(float(le), mx)
    return mx


def summary(dump: Dict[str, Any],
            stats: Optional[Iterable] = None) -> str:
    """Render the dump as the operator-facing table. ``stats`` accepts
    :class:`paddle_tpu.utils.stats.StatSnapshot` values (or any object
    with name/total/avg/max/count) so one call subsumes the legacy
    ``StatSet.report()`` output."""
    counters, gauges, hists = [], [], []
    for s in dump.get("metrics", ()):
        {"counter": counters, "gauge": gauges,
         "histogram": hists}.get(s["type"], []).append(s)
    lines: List[str] = []
    if counters:
        lines.append("== counters ==")
        for s in counters:
            v = s.get("value", 0)
            v = int(v) if float(v).is_integer() else v
            lines.append(f"{s['name'] + _fmt_labels(s.get('labels')):<52} "
                         f"{v:>12}")
    if gauges:
        lines.append("== gauges ==")
        for s in gauges:
            lines.append(f"{s['name'] + _fmt_labels(s.get('labels')):<52} "
                         f"{s.get('value', 0):>12g}  "
                         f"(peak {s.get('high_water', 0):g})")
    if hists:
        lines.append("== histograms ==")
        lines.append(f"{'name':<44} {'count':>7} {'mean':>10} "
                     f"{'p50':>10} {'p99':>10} {'max':>10}")
        for s in hists:
            n = s.get("count", 0)
            mean = (s.get("sum", 0.0) / n) if n else 0.0
            lines.append(
                f"{s['name'] + _fmt_labels(s.get('labels')):<44} {n:>7} "
                f"{mean * 1e3:>9.3f}ms {_hist_quantile(s, 0.5) * 1e3:>9.3f}ms "
                f"{_hist_quantile(s, 0.99) * 1e3:>9.3f}ms "
                f"{s.get('max', 0.0) * 1e3:>9.3f}ms")
    if stats:
        snaps = sorted(stats, key=lambda i: -i.total)
        if snaps:
            lines.append("== timers (StatSet) ==")
            for i in snaps:
                lines.append(
                    f"{i.name:<44} total={i.total * 1e3:10.2f}ms "
                    f"avg={i.avg * 1e3:8.3f}ms max={i.max * 1e3:8.3f}ms "
                    f"count={i.count}")
    spans = [e for e in dump.get("events", ()) if e.get("kind") == "span"]
    if spans:
        agg: Dict[str, List[float]] = {}
        for e in spans:
            agg.setdefault(e["name"], []).append(e.get("dur", 0.0))
        lines.append("== spans ==")
        for name in sorted(agg):
            durs = agg[name]
            lines.append(f"{name:<44} count={len(durs):>6} "
                         f"total={sum(durs) * 1e3:10.2f}ms "
                         f"max={max(durs) * 1e3:8.3f}ms")
    return "\n".join(lines) if lines else "(no observability data)"
