"""Exporters: Chrome trace_event JSON, Prometheus text, JSONL, summary table.

All four read ONE shape — the ``dump`` dict produced by
:meth:`ObsSession.dump` and round-tripped through the JSONL sink::

    {"meta":    {...},
     "metrics": [MetricsRegistry.collect() samples],
     "events":  [Tracer events (spans + instants)]}

so the in-process path (``session.export_chrome()``) and the offline path
(``paddle_tpu obs export --input run.jsonl``) are the same code.

* :func:`chrome_trace` — ``{"traceEvents": [...]}`` for Perfetto /
  chrome://tracing: spans as complete (``ph:"X"``) events in µs, instants
  as ``ph:"i"``, counters as ``ph:"C"`` counter tracks, thread metadata.
* :func:`prometheus_text` — the text exposition format (``# TYPE`` lines,
  ``_bucket{le=...}``/``_sum``/``_count`` for histograms); names mangled
  ``subsystem.noun`` -> ``paddle_tpu_subsystem_noun``.
* :func:`write_jsonl` / :func:`read_jsonl` — the durable event stream.
* :func:`summary` — the human table; subsumes ``StatSet.report()`` by
  accepting stat snapshots alongside typed metrics.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional

JSONL_VERSION = 1


# -- JSONL sink -----------------------------------------------------------------

def jsonl_lines(dump: Dict[str, Any]):
    """The dump as kind-tagged JSON lines (meta, then metrics, then
    events) — the single serialization both :func:`write_jsonl` and the
    CLI's stdout path emit."""
    meta = {"kind": "meta", "version": JSONL_VERSION}
    meta.update(dump.get("meta") or {})
    yield json.dumps(meta)
    for s in dump.get("metrics", ()):
        yield json.dumps({"kind": "metric", **s})
    for e in dump.get("events", ()):
        yield json.dumps(e)


def write_jsonl(path: str, dump: Dict[str, Any]) -> str:
    """Persist a session dump as line-delimited JSON: one ``meta`` line,
    one line per metric sample, one per trace event. Append-friendly and
    greppable — the chaos/CI artifact format."""
    with open(path, "w") as f:
        for line in jsonl_lines(dump):
            f.write(line + "\n")
    return path


def read_jsonl(path: str) -> Dict[str, Any]:
    """Inverse of :func:`write_jsonl`; tolerant of missing meta AND of
    torn/corrupt lines — a process killed mid-``save`` leaves a partial
    final line, and the dump of exactly that crashed run must still
    export whatever landed (malformed lines are skipped)."""
    meta: Dict[str, Any] = {}
    metrics: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue                 # torn tail / corrupt line
            if not isinstance(rec, dict):
                continue
            kind = rec.pop("kind", None)
            if kind == "meta":
                meta = rec
            elif kind == "metric":
                metrics.append(rec)
            elif kind in ("span", "instant"):
                events.append({"kind": kind, **rec})
    return {"meta": meta, "metrics": metrics, "events": events}


# -- Chrome trace_event ---------------------------------------------------------

def chrome_trace(dump: Dict[str, Any]) -> Dict[str, Any]:
    """Convert a dump to Chrome's trace_event JSON object format.

    Spans become ``ph:"X"`` complete events (ts/dur in µs); Perfetto nests
    same-tid events by containment, which matches the tracer's per-thread
    parent stacks. Counters ride as ``ph:"C"`` tracks stamped at the trace
    end so the final tally is visible on the timeline.
    """
    events = dump.get("events", [])
    pid = None
    t_end = 0.0
    out: List[Dict[str, Any]] = []
    for e in events:
        pid = e.get("pid", pid)
        ts_us = e["ts"] * 1e6
        if e["kind"] == "span":
            dur_us = e.get("dur", 0.0) * 1e6
            t_end = max(t_end, ts_us + dur_us)
            out.append({"name": e["name"], "ph": "X", "ts": ts_us,
                        "dur": dur_us, "pid": e.get("pid", 0),
                        "tid": e.get("tid", 0),
                        "cat": e["name"].split(".", 1)[0],
                        "args": e.get("args") or {}})
        else:
            t_end = max(t_end, ts_us)
            out.append({"name": e["name"], "ph": "i", "ts": ts_us, "s": "t",
                        "pid": e.get("pid", 0), "tid": e.get("tid", 0),
                        "cat": e["name"].split(".", 1)[0],
                        "args": e.get("args") or {}})
    pid = pid if pid is not None else 0
    for s in dump.get("metrics", ()):
        if s.get("type") != "counter":
            continue
        label = s["name"]
        if s.get("labels"):
            inner = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
            label += f"{{{inner}}}"
        out.append({"name": label, "ph": "C", "ts": t_end, "pid": pid,
                    "tid": 0, "args": {"value": s.get("value", 0)}})
    out.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": "paddle_tpu"}})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": dump.get("meta") or {}}


# -- Prometheus text format -----------------------------------------------------

def _prom_name(name: str) -> str:
    return "paddle_tpu_" + name.replace(".", "_")


def _prom_labels(labels: Dict[str, Any], extra: Optional[str] = None) -> str:
    parts = [f'{k}="{v}"' for k, v in sorted((labels or {}).items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(dump: Dict[str, Any]) -> str:
    """Text exposition format — what a ``/metrics`` endpoint (or a node
    textfile collector picking up the dump) serves."""
    lines: List[str] = []
    seen_type = set()
    for s in dump.get("metrics", ()):
        name = _prom_name(s["name"])
        if name not in seen_type:
            if s.get("help"):
                lines.append(f"# HELP {name} {s['help']}")
            lines.append(f"# TYPE {name} {s['type']}")
            seen_type.add(name)
        if s["type"] == "histogram":
            for le, cum in s.get("buckets", ()):
                le_s = "+Inf" if le == "+Inf" else repr(float(le))
                labels = _prom_labels(s.get("labels"), f'le="{le_s}"')
                lines.append(f"{name}_bucket{labels} {cum}")
            lines.append(f"{name}_sum{_prom_labels(s.get('labels'))} "
                         f"{s.get('sum', 0.0)}")
            lines.append(f"{name}_count{_prom_labels(s.get('labels'))} "
                         f"{s.get('count', 0)}")
        else:
            lines.append(f"{name}{_prom_labels(s.get('labels'))} "
                         f"{s.get('value', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- human summary --------------------------------------------------------------

def _fmt_labels(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"


def _hist_quantile(snap: Dict[str, Any], q: float) -> float:
    """Upper-bound estimate of quantile ``q`` from cumulative buckets,
    clamped to the observed max — a 0.03ms sample in the le=0.5ms bucket
    must not report p50=0.5ms > max."""
    count = snap.get("count", 0)
    if not count:
        return 0.0
    mx = snap.get("max", 0.0)
    rank = q * count
    for le, cum in snap.get("buckets", ()):
        if cum >= rank:
            return mx if le == "+Inf" else min(float(le), mx)
    return mx


def summary(dump: Dict[str, Any],
            stats: Optional[Iterable] = None) -> str:
    """Render the dump as the operator-facing table. ``stats`` accepts
    :class:`paddle_tpu.utils.stats.StatSnapshot` values (or any object
    with name/total/avg/max/count) so one call subsumes the legacy
    ``StatSet.report()`` output."""
    counters, gauges, hists = [], [], []
    for s in dump.get("metrics", ()):
        {"counter": counters, "gauge": gauges,
         "histogram": hists}.get(s["type"], []).append(s)
    lines: List[str] = []
    if counters:
        lines.append("== counters ==")
        for s in counters:
            v = s.get("value", 0)
            v = int(v) if float(v).is_integer() else v
            lines.append(f"{s['name'] + _fmt_labels(s.get('labels')):<52} "
                         f"{v:>12}")
    if gauges:
        lines.append("== gauges ==")
        for s in gauges:
            lines.append(f"{s['name'] + _fmt_labels(s.get('labels')):<52} "
                         f"{s.get('value', 0):>12g}  "
                         f"(peak {s.get('high_water', 0):g})")
    if hists:
        lines.append("== histograms ==")
        lines.append(f"{'name':<44} {'count':>7} {'mean':>10} "
                     f"{'p50':>10} {'p99':>10} {'max':>10}")
        for s in hists:
            n = s.get("count", 0)
            mean = (s.get("sum", 0.0) / n) if n else 0.0
            lines.append(
                f"{s['name'] + _fmt_labels(s.get('labels')):<44} {n:>7} "
                f"{mean * 1e3:>9.3f}ms {_hist_quantile(s, 0.5) * 1e3:>9.3f}ms "
                f"{_hist_quantile(s, 0.99) * 1e3:>9.3f}ms "
                f"{s.get('max', 0.0) * 1e3:>9.3f}ms")
    if stats:
        snaps = sorted(stats, key=lambda i: -i.total)
        if snaps:
            lines.append("== timers (StatSet) ==")
            for i in snaps:
                lines.append(
                    f"{i.name:<44} total={i.total * 1e3:10.2f}ms "
                    f"avg={i.avg * 1e3:8.3f}ms max={i.max * 1e3:8.3f}ms "
                    f"count={i.count}")
    spans = [e for e in dump.get("events", ()) if e.get("kind") == "span"]
    if spans:
        agg: Dict[str, List[float]] = {}
        for e in spans:
            agg.setdefault(e["name"], []).append(e.get("dur", 0.0))
        lines.append("== spans ==")
        for name in sorted(agg):
            durs = agg[name]
            lines.append(f"{name:<44} count={len(durs):>6} "
                         f"total={sum(durs) * 1e3:10.2f}ms "
                         f"max={max(durs) * 1e3:8.3f}ms")
    return "\n".join(lines) if lines else "(no observability data)"
