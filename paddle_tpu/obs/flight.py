"""Crash flight recorder: the last N spans + metric deltas, dumped on death.

The faults plane (PR 2) makes processes die on purpose; PR 3's ObsSession
kept its whole buffer in memory — so the runs whose telemetry matters most
(the crashed ones) were exactly the runs that lost it. The flight recorder
closes that hole the way an aircraft FDR does: a bounded ring of the most
*recent* events (the Tracer's ``ring`` — the main event list keeps a run's
beginning when it overflows; the ring keeps its end) plus counter deltas
since arming, written to disk at the moment of death:

* **SIGTERM** — the preemption signal; the previous handler is chained, so
  the trainer's checkpoint-then-exit still runs.
* **uncaught exception** — ``sys.excepthook`` chain (fatal hook).
* **interpreter exit** — ``atexit``, covering ``os._exit``-free paths and
  any death mode that unwinds normally.
* **faults-plane injected raise** — :func:`paddle_tpu.faults.fire` calls
  :func:`paddle_tpu.obs.flight_dump` just before raising, so the dump
  exists even if a retry layer later swallows the exception and the
  process is then SIGKILLed (which no hook can catch).

``kill -9`` during the dump itself can still lose it — the write is one
buffered pass over a small ring — but every *anticipated* death mode
leaves a self-describing artifact that ``paddle_tpu obs export`` reads
like any session dump.

Cost: one ``deque.append`` per trace event while armed (≪ 1µs; measured
≤ ~5µs/batch in tests/test_obs.py) and nothing at all on the metrics hot
path — deltas are computed at dump time from the registry.

Dump schema (public contract, docs/design/observability.md): a normal
JSONL dump whose meta carries ``{"flight": true, "reason": <why>,
"ring_size": N}`` and whose counter samples carry an extra ``"delta"``
field (value minus the arm-time baseline).
"""

from __future__ import annotations

import atexit
import os
import signal
import sys
import threading
from typing import Any, Dict, List, Optional

#: default ring length — ~100 batches of trainer spans; small enough that a
#: dump is one disk block burst, large enough to show what led to the crash
DEFAULT_RING = 2048


def _sample_key(s: Dict[str, Any]):
    return (s["name"], tuple(sorted((s.get("labels") or {}).items())))


class FlightRecorder:
    """Always-on tail capture for one :class:`ObsSession`.

    Usage::

        session = obs.ObsSession().install()
        rec = obs.FlightRecorder(session, "run.jsonl").arm()
        try:
            ...                      # crash anywhere -> run.jsonl exists
        finally:
            rec.disarm()             # clean exit: the caller's full
            session.save("run.jsonl")  # session.save owns the path now
    """

    def __init__(self, session, path: str, ring_size: int = DEFAULT_RING):
        self.session = session
        self.path = path
        self.ring_size = ring_size
        self._lock = threading.Lock()
        self._armed = False
        self._final = False          # a death-path dump already written
        self._baseline: Dict[Any, float] = {}
        self._prev_sigterm = None
        self._prev_excepthook = None

    # -- lifecycle ----------------------------------------------------------
    def arm(self) -> "FlightRecorder":
        """Enable the ring, snapshot the counter baseline, register the
        death hooks. Idempotent."""
        with self._lock:
            if self._armed:
                return self
            self._armed = True
        self.session.tracer.enable_ring(self.ring_size)
        self._baseline = {
            _sample_key(s): float(s.get("value", 0.0))
            for s in self.session.registry.collect()
            if s.get("type") == "counter"}
        from . import _set_flight
        _set_flight(self)
        atexit.register(self._atexit_dump)
        self._prev_excepthook = sys.excepthook
        sys.excepthook = self._excepthook
        try:
            # main thread only; elsewhere the atexit/excepthook pair still
            # covers every catchable death mode
            self._prev_sigterm = signal.signal(signal.SIGTERM, self._sigterm)
        except ValueError:
            self._prev_sigterm = None
        return self

    def disarm(self) -> None:
        """Unregister the hooks — the clean-exit path, called before the
        owner writes its full session dump to the same file."""
        with self._lock:
            if not self._armed:
                return
            self._armed = False
        # release the ring too: "zero cost when not armed" includes the
        # per-event deque append and the up-to-ring_size pinned event dicts
        self.session.tracer.enable_ring(0)
        from . import _set_flight
        _set_flight(None)
        atexit.unregister(self._atexit_dump)
        # == not `is`: each `self._hook` access builds a fresh bound method,
        # so identity would never match; equality compares __self__/__func__
        if sys.excepthook == self._excepthook:
            sys.excepthook = self._prev_excepthook or sys.__excepthook__
        try:
            if signal.getsignal(signal.SIGTERM) == self._sigterm:
                signal.signal(signal.SIGTERM,
                              self._prev_sigterm or signal.SIG_DFL)
        except ValueError:
            pass

    # -- capture ------------------------------------------------------------
    def snapshot(self, reason: str) -> Dict[str, Any]:
        """The flight dump: meta + full metric samples (counters annotated
        with their delta since arming) + the ring tail."""
        metrics: List[Dict[str, Any]] = []
        for s in self.session.registry.collect():
            if s.get("type") == "counter":
                s = dict(s)
                base = self._baseline.get(_sample_key(s), 0.0)
                s["delta"] = float(s.get("value", 0.0)) - base
            metrics.append(s)
        # the session's own meta block (shared shape) + the flight fields
        meta = dict(self.session.meta(), flight=True, reason=reason,
                    ring_size=self.ring_size)
        return {"meta": meta, "metrics": metrics,
                "events": self.session.tracer.ring_snapshot()}

    def dump(self, reason: str, final: bool = False) -> Optional[str]:
        """Write the flight dump to ``self.path`` (overwriting an earlier,
        staler one). ``final`` marks a death-path dump so the atexit hook
        does not clobber it with a later, emptier snapshot. Never raises —
        a failing dump must not mask the crash being recorded."""
        if final:
            self._final = True
        try:
            from .export import write_jsonl
            return write_jsonl(self.path, self.snapshot(reason))
        except Exception:
            return None

    # -- death hooks --------------------------------------------------------
    def _atexit_dump(self) -> None:
        if self._armed and not self._final:
            self.dump("atexit", final=True)

    def _excepthook(self, exc_type, exc, tb) -> None:
        if self._armed:
            self.dump(f"exception:{exc_type.__name__}", final=True)
        hook = self._prev_excepthook or sys.__excepthook__
        hook(exc_type, exc, tb)

    def _sigterm(self, signum, frame) -> None:
        if self._armed:
            self.dump("sigterm", final=True)
        prev = self._prev_sigterm
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore + re-raise so the exit status stays "killed by
            # SIGTERM", not a bespoke exit code
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
