"""The goodput ledger — where did the wall-clock actually go?

A training pass (or a serving scheduler loop) spends its wall time in
five places, and only one of them is the chip doing useful work:

* ``compile``   — XLA backend compiles (via the ``jax.monitoring``
  bridge, obs/jaxhooks.py; stolen out of whatever bucket the compile
  fired inside so nothing double-counts);
* ``host_input`` — waiting on the reader/feeder for the next batch, or
  assembling an admission group;
* ``device``    — dispatching device work and blocking on its result
  (under async dispatch the execution time surfaces wherever the host
  first blocks — the driver loops put that block in this bucket);
* ``host_sync`` — host-side bookkeeping on results (token collection,
  loss reads, evaluator updates);
* ``idle``      — everything else inside the open window (event
  handlers, logging, scheduler waits), computed at close as
  ``wall - sum(buckets)``.

Exported as ``goodput.<bucket>_seconds_total`` counters (labelled
``component=trainer|v2_sgd|serving``) plus the ``goodput.ratio`` gauge —
``device / wall`` over the window, the number the Ascend field study
calls goodput. One ledger is open per driver loop; concurrent loops
(a trainer and a serving engine under one session) sum into the same
counters under their own component label.

Everything is injectable for tests: ``GoodputLedger(registry=...,
clock=fake)`` runs the whole bucket accounting with no real sleeps.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

BUCKETS = ("compile", "host_input", "device", "host_sync", "idle")

#: minimum seconds between live ratio-gauge refreshes
_RATIO_WINDOW_S = 0.25

# per-thread stack of open ledgers: the jax.monitoring bridge forwards a
# compile duration to the ledger(s) open on the COMPILING thread, which
# is the thread whose bucket the compile time is hiding inside
_tls = threading.local()


def _open_stack() -> List["GoodputLedger"]:
    st = getattr(_tls, "ledgers", None)
    if st is None:
        st = _tls.ledgers = []
    return st


class GoodputLedger:
    """One open accounting window over a driver loop's wall time."""

    def __init__(self, registry, component: str = "run",
                 clock=time.monotonic):
        self.registry = registry
        self.component = component
        self.clock = clock
        self.totals: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._t_open: Optional[float] = None
        self._t_ratio = 0.0
        self._lock = threading.Lock()
        # innermost open bucket per thread: (name, stolen_seconds) —
        # compile notes steal from it so the bucket reports its OWN time
        self._bucket_tls = threading.local()

    # -- lifecycle -----------------------------------------------------
    def open(self) -> "GoodputLedger":
        self._t_open = self.clock()
        _open_stack().append(self)
        return self

    def close(self) -> None:
        """Close the window: everything not accounted becomes ``idle``,
        and the ratio gauge gets its final value."""
        st = _open_stack()
        if self in st:
            st.remove(self)
        if self._t_open is None:
            return
        wall = max(self.clock() - self._t_open, 0.0)
        with self._lock:
            accounted = sum(self.totals.values()) - self.totals["idle"]
            idle = max(wall - accounted, 0.0)
            self.totals["idle"] += idle
        if idle:
            self._counter("idle").inc(idle)
        self._set_ratio(wall)
        self._t_open = None

    @contextmanager
    def window(self):
        self.open()
        try:
            yield self
        finally:
            self.close()

    # -- recording -----------------------------------------------------
    def _counter(self, bucket: str):
        return self.registry.counter(
            f"goodput.{bucket}_seconds_total").labels(
                component=self.component)

    def add(self, bucket: str, seconds: float) -> None:
        if bucket not in BUCKETS:
            raise ValueError(f"unknown goodput bucket {bucket!r} "
                             f"(one of {BUCKETS})")
        seconds = max(float(seconds), 0.0)
        with self._lock:
            self.totals[bucket] += seconds
        self._counter(bucket).inc(seconds)
        if self._t_open is not None:
            now = self.clock()
            if now - self._t_ratio >= _RATIO_WINDOW_S:
                self._t_ratio = now
                self._set_ratio(max(now - self._t_open, 0.0))

    @contextmanager
    def bucket(self, name: str):
        """Time a region into ``name``; compile seconds noted while it is
        open are STOLEN from it (they land in ``compile`` instead)."""
        tls = self._bucket_tls
        prev = getattr(tls, "top", None)
        tls.top = frame = [name, 0.0]
        t0 = self.clock()
        try:
            yield
        finally:
            t1 = self.clock()
            dur = t1 - t0 - frame[1]
            tls.top = prev
            if prev is not None:
                # a nested bucket's whole span (incl. its stolen compile
                # time) is not the OUTER bucket's own time either
                prev[1] += t1 - t0
            self.add(name, dur)

    def note_compile(self, seconds: float) -> None:
        """A backend compile ran inside this window (jaxhooks bridge):
        account it to ``compile`` and steal it from the innermost open
        bucket on this thread so the wall second is counted once."""
        seconds = max(float(seconds), 0.0)
        frame = getattr(self._bucket_tls, "top", None)
        if frame is not None:
            frame[1] += seconds
        self.add("compile", seconds)

    # -- derivation ----------------------------------------------------
    def _set_ratio(self, wall: float) -> None:
        if wall <= 0:
            return
        with self._lock:
            device = self.totals["device"]
        self.registry.gauge("goodput.ratio").set(
            min(device / wall, 1.0), component=self.component)

    def ratio(self) -> Optional[float]:
        """device / wall over the window so far (None before open)."""
        if self._t_open is None:
            return None
        wall = self.clock() - self._t_open
        if wall <= 0:
            return None
        with self._lock:
            return min(self.totals["device"] / wall, 1.0)


# -- module surface (what instrumented drivers call) ---------------------------

def open_ledger(component: str, clock=time.monotonic
                ) -> Optional[GoodputLedger]:
    """Open a goodput window on the installed session's registry; None
    (and zero cost) when no session is installed."""
    from . import session
    s = session()
    if s is None:
        return None
    return GoodputLedger(s.registry, component=component,
                         clock=clock).open()


def note_compile(seconds: float) -> None:
    """Forward one backend-compile duration to the ledger(s) open on the
    current thread — called by the jax.monitoring bridge
    (obs/jaxhooks.py). Cheap no-op when none is open."""
    st = getattr(_tls, "ledgers", None)
    if not st:
        return
    for ledger in st:
        ledger.note_compile(seconds)


@contextmanager
def maybe_bucket(ledger: Optional[GoodputLedger], name: str):
    """``ledger.bucket(name)`` when a ledger is open, else a no-op — the
    one-liner instrumented loops use so the plane stays zero-cost off."""
    if ledger is None:
        yield
    else:
        with ledger.bucket(name):
            yield
