"""Fleet health plane: windowed cluster time-series + per-worker health.

The master-side :class:`~paddle_tpu.obs.aggregate.ClusterAggregator`
(PR 4) keeps only the *latest* snapshot per worker — enough for a
point-in-time ``/metrics`` scrape, useless for trends: the elastic
autoscale hook reasoned from one instantaneous sample, no operator could
see a straggler forming, and no SLO burn rate existed to alert on. The
Ascend field study (PAPERS.md) is blunt that accelerator fleets die
without *continuous* utilization telemetry and per-worker health
attribution. This module is that plane's storage + derivation half
(:mod:`paddle_tpu.obs.alerts` is the rules half):

* :class:`TimeSeriesStore` — a bounded ring of timestamped samples per
  ``worker|metric|labels`` series. Memory is bounded twice (``max_points``
  per ring, ``max_series`` total); the clock is injectable so every test
  time-travels instead of sleeping. :func:`rate` is the ONE shared
  counter-delta → per-second derivation (restart-tolerant); :func:`ewma`
  the shared exponentially-weighted mean/variance.
* :class:`FleetHealth` — per-worker derived signals: goodput-ratio EWMA +
  variance and step-time EWMA off the windowed store, a **straggler
  score** (this worker's recent median shard latency over the OTHER
  workers' median — fed from the elastic ``ela_grad`` timings), heartbeat-interval
  jitter (fed from accepted membership heartbeats), and a goodput-collapse
  flag. The snapshot lands in ``cluster.health_*`` gauges (worker-labeled,
  bounded by the fleet size) AND back into the store, so alert rules can
  threshold on derived health like any other series.
* :func:`health_table` — the per-worker operator table ``paddle_tpu obs
  top`` and ``obs serve /summary`` render.

Zero-cost contract: everything here runs on the MASTER, driven by pushes
that only happen when a worker installed an ObsSession + ObsPusher. The
worker-side hooks this plane feeds from (shard timing in
``trainer/elastic.py``, ``faults.fire`` chaos sites) are a clock read and
an is-None branch when the planes are off.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

SeriesKey = Tuple[str, str, Tuple[Tuple[str, str], ...]]


def _series_key(worker: str, name: str, labels: Optional[Dict]) -> SeriesKey:
    return (str(worker), str(name),
            tuple(sorted((str(k), str(v))
                         for k, v in (labels or {}).items())))


def _point_payload(sample: Dict[str, Any]):
    """What one ring point stores per sample kind: a float for
    counters/gauges, the (count, sum, cumulative buckets) triple for
    histograms — the minimum burn-rate math needs."""
    t = sample.get("type")
    if t == "histogram":
        return {"count": int(sample.get("count", 0)),
                "sum": float(sample.get("sum", 0.0)),
                "buckets": [[le, int(c)]
                            for le, c in (sample.get("buckets") or ())]}
    v = sample.get("value")
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


class TimeSeriesStore:
    """Bounded windowed sample store keyed ``worker|metric|labels``.

    Args:
      window_s: read horizon — :meth:`points` drops older samples (the
        rings may briefly hold older points; reads never return them).
      max_points: ring length per series (the hard per-series bound).
      max_series: total distinct series admitted; past the cap NEW series
        are dropped (and counted in :attr:`dropped_series`) rather than
        growing without bound — a worker minting runaway label values
        must not melt the master.
      clock: injectable monotonic clock (tests time-travel).
    """

    def __init__(self, window_s: float = 300.0, max_points: int = 240,
                 max_series: int = 4096,
                 clock: Optional[Callable[[], float]] = None):
        self.window_s = float(window_s)
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._series: Dict[SeriesKey, Deque[Tuple[float, Any]]] = {}
        self.dropped_series = 0

    # -- writing ------------------------------------------------------------
    def record(self, worker: str, samples, ts: Optional[float] = None) -> int:
        """Append one timestamped point per sample (aggregator-cleaned
        shape); returns the number of points stored."""
        ts = self._clock() if ts is None else float(ts)
        stored = 0
        with self._lock:
            for s in samples or ():
                if not isinstance(s, dict) or not s.get("name"):
                    continue
                payload = _point_payload(s)
                if payload is None:
                    continue
                key = _series_key(worker, s["name"], s.get("labels"))
                ring = self._series.get(key)
                if ring is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    ring = self._series[key] = collections.deque(
                        maxlen=self.max_points)
                ring.append((ts, payload))
                stored += 1
        return stored

    def record_value(self, worker: str, name: str, value: float,
                     labels: Optional[Dict] = None,
                     ts: Optional[float] = None) -> None:
        """Single-value convenience (derived gauges, master-side series)."""
        self.record(worker, [{"name": name, "type": "gauge",
                              "value": value, "labels": labels or {}}], ts)

    def drop_worker(self, worker: str) -> int:
        """Drop every series of ONE worker (the membership leave/evict
        reap — without it a health-fed-only worker's derived series, and
        any alert frozen on them, would outlive the worker forever);
        returns the number of series removed."""
        worker = str(worker)
        with self._lock:
            dead = [k for k in self._series if k[0] == worker]
            for k in dead:
                del self._series[k]
        return len(dead)

    def prune(self, live_workers) -> int:
        """Drop every series belonging to a worker not in ``live_workers``
        (the aggregator's TTL ageing applied to history); returns the
        number of series removed."""
        live = {str(w) for w in live_workers}
        # "_master" series (autoscale signal, backlog) are the master's
        # own and never age out with worker churn
        live.add(MASTER_WORKER)
        with self._lock:
            dead = [k for k in self._series if k[0] not in live]
            for k in dead:
                del self._series[k]
        return len(dead)

    # -- reading ------------------------------------------------------------
    def points(self, worker: str, name: str,
               labels: Optional[Dict] = None,
               window_s: Optional[float] = None,
               now: Optional[float] = None) -> List[Tuple[float, Any]]:
        """The series' points inside the window, oldest first."""
        now = self._clock() if now is None else float(now)
        horizon = now - (self.window_s if window_s is None
                         else float(window_s))
        key = _series_key(worker, name, labels)
        with self._lock:
            ring = self._series.get(key)
            pts = list(ring) if ring is not None else []
        return [(t, v) for t, v in pts if t >= horizon]

    def series_for(self, name: str) -> List[Tuple[str, Dict[str, str],
                                                  List[Tuple[float, Any]]]]:
        """Every stored series of ``name``: (worker, labels, points)."""
        with self._lock:
            items = [(k, list(ring)) for k, ring in self._series.items()
                     if k[1] == name]
        return [(k[0], dict(k[2]), pts) for k, pts in sorted(items)]

    def workers(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._series}
                          - {MASTER_WORKER})

    def n_series(self) -> int:
        with self._lock:
            return len(self._series)

    def n_points(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._series.values())


#: the store's reserved worker id for master-side series (autoscale
#: signal, backlog) — never a real fleet member name (worker ids come
#: from worker processes; the underscore prefix keeps the namespace)
MASTER_WORKER = "_master"


# -- shared derivations ---------------------------------------------------------

def rate(points: List[Tuple[float, Any]], *, now: Optional[float] = None,
         min_span_s: float = 1e-9) -> Optional[float]:
    """Counter-delta → per-second rate over a series' windowed points —
    the shared derivation for anything consuming counter series out of
    the store (external scalers reading the history; a future rate-
    threshold rule kind; the built-in detectors read gauges/histograms
    directly). Restart-tolerant: a negative delta (worker restarted,
    counter reset) re-bases at the newest value instead of reporting a
    negative rate. None with < 2 points (no window)."""
    vals = [(t, v) for t, v in points if isinstance(v, (int, float))]
    if len(vals) < 2:
        return None
    (t0, v0), (t1, v1) = vals[0], vals[-1]
    span = t1 - t0
    if span < min_span_s:
        return None
    delta = v1 - v0
    if delta < 0:             # counter reset mid-window: count since reset
        delta = v1
    return delta / span


def ewma(values, alpha: float = 0.3) -> Tuple[Optional[float],
                                              Optional[float]]:
    """Exponentially-weighted mean AND variance over ``values`` (oldest
    first) — the smoothing the health snapshot applies to goodput ratio
    and step time. Returns (None, None) when empty."""
    mean = var = None
    for v in values:
        v = float(v)
        if mean is None:
            mean, var = v, 0.0
        else:
            d = v - mean
            mean += alpha * d
            var = (1.0 - alpha) * (var + alpha * d * d)
    return mean, var


def _median(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _hist_mean_delta(points: List[Tuple[float, Any]]) -> Optional[float]:
    """Windowed mean of a histogram series: (Δsum / Δcount) between the
    window's first and last snapshots. None without new observations."""
    snaps = [(t, v) for t, v in points if isinstance(v, dict)]
    if len(snaps) < 2:
        return None
    a, b = snaps[0][1], snaps[-1][1]
    dc = b.get("count", 0) - a.get("count", 0)
    if dc <= 0:
        return None
    return (b.get("sum", 0.0) - a.get("sum", 0.0)) / dc


class FleetHealth:
    """Derived per-worker health over the windowed store.

    The master feeds the two signals the store cannot see from pushed
    snapshots alone:

    * :meth:`note_shard` — per accepted ``ela_grad``, the worker-reported
      shard gradient wall time (``trainer/elastic.py``); the straggler
      score derives from these.
    * :meth:`note_heartbeat` — per accepted membership heartbeat
      (``runtime/membership.py``); heartbeat-interval jitter derives from
      the arrival times.

    :meth:`snapshot` folds both with the store's ``goodput.ratio`` /
    ``trainer.step_seconds`` series into one per-worker dict. Detection
    thresholds live HERE (one owner); the alert rules threshold on the
    emitted ``cluster.health_*`` gauges, so rule values and these
    constants agree by construction (alerts.default_rules reads them).
    """

    #: straggler: worker median shard latency > this multiple of the
    #: OTHER workers' median (leave-one-out; needs >= 2 reporting workers)
    STRAGGLER_RATIO = 2.0
    #: heartbeat jitter: interval stddev beyond this fraction of the
    #: median interval marks arrival timing as unstable
    JITTER_RATIO = 0.5
    #: goodput collapse: EWMA below this fraction of the worker's own
    #: windowed peak (and the peak itself was a real signal)
    COLLAPSE_RATIO = 0.33

    def __init__(self, *, clock: Optional[Callable[[], float]] = None,
                 shard_window: int = 32, heartbeat_window: int = 16):
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._shards: Dict[str, Deque[Tuple[float, float]]] = {}
        self._beats: Dict[str, Deque[float]] = {}
        self.shard_window = int(shard_window)
        self.heartbeat_window = int(heartbeat_window)

    # -- feeds (master-side call sites) -------------------------------------
    def note_shard(self, worker: str, seconds: float,
                   now: Optional[float] = None) -> None:
        now = self._clock() if now is None else float(now)
        with self._lock:
            dq = self._shards.get(worker)
            if dq is None:
                dq = self._shards[worker] = collections.deque(
                    maxlen=self.shard_window)
            dq.append((now, float(seconds)))

    def note_heartbeat(self, worker: str,
                       now: Optional[float] = None) -> None:
        now = self._clock() if now is None else float(now)
        with self._lock:
            dq = self._beats.get(worker)
            if dq is None:
                dq = self._beats[worker] = collections.deque(
                    maxlen=self.heartbeat_window)
            dq.append(now)

    def forget(self, worker: str) -> None:
        """Drop a departed worker's feeds (the membership leave/evict
        hook) so a re-join starts clean."""
        with self._lock:
            self._shards.pop(worker, None)
            self._beats.pop(worker, None)

    def known_workers(self):
        """Workers any feed has seen (and not yet forgotten) — the
        aggregator's prune keeps their history alive even when they never
        obs_push (elastic CLI workers feed shard timings/heartbeats only;
        membership leave/evict forget()s them, closing the loop)."""
        with self._lock:
            return set(self._shards) | set(self._beats)

    # -- derivation ---------------------------------------------------------
    def _shard_median(self, worker: str, horizon: float) -> Optional[float]:
        with self._lock:   # note_shard appends concurrently (RPC threads)
            dq = self._shards.get(worker)
            if not dq:
                return None
            vals = [s for t, s in dq if t >= horizon]
        return _median(vals)

    def snapshot(self, store: Optional[TimeSeriesStore] = None,
                 now: Optional[float] = None) -> Dict[str, Dict[str, Any]]:
        """Per-worker health: ``{worker: {goodput_ewma, goodput_var,
        step_ewma, straggler_score, heartbeat_jitter, flags...}}``.

        The worker set is the union of everything any feed has seen —
        a worker that stopped pushing still shows up (with its stale
        signals), which is exactly when an operator needs the row.
        """
        now = self._clock() if now is None else float(now)
        window = store.window_s if store is not None else 300.0
        horizon = now - window
        with self._lock:
            workers = set(self._shards) | set(self._beats)
        # ONE store scan per metric family (not per worker — series_for
        # walks every stored series under the store lock)
        goodput_by_w: Dict[str, List[Tuple[float, float]]] = {}
        steps_by_w: Dict[str, List[Optional[float]]] = {}
        if store is not None:
            workers |= set(store.workers())
            for w, _labels, pts in store.series_for("goodput.ratio"):
                goodput_by_w.setdefault(w, []).extend(
                    (t, v) for t, v in pts
                    if t >= horizon and isinstance(v, (int, float)))
            for w, _labels, pts in store.series_for(
                    "trainer.step_seconds"):
                steps_by_w.setdefault(w, []).append(_hist_mean_delta(
                    [(t, v) for t, v in pts if t >= horizon]))
        out: Dict[str, Dict[str, Any]] = {}
        medians: Dict[str, Optional[float]] = {
            w: self._shard_median(w, horizon) for w in workers}
        for w in sorted(workers):
            h: Dict[str, Any] = {
                "goodput_ewma": None, "goodput_var": None,
                "step_ewma": None, "straggler_score": None,
                "heartbeat_jitter": None, "straggler": False,
                "heartbeat_unstable": False, "goodput_collapse": False}
            if store is not None:
                # goodput.ratio is per-component; a worker usually runs
                # one driver loop — series merge time-ordered for the EWMA
                merged = sorted(goodput_by_w.get(w, ()),
                                key=lambda p: p[0])
                vals = [v for _, v in merged]
                if vals:
                    h["goodput_ewma"], h["goodput_var"] = ewma(vals)
                    peak = max(vals)
                    if (peak > 0.05 and h["goodput_ewma"] is not None
                            and h["goodput_ewma"]
                            < self.COLLAPSE_RATIO * peak):
                        h["goodput_collapse"] = True
                means = [m for m in steps_by_w.get(w, ()) if m is not None]
                h["step_ewma"] = ewma(means)[0] if means else None
            m = medians.get(w)
            # leave-one-out reference: the median of the OTHER workers'
            # medians. Including the candidate itself caps the score at
            # N/(N-1)-ish — on a 2-worker fleet an arbitrarily slow
            # worker could never cross 2.0 (found live, ISSUE 15 drive)
            others = [v for k, v in medians.items()
                      if k != w and v is not None]
            ref = _median(others)
            if m is not None and ref:
                score = m / ref
                h["straggler_score"] = score
                if score > self.STRAGGLER_RATIO:
                    h["straggler"] = True
            with self._lock:
                beats = [t for t in self._beats.get(w, ()) if t >= horizon]
            if len(beats) >= 3:
                ivals = [b - a for a, b in zip(beats, beats[1:])]
                med = _median(ivals) or 0.0
                mean = sum(ivals) / len(ivals)
                sd = math.sqrt(sum((x - mean) ** 2 for x in ivals)
                               / len(ivals))
                h["heartbeat_jitter"] = sd
                if med > 0 and sd > self.JITTER_RATIO * med:
                    h["heartbeat_unstable"] = True
            out[w] = h
        return out


# -- the operator table ---------------------------------------------------------

def fold_alert_stream(alerts) -> set:
    """Chronological fold of an alert stream (transition events and/or
    live active entries, oldest first) into the currently-live
    ``{(worker, rule)}`` set: fired/firing adds, a later resolved clears.
    The ONE interpretation of the stream — the table and the ``obs top``
    header both read it, so they cannot disagree."""
    live: set = set()
    for a in alerts or ():
        if not isinstance(a, dict):
            continue
        args = a.get("args", a)
        key = (str(args.get("worker", "") or ""),
               str(args.get("rule", "?")))
        if args.get("state", "firing") in ("fired", "firing"):
            live.add(key)
        elif args.get("state") == "resolved":
            live.discard(key)
    return live

def _latest_by_worker(samples, name: str) -> Dict[str, float]:
    """worker -> last sample value of ``name`` from a flat merged sample
    list (every pushed series carries the worker label contract)."""
    out: Dict[str, float] = {}
    for s in samples or ():
        if not isinstance(s, dict) or s.get("name") != name:
            continue
        v = s.get("value")
        if not isinstance(v, (int, float)):
            continue
        out[(s.get("labels") or {}).get("worker", "?")] = float(v)
    return out


def health_table(samples, alerts=None, health=None, actions=None) -> str:
    """The per-worker fleet table (``obs top`` / ``obs serve /summary``):
    one row per worker with goodput ratio, mfu, queue depth, straggler
    score and its active alerts — read from a merged sample list (live
    ``obs_stats`` or a dump on disk), so the table renders with or
    without a live master. ``health`` optionally takes the master's
    derived per-worker snapshot (``obs_health``) and fills the straggler /
    jitter / goodput cells the samples alone cannot carry. ``actions``
    optionally takes the committed fleet-actor journal (ISSUE 18) and
    appends an "autoscale actions" tail — the operator's one-glance
    answer to "did the actor ACT or is the recommendation just held?"."""
    goodput = _latest_by_worker(samples, "goodput.ratio")
    mfu = _latest_by_worker(samples, "roofline.mfu")
    queue = _latest_by_worker(samples, "serving.queue_depth")
    score = _latest_by_worker(samples, "cluster.health_straggler_score")
    jitter = _latest_by_worker(samples, "cluster.health_heartbeat_jitter")
    for w, h in (health or {}).items():
        for field, dest in (("straggler_score", score),
                            ("heartbeat_jitter", jitter),
                            ("goodput_ewma", goodput)):
            v = h.get(field)
            if v is not None and w not in dest:
                dest[w] = float(v)
    workers = sorted((set(goodput) | set(mfu) | set(queue) | set(score)
                      | set(jitter)) - {"?"})
    by_worker_alerts: Dict[str, List[str]] = {}
    for w, rule in fold_alert_stream(alerts):
        by_worker_alerts.setdefault(w, []).append(rule)
    if not workers:
        return _actions_tail(actions)
    fmt = "{:<20} {:>8} {:>7} {:>6} {:>10} {:>8}  {}"
    lines = [fmt.format("worker", "goodput", "mfu", "queue",
                        "straggler", "hb_jit", "alerts")]

    def cell(d, w, pat="{:.2f}"):
        return pat.format(d[w]) if w in d else "-"

    for w in workers:
        rules = sorted(set(by_worker_alerts.get(w, [])
                           + by_worker_alerts.get("", [])))
        lines.append(fmt.format(
            w[:20], cell(goodput, w), cell(mfu, w),
            cell(queue, w, "{:.0f}"), cell(score, w),
            cell(jitter, w, "{:.3f}"),
            ",".join(rules) if rules else "-"))
    tail = _actions_tail(actions)
    return "\n".join(lines) + (("\n\n" + tail) if tail else "")


def _actions_tail(actions) -> str:
    """Render the committed autoscale-action journal (newest last)."""
    if not actions:
        return ""
    fmt = "{:>10} {:<6} {:<12} {:<20} {}"
    lines = ["== autoscale actions ==",
             fmt.format("ts", "action", "population", "worker", "reason")]
    for a in actions:
        if not isinstance(a, dict):
            continue
        try:
            ts = "{:.1f}".format(float(a.get("ts", 0.0)))
        except (TypeError, ValueError):
            ts = "-"
        lines.append(fmt.format(
            ts, str(a.get("action", "-"))[:6],
            str(a.get("population", "-"))[:12],
            str(a.get("worker", "-"))[:20],
            str(a.get("reason", ""))[:60]))
    return "\n".join(lines)
