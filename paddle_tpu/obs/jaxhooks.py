"""Bridge jax.monitoring compilation events into the metrics plane.

XLA compilation is the dominant hidden cost of a jit-first framework: a
shape change in the train loop silently recompiles and a step that should
take milliseconds takes seconds. jax reports these through
``jax.monitoring`` duration events (e.g. ``.../backend_compile_time``);
this module registers ONE process-wide listener that forwards any
compilation-duration event into the installed session as
``jax.compiles_total`` / ``jax.compile_seconds`` — the compile-vs-execute
split the trainer's step histograms can't see from the host side.

The listener is registered lazily on the first session install and checks
``obs.is_active()`` per event, so an uninstalled process pays nothing and
jax's listener list is never cleared (other packages may have their own).
The jax.monitoring surface is semi-public and varies across versions, so
registration is best-effort: on any API mismatch the bridge degrades to a
no-op and the rest of the plane works unchanged.
"""

from __future__ import annotations

import threading

_registered = False
_lock = threading.Lock()

#: event-name marker for "one XLA backend compile". One jit call emits
#: SEVERAL duration events (jaxpr trace, mlir lowering, backend compile);
#: counting anything broader than backend_compile would tally one compile
#: 3x and mix unrelated distributions into one histogram.
_COMPILE_MARKER = "backend_compile"


def _on_duration(event: str, duration_secs: float = 0.0, **kw) -> None:
    # late import: this module must stay importable before obs/__init__
    # finishes (it registers us during _install)
    from . import _SESSION
    s = _SESSION
    if s is None:
        return
    if _COMPILE_MARKER not in event:
        return
    try:
        s.registry.counter("jax.compiles_total").inc()
        s.registry.histogram("jax.compile_seconds").observe(duration_secs)
        s.tracer.instant("jax.compile", event=event,
                         duration_secs=duration_secs)
        # goodput ledger: the compile second is hiding inside whatever
        # bucket the compiling thread has open — move it to `compile`
        from . import goodput
        goodput.note_compile(duration_secs)
    except Exception:
        # a telemetry bridge must never take down a compile
        pass


def ensure_registered() -> bool:
    """Idempotently hook jax.monitoring; True when the bridge is live."""
    global _registered
    with _lock:
        if _registered:
            return True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(_on_duration)
        except Exception:
            return False
        _registered = True
        return True
