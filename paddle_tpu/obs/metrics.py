"""Typed metrics: Counter / Gauge / Histogram behind a registry.

The reference's only numeric observability is the ``StatSet`` timer table
(paddle/utils/Stat.h) — unlabeled, untyped, print-only. This module is the
typed half of the observability plane (docs/design/observability.md): three
metric kinds with Prometheus-compatible semantics, label support, and a
registry that can be process-global (the default every instrumented module
reports into via :mod:`paddle_tpu.obs` hooks) or instantiated per-test so
assertions never see another test's counts.

Naming is a public contract: ``subsystem.noun_qualifier`` — exactly one
dot, snake_case atoms (``trainer.steps_total``, ``rpc.call_seconds``).
The registry enforces the shape at registration time; the suffix-per-kind
conventions (counters ``_total``, histograms ``_seconds``/``_bytes``) are
checked by the ``L005`` lint (analysis/lints.py:lint_metric_names), which
also runs over the static :mod:`~paddle_tpu.obs.catalogue` in
``paddle_tpu lint``.

Thread safety: every mutation takes the metric's lock — trainer threads,
prefetch workers and lease keepers all report concurrently. The cost is
only paid while a session is installed (see paddle_tpu/obs/__init__.py for
the zero-cost-when-off discipline).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: the naming contract: one dot, snake_case atoms on both sides
METRIC_NAME_RE = re.compile(
    r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)*\.[a-z][a-z0-9]*(?:_[a-z0-9]+)*$")

#: default histogram boundaries (seconds): tuned for host-loop latencies —
#: sub-ms jit dispatch up through multi-second compiles/checkpoints
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Metric:
    """Family base: one name, many label-sets (children)."""

    kind = "metric"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        # RLock (not Lock): the flight recorder's signal-handler dump
        # collects these on the main thread, which may itself be paused
        # inside a mutation's critical section — re-entry must not
        # deadlock the dying process (see obs/flight.py)
        self._lock = threading.RLock()


class Counter(Metric):
    """Monotonic accumulator. ``inc`` only; negative increments raise."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._vals: Dict[LabelKey, float] = {}

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        key = _label_key(labels)
        with self._lock:
            self._vals[key] = self._vals.get(key, 0.0) + n

    def labels(self, **labels) -> "_BoundCounter":
        return _BoundCounter(self, labels)

    def get(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_label_key(labels), 0.0)

    def samples(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._vals.items())


class _BoundCounter:
    """A counter pinned to one label-set (Prometheus ``.labels()`` child)."""

    __slots__ = ("_c", "_labels")

    def __init__(self, counter: Counter, labels: Dict[str, object]):
        self._c = counter
        self._labels = labels

    def inc(self, n: float = 1) -> None:
        self._c.inc(n, **self._labels)

    def get(self) -> float:
        return self._c.get(**self._labels)


class Gauge(Metric):
    """Point-in-time value; ``set``/``inc``/``dec``. Tracks the high-water
    mark per label-set (``max``) so a sampled value like queue depth still
    reports its peak after the fact."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._vals: Dict[LabelKey, float] = {}
        self._max: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._vals[key] = float(value)
            if value > self._max.get(key, float("-inf")):
                self._max[key] = float(value)

    def inc(self, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            v = self._vals.get(key, 0.0) + n
            self._vals[key] = v
            if v > self._max.get(key, float("-inf")):
                self._max[key] = v

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def get(self, **labels) -> float:
        with self._lock:
            return self._vals.get(_label_key(labels), 0.0)

    def high_water(self, **labels) -> float:
        with self._lock:
            return self._max.get(_label_key(labels), 0.0)

    def samples(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._vals.items())


class _HistState:
    __slots__ = ("counts", "sum", "count", "max")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)   # +1 = overflow (+Inf) bucket
        self.sum = 0.0
        self.count = 0
        self.max = 0.0


class Histogram(Metric):
    """Fixed-boundary histogram (``le``-style cumulative buckets at export).

    Boundaries are fixed at construction — re-registering the same name
    with different buckets is an error (the series would be unmergeable).
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help)
        b = tuple(float(x) for x in buckets)
        if not b or list(b) != sorted(set(b)):
            raise ValueError(
                f"histogram {name}: buckets must be strictly increasing")
        self.buckets = b
        self._states: Dict[LabelKey, _HistState] = {}

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _HistState(len(self.buckets))
            # first bucket whose boundary >= value; else overflow
            for i, le in enumerate(self.buckets):
                if value <= le:
                    st.counts[i] += 1
                    break
            else:
                st.counts[-1] += 1
            st.sum += value
            st.count += 1
            if value > st.max:
                st.max = value

    def snapshot(self, **labels) -> Dict[str, object]:
        """Cumulative (prometheus-style) view: ``buckets`` is a list of
        ``[le, cumulative_count]`` ending with ``["+Inf", count]``."""
        key = _label_key(labels)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                return {"buckets": [], "sum": 0.0, "count": 0, "max": 0.0}
            cum, out = 0, []
            for le, c in zip(self.buckets, st.counts):
                cum += c
                out.append([le, cum])
            out.append(["+Inf", cum + st.counts[-1]])
            return {"buckets": out, "sum": st.sum, "count": st.count,
                    "max": st.max}

    def samples(self) -> List[Tuple[LabelKey, Dict[str, object]]]:
        with self._lock:
            keys = sorted(self._states)
        return [(k, self.snapshot(**dict(k))) for k in keys]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create home for metric families.

    One process-global instance backs the installed session by default
    (``paddle_tpu.obs.REGISTRY``); tests construct their own so counts
    are isolated. Kind conflicts and malformed names raise immediately —
    a metric name is API surface, not a string that fails at scrape time.
    """

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.RLock()   # signal-safe: see Metric._lock

    def _get(self, cls, name: str, help: str, **kw) -> Metric:
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} violates the subsystem.noun_qualifier "
                "convention (one dot, snake_case atoms); see "
                "docs/design/observability.md")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            elif kw.get("buckets") is not None and \
                    tuple(float(x) for x in kw["buckets"]) != m.buckets:
                raise ValueError(
                    f"histogram {name!r} already registered with different "
                    "bucket boundaries")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        if buckets is None:
            with self._lock:
                m = self._metrics.get(name)
            if isinstance(m, Histogram):
                return m
            buckets = DEFAULT_BUCKETS
        return self._get(Histogram, name, help, buckets=buckets)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def collect(self) -> List[Dict[str, object]]:
        """Flat sample list every exporter consumes (and the JSONL dump
        serializes): one dict per (metric, label-set)."""
        out: List[Dict[str, object]] = []
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        for m in metrics:
            if isinstance(m, Histogram):
                for key, snap in m.samples():
                    out.append({"type": "histogram", "name": m.name,
                                "help": m.help, "labels": dict(key), **snap})
            elif isinstance(m, Gauge):
                for key, v in m.samples():
                    out.append({"type": "gauge", "name": m.name,
                                "help": m.help, "labels": dict(key),
                                "value": v,
                                "high_water": m.high_water(**dict(key))})
            else:
                for key, v in m.samples():
                    out.append({"type": "counter", "name": m.name,
                                "help": m.help, "labels": dict(key),
                                "value": v})
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
