"""Per-request timeline ledger for the serving fabric.

Every request that crosses the router/prefill/decode fabric carries a
client-minted ``submit_key``; each process appends structured phase
records to its local :class:`RequestLedger` under that key (re-routed
legs under the derived ``{key}#r{n}``). Ledger exports flow to the
router/master :class:`RequestStore` (scrape pump + ``obs_health``),
where :func:`stitch` merges the legs into one timeline per base key —
the evidence layer behind ``serving.phase_seconds{phase}``, the
slowest-K exemplar ring attached to burn-rate alerts, ``paddle_tpu obs
trace`` and the ``/requests`` endpoint (docs/design/observability.md,
"Request timelines & SLO attribution").

Durations telescope: an event's ``dur`` is the gap since the previous
event for that key on the same ledger, so per-ledger duration sums are
exact by construction; recorders that measured a sub-interval
themselves (the prefill worker's compute/ship walls) pass ``dur``
explicitly. Cross-process gaps therefore surface as unattributed
remainder rather than being mis-billed to a phase.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Tuple

from . import count as _count
from . import observe as _observe

# canonical phase vocabulary (docs/design/observability.md)
PHASES = ("admitted", "queued", "scheduled", "prefill", "ship", "adopt",
          "first_token", "decode", "route", "reroute", "done", "cancel")
#: phases that close a timeline
TERMINAL = ("done", "cancel")
#: phases whose telescoped duration is attributed into the SLO
#: breakdown histogram serving.phase_seconds{phase} — a bounded enum,
#: never a request key (L005)
ATTRIBUTED = ("queued", "scheduled", "prefill", "ship", "adopt", "decode")
#: point events that repeat per segment and fold into one record
_FOLDABLE = ("decode",)

_MAX_EXTRA = 6
_MAX_EXTRA_STR = 80


def base_key(key: str) -> str:
    """Strip the re-route suffix: ``k#r2`` → ``k`` (router.py derives
    leg keys as ``f"{key}#r{n}"`` on every re-route)."""
    return str(key).split("#r", 1)[0]


def leg_of(key: str) -> int:
    """Leg ordinal encoded in the key: ``k`` → 0, ``k#r2`` → 2."""
    s = str(key)
    if "#r" not in s:
        return 0
    try:
        return int(s.rsplit("#r", 1)[1])
    except ValueError:
        return 0


def _clean_extra(extra: dict) -> dict:
    out = {}
    for k, v in extra.items():
        if len(out) >= _MAX_EXTRA:
            break
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[str(k)] = v
        elif isinstance(v, str):
            out[str(k)] = v[:_MAX_EXTRA_STR]
    return out


class RequestLedger:
    """Bounded per-process ring of request timelines.

    Thread-safe; install via :func:`paddle_tpu.obs.ensure_request_ledger`
    so the ``obs.req_phase`` hook finds it. ``clock`` is injectable for
    deterministic tests; ``origin_unix`` maps ledger timestamps onto
    unix time so legs recorded by different processes stitch onto one
    axis (same contract as the session meta's ``clock_origin_unix``).
    """

    def __init__(self, *, cap: int = 1024, events_cap: int = 256,
                 clock=None, ident: Optional[str] = None):
        self._clock = clock if clock is not None else time.time
        self.origin_unix = time.time() - self._clock()
        self.cap = int(cap)
        self.events_cap = int(events_cap)
        self.ident = str(ident) if ident else f"pid{__import__('os').getpid()}"
        self._lock = threading.Lock()
        self._tl: "OrderedDict[str, dict]" = OrderedDict()
        self.dropped = 0  # timelines evicted by the ring cap

    def __len__(self) -> int:
        with self._lock:
            return len(self._tl)

    def install(self) -> "RequestLedger":
        from . import _set_requests
        _set_requests(self)
        return self

    def uninstall(self) -> None:
        from . import _REQUESTS, _set_requests
        if _REQUESTS is self:
            _set_requests(None)

    def phase(self, key: str, phase: str, dur: Optional[float] = None,
              **extra) -> None:
        """Append a phase record. ``dur`` defaults to the telescoped gap
        since this key's previous event (0.0 for the first)."""
        now = self._clock()
        key = str(key)
        with self._lock:
            tl = self._tl.get(key)
            if tl is None:
                if len(self._tl) >= self.cap:
                    self._tl.popitem(last=False)
                    self.dropped += 1
                tl = {"key": key, "recorder": self.ident,
                      "origin": self.origin_unix, "events": [],
                      "done": False, "updated": now}
                self._tl[key] = tl
            else:
                self._tl.move_to_end(key)
            evs = tl["events"]
            d = float(dur) if dur is not None else (
                max(0.0, now - evs[-1]["t"]) if evs else 0.0)
            last = evs[-1] if evs else None
            if (last is not None and phase in _FOLDABLE
                    and last["phase"] == phase):
                # fold the per-segment decode stream into one record so a
                # long generation stays O(1) in the event list
                last["t"] = now
                last["dur"] += d
                if "n" in extra:
                    last["n"] = int(last.get("n", 0)) + int(extra["n"])
                last["folds"] = int(last.get("folds", 0)) + 1
            elif len(evs) >= self.events_cap:
                tl["overflow"] = int(tl.get("overflow", 0)) + 1
            else:
                ev = {"phase": str(phase), "t": now, "dur": d}
                ev.update(_clean_extra(extra))
                evs.append(ev)
            if phase in TERMINAL:
                tl["done"] = True
            tl["updated"] = now
        if phase in ATTRIBUTED and d > 0.0:
            _observe("serving.phase_seconds", d, phase=phase)

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            tl = self._tl.get(str(key))
            return _copy_tl(tl) if tl is not None else None

    def export(self, n: Optional[int] = None,
               keys: Optional[Iterable[str]] = None) -> List[dict]:
        """Wire-safe copies of the most recently updated ``n`` timelines
        (all when ``n`` is None), oldest-update first."""
        with self._lock:
            if keys is not None:
                picked = [self._tl[k] for k in keys if k in self._tl]
            else:
                picked = list(self._tl.values())
                if n is not None and len(picked) > n:
                    picked = picked[-int(n):]
            return [_copy_tl(tl) for tl in picked]

    def forget(self, key: str) -> bool:
        """Drop one timeline (membership reap / post-aggregation)."""
        with self._lock:
            return self._tl.pop(str(key), None) is not None


def _copy_tl(tl: dict) -> dict:
    out = dict(tl)
    out["events"] = [dict(ev) for ev in tl["events"]]
    return out


def group_legs(timelines) -> Dict[str, List[dict]]:
    """Group raw timelines by base key for :func:`stitch`, deduplicating
    legs recorded by the same ``(recorder, key)`` — a leg can reach a
    merged dump twice (scrape pump AND the recorder's own dump); the
    copy with more events wins."""
    best: Dict[Tuple[str, str], dict] = {}
    for tl in timelines or ():
        if not isinstance(tl, dict) or not tl.get("key"):
            continue
        lk = (str(tl.get("recorder") or tl.get("worker") or ""),
              str(tl["key"]))
        cur = best.get(lk)
        if cur is None or len(tl.get("events") or ()) >= \
                len(cur.get("events") or ()):
            best[lk] = tl
    out: Dict[str, List[dict]] = {}
    for (_, key), tl in best.items():
        out.setdefault(base_key(key), []).append(tl)
    return out


def stitch(timelines: Iterable[dict]) -> Optional[dict]:
    """Merge one request's legs (``k``, ``k#r1``, ...) across recorders
    into a single timeline on the unix-time axis.

    The stitching contract: events sort by ``origin + t``; the earliest
    ``first_token`` is canonical and later ones (a re-routed leg
    resuming the stream) are flagged ``resumed`` so TTFT is never
    double-counted; ``breakdown`` sums only ATTRIBUTED phase durations
    while ``total_s`` sums every duration, so per-ledger telescoping
    reconciles against observed TTFT + decode wall time.
    """
    tls = [tl for tl in timelines if isinstance(tl, dict)
           and tl.get("events")]
    if not tls:
        return None
    base = base_key(tls[0].get("key", ""))
    events: List[dict] = []
    legs = set()
    workers = set()
    for tl in tls:
        origin = float(tl.get("origin", 0.0))
        leg = leg_of(tl.get("key", ""))
        legs.add(leg)
        w = tl.get("worker")
        if w:
            workers.add(str(w))
        for seq, ev in enumerate(tl["events"]):
            try:
                t_unix = origin + float(ev["t"])
            except (KeyError, TypeError, ValueError):
                continue
            e = dict(ev)
            e["t_unix"] = t_unix
            e["leg"] = leg
            if w:
                e["worker"] = str(w)
            rec = tl.get("recorder")
            if rec:
                e["recorder"] = str(rec)
            events.append((t_unix, leg, seq, e))
    if not events:
        return None
    events.sort(key=lambda it: (it[0], it[1], it[2]))
    evs = [e for (_, _, _, e) in events]
    t0 = evs[0]["t_unix"]
    t_ft = None
    for e in evs:
        if e["phase"] == "first_token":
            if t_ft is None:
                t_ft = e["t_unix"]
            else:
                e["resumed"] = True
    t_end = evs[-1]["t_unix"]
    done = any(e["phase"] in TERMINAL for e in evs)
    breakdown: Dict[str, float] = {}
    total = 0.0
    for e in evs:
        d = float(e.get("dur", 0.0))
        total += d
        if e["phase"] in ATTRIBUTED:
            breakdown[e["phase"]] = breakdown.get(e["phase"], 0.0) + d
    dominant = max(breakdown, key=breakdown.get) if breakdown else None
    return {
        "key": base,
        "legs": sorted(legs),
        "workers": sorted(workers),
        "reroutes": max(legs) if legs else 0,
        "done": done,
        "t0_unix": t0,
        "ttft_s": (t_ft - t0) if t_ft is not None else None,
        "wall_s": t_end - t0,
        "total_s": total,
        "breakdown": breakdown,
        "dominant": dominant,
        "events": evs,
    }


def format_timeline(st: dict) -> str:
    """Human-readable rendering of a stitched timeline for the
    ``paddle_tpu obs trace`` CLI."""
    lines = []
    ttft = st.get("ttft_s")
    head = (f"request {st['key']}  "
            f"{'done' if st.get('done') else 'in-flight'}  "
            f"legs={len(st.get('legs') or [0])}")
    if ttft is not None:
        head += f"  ttft={ttft * 1e3:.1f}ms"
    head += f"  wall={st.get('wall_s', 0.0) * 1e3:.1f}ms"
    if st.get("dominant"):
        head += f"  dominant={st['dominant']}"
    lines.append(head)
    bd = st.get("breakdown") or {}
    if bd:
        parts = [f"{p}={bd[p] * 1e3:.1f}ms" for p in ATTRIBUTED if p in bd]
        lines.append("  breakdown: " + "  ".join(parts))
    t0 = st.get("t0_unix", 0.0)
    for e in st.get("events", []):
        rel = e.get("t_unix", t0) - t0
        who = e.get("worker") or e.get("recorder") or "?"
        row = (f"  +{rel * 1e3:9.2f}ms  leg{e.get('leg', 0)} "
               f"{who:<16} {e['phase']:<12}")
        d = float(e.get("dur", 0.0))
        if d > 0.0:
            row += f" dur={d * 1e3:.2f}ms"
        for k in ("n", "why", "reason", "to", "tenant", "folds"):
            if k in e:
                row += f" {k}={e[k]}"
        if e.get("resumed"):
            row += " resumed"
        lines.append(row)
    return "\n".join(lines)


class RequestStore:
    """Router/master-side aggregation of ledger exports.

    Legs key on ``(recorder, key)`` so a timeline pushed twice (scrape
    pump AND loopback push) replaces rather than duplicates. Memory is
    a ring over base keys plus the slowest-K exemplar window; membership
    ``forget_worker`` reaps a departed worker's legs for *completed*
    requests immediately while in-flight legs survive until their base
    stitches done — exactly what re-route stitching after kill -9
    needs (tests/test_serving_router.py).
    """

    def __init__(self, *, cap: int = 1024, exemplar_k: int = 8,
                 window_s: float = 600.0, clock=None):
        self._clock = clock if clock is not None else time.monotonic
        self.cap = int(cap)
        self.exemplar_k = int(exemplar_k)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        # base key -> {"legs": {(recorder, key): tl}, "noted": bool}
        self._reqs: "OrderedDict[str, dict]" = OrderedDict()
        self._exemplars: List[dict] = []  # slowest-first within window
        self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._reqs)

    def push(self, worker: str, timelines) -> int:
        """Absorb one worker's ledger export; returns accepted count.
        Wire-tolerant: malformed entries are skipped, never raised."""
        if not isinstance(timelines, (list, tuple)):
            return 0
        accepted = 0
        touched = []
        with self._lock:
            for tl in timelines:
                if not isinstance(tl, dict):
                    continue
                key = tl.get("key")
                if not isinstance(key, str) or not key:
                    continue
                evs = tl.get("events")
                if not isinstance(evs, list):
                    continue
                clean = _copy_tl({**tl, "events": [
                    e for e in evs if isinstance(e, dict)
                    and isinstance(e.get("phase"), str)
                    and isinstance(e.get("t"), (int, float))]})
                clean["worker"] = str(worker)
                base = base_key(key)
                req = self._reqs.get(base)
                if req is None:
                    if len(self._reqs) >= self.cap:
                        self._reqs.popitem(last=False)
                        self.dropped += 1
                    req = {"legs": {}, "noted": False}
                    self._reqs[base] = req
                else:
                    self._reqs.move_to_end(base)
                rec = str(clean.get("recorder") or worker)
                req["legs"][(rec, key)] = clean
                accepted += 1
                touched.append(base)
            stitched = []
            for base in dict.fromkeys(touched):
                req = self._reqs.get(base)
                if req is None or req["noted"]:
                    continue
                st = stitch(req["legs"].values())
                if st is not None and st["done"]:
                    req["noted"] = True
                    stitched.append(st)
        for st in stitched:
            self._note_exemplar(st)
        return accepted

    def _note_exemplar(self, st: dict) -> None:
        # rank by TTFT when the request produced a first token, else by
        # wall time (a cancelled request can still be the slow exemplar)
        score = st["ttft_s"] if st.get("ttft_s") is not None \
            else st.get("wall_s", 0.0)
        entry = dict(st)
        entry["score"] = float(score)
        entry["noted_at"] = self._clock()
        with self._lock:
            self._exemplars.append(entry)
            self._exemplars.sort(key=lambda e: -e["score"])
            del self._exemplars[self.exemplar_k:]
        _count("serving.exemplars_total",
               phase=str(st.get("dominant") or "none"))

    def exemplars(self, k: Optional[int] = None,
                  full: bool = False) -> List[dict]:
        """Slowest-K stitched timelines inside the alert window,
        slowest first. ``full=False`` drops the event list — the compact
        form attached to burn-rate alert transitions."""
        now = self._clock()
        with self._lock:
            self._exemplars = [e for e in self._exemplars
                               if now - e["noted_at"] <= self.window_s]
            picked = self._exemplars[:k if k is not None else self.exemplar_k]
            out = []
            for e in picked:
                c = dict(e)
                c.pop("noted_at", None)
                if not full:
                    c.pop("events", None)
                out.append(c)
            return out

    def get(self, key: str) -> Optional[dict]:
        """Stitched timeline for a base (or leg) key."""
        with self._lock:
            req = self._reqs.get(base_key(key))
            legs = list(req["legs"].values()) if req else []
        return stitch(legs) if legs else None

    def recent(self, n: int = 64) -> List[dict]:
        """Stitched summaries (no event lists) of the n most recently
        updated requests, oldest first."""
        with self._lock:
            bases = list(self._reqs.keys())[-int(n):]
            legs_by_base = [(b, list(self._reqs[b]["legs"].values()))
                            for b in bases]
        out = []
        for b, legs in legs_by_base:
            st = stitch(legs)
            if st is not None:
                st.pop("events", None)
                out.append(st)
        return out

    def export_legs(self, n: int = 128) -> List[dict]:
        """Raw leg timelines of the n most recent bases — the wire form
        served by ``obs_health`` / ``/requests`` so every consumer runs
        the same :func:`stitch`."""
        with self._lock:
            bases = list(self._reqs.keys())[-int(n):]
            return [_copy_tl(tl) for b in bases
                    for tl in self._reqs[b]["legs"].values()]

    def forget(self, key: str) -> bool:
        with self._lock:
            return self._reqs.pop(base_key(key), None) is not None

    def forget_worker(self, worker: str) -> int:
        """Membership reap: drop the departed worker's legs for
        completed requests (in-flight legs stay stitchable)."""
        w = str(worker)
        dropped = 0
        with self._lock:
            for base in list(self._reqs.keys()):
                req = self._reqs[base]
                if not req["noted"]:
                    continue
                legs = req["legs"]
                for lk in [lk for lk, tl in legs.items()
                           if tl.get("worker") == w]:
                    del legs[lk]
                    dropped += 1
                if not legs:
                    del self._reqs[base]
        return dropped
