"""The device cost ledger — chip utilization as a first-class obs signal.

Every ``mfu`` / ``hbm_bw_util`` figure the bench harness ever printed was
an offline artifact: ``benchmarks/mfu.py`` cost-analyzed a step in a side
script and the serving rows hand-modeled their bytes. This module is the
ONE resolution path both the bench rows and the live gauges go through,
so the two can never disagree on methodology:

* **Peak tables** — dense-peak TFLOP/s *and* HBM GB/s per jax
  ``device_kind`` (the HBM table is new; the TFLOP table is shared with
  ``benchmarks/mfu.py``, which now delegates here). ``None`` peaks (CPU,
  unknown chips) make every derived utilization an honest null, never a
  fabricated number. Override with ``PADDLE_TPU_PEAK_TFLOPS`` /
  ``PADDLE_TPU_PEAK_HBM_GBPS``.
* **Per-executable costs** — :func:`compiled_cost` reads
  ``compiled.cost_analysis()`` (FLOPs, bytes accessed) and
  ``memory_analysis()`` (peak temp/argument HBM) off an AOT-compiled
  executable; :class:`CostInstrumentedJit` wraps a ``jax.jit`` callable
  so its first call per argument signature lowers + compiles AOT,
  records the :class:`Cost`, and every call *accounts* it.
* **Kernel cost models** — Pallas custom calls report ZERO FLOPs/bytes
  to XLA, so the routes that dispatch them (:func:`register_kernel_cost`
  / :func:`kernel_cost`) contribute their modeled bytes instead:
  ``ops/pallas_kernels.py`` registers ``decode_attention`` /
  ``paged_decode_attention``; the model/serving call sites and
  ``benchmarks/serving_decode.py`` resolve through the same entry.
* **Accounting** — :func:`account` accumulates
  ``fluid.device_flops_total`` / ``fluid.device_bytes_total`` on the
  installed session and derives the live ``roofline.mfu`` /
  ``roofline.hbm_bw_util`` gauges from the counter deltas over a short
  window — visible in ``paddle_tpu obs serve`` and the cluster
  aggregator exactly like any other series.

Failure is loud but bounded: a broken cost analysis warns ONCE per
process, counts ``roofline.cost_analysis_failures_total``, and resolves
to ``None`` — an honest unknown, not a quiet null
(docs/design/observability.md "Device timelines & roofline").
"""

from __future__ import annotations

import os
import threading
import time
import warnings
import weakref
from typing import Any, Callable, Dict, Optional, Tuple

# -- peak tables (the roofline's two ceilings) ---------------------------------

#: dense bf16 peak TFLOP/s by jax device_kind (f32 shares the MXU peak via
#: XLA's 3-pass bf16 decomposition; the convention is noted in bench JSON)
PEAK_TFLOPS: Dict[str, Optional[float]] = {
    "TPU v5 lite": 197.0,       # v5e
    "TPU v5e": 197.0,
    "TPU v5": 459.0,            # v5p
    "TPU v4": 275.0,
    "TPU v6 lite": 918.0,       # v6e / Trillium
    "cpu": None,
}

#: HBM bandwidth GB/s by device_kind — the table benchmarks/serving_decode
#: hard-coded as a module constant before this existed
PEAK_HBM_GBPS: Dict[str, Optional[float]] = {
    "TPU v5 lite": 819.0,       # v5e
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,           # v5p
    "TPU v4": 1228.0,
    "TPU v6 lite": 1640.0,      # v6e / Trillium
    "cpu": None,
}


def _device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return "cpu"


_warned_env_vars: set = set()


def _env_peak(var: str) -> Optional[float]:
    """``float(os.environ[var])`` with a malformed value demoted to a
    once-per-process warning and a fall-through to the device table —
    these run inside ``account()`` on the dispatch hot path, and
    telemetry must never destroy a successful run."""
    env = os.environ.get(var)
    if not env:
        return None
    try:
        return float(env)
    except ValueError:
        with _warn_lock:
            if var in _warned_env_vars:
                return None
            _warned_env_vars.add(var)
        warnings.warn(
            f"ignoring malformed {var}={env!r} (expected a number); peak "
            "resolves from the built-in device table instead",
            RuntimeWarning, stacklevel=3)
        return None


def peak_flops_per_sec() -> Optional[float]:
    """Chip dense peak in FLOP/s, or None when unknown (derived MFU is
    then omitted/null)."""
    env = _env_peak("PADDLE_TPU_PEAK_TFLOPS")
    if env is not None:
        return env * 1e12
    tf = PEAK_TFLOPS.get(_device_kind())
    return None if tf is None else tf * 1e12


def peak_hbm_bytes_per_sec() -> Optional[float]:
    """Chip HBM bandwidth in bytes/s, or None when unknown."""
    env = _env_peak("PADDLE_TPU_PEAK_HBM_GBPS")
    if env is not None:
        return env * 1e9
    gb = PEAK_HBM_GBPS.get(_device_kind())
    return None if gb is None else gb * 1e9


# -- failure path (shared with benchmarks/mfu.py) ------------------------------

_warned_cost_failure = False
_warn_lock = threading.Lock()


def cost_failure(where: str, exc: Optional[BaseException] = None) -> None:
    """A cost analysis failed: count it and warn ONCE per process — the
    old ``benchmarks/mfu.step_flops`` swallowed every exception into a
    silent None, and a broken methodology read as a legit unknown."""
    from . import count
    count("roofline.cost_analysis_failures_total")
    global _warned_cost_failure
    with _warn_lock:
        if _warned_cost_failure:
            return
        _warned_cost_failure = True
    detail = f": {type(exc).__name__}: {exc}" if exc is not None else ""
    warnings.warn(
        f"XLA cost analysis failed at {where}{detail} — derived "
        "FLOPs/bytes resolve to null for this executable (counted in "
        "roofline.cost_analysis_failures_total; further failures this "
        "process are counted silently)",
        RuntimeWarning, stacklevel=3)


# -- the per-executable cost record --------------------------------------------

class Cost:
    """FLOPs + HBM bytes of ONE dispatch of a compiled executable (plus
    its compile-time peak-memory estimate). ``None`` fields mean the
    analysis could not resolve them — honest unknowns."""

    __slots__ = ("flops", "bytes", "peak_hbm_bytes")

    def __init__(self, flops: Optional[float] = None,
                 bytes: Optional[float] = None,
                 peak_hbm_bytes: Optional[int] = None):
        self.flops = flops
        self.bytes = bytes
        self.peak_hbm_bytes = peak_hbm_bytes

    def __repr__(self):
        return (f"Cost(flops={self.flops}, bytes={self.bytes}, "
                f"peak_hbm_bytes={self.peak_hbm_bytes})")


def compiled_cost(compiled, where: str = "compiled") -> Optional[Cost]:
    """Resolve one executable's :class:`Cost` from XLA's own analyses.

    ``cost_analysis()`` yields ``flops`` and ``bytes accessed``;
    ``memory_analysis()`` the argument/output/temp footprint whose sum
    approximates peak HBM for the dispatch. Pallas custom calls report
    zero to both — callers whose executables route through hand kernels
    add the registered :func:`kernel_cost` on top (see
    :class:`CostInstrumentedJit`'s ``extra_bytes``)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0)) if hasattr(ca, "get") else 0.0
        nbytes = (float(ca.get("bytes accessed", 0.0))
                  if hasattr(ca, "get") else 0.0)
    except Exception as e:
        cost_failure(where, e)
        return None
    peak = None
    try:
        ma = compiled.memory_analysis()
        peak = int(getattr(ma, "argument_size_in_bytes", 0)
                   + getattr(ma, "output_size_in_bytes", 0)
                   + getattr(ma, "temp_size_in_bytes", 0)
                   - getattr(ma, "alias_size_in_bytes", 0))
    except Exception:
        pass          # memory_analysis is optional on some backends
    return Cost(flops=flops if flops > 0 else None,
                bytes=nbytes if nbytes > 0 else None,
                peak_hbm_bytes=peak)


def analyze_fn(fn, *args, where: str = "analyze_fn",
               **kwargs) -> Optional[Cost]:
    """Lower + compile ``fn(*args)`` and resolve its :class:`Cost` — the
    shared resolution path behind ``benchmarks/mfu.step_flops`` and the
    executor's ledger."""
    import jax
    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception as e:
        cost_failure(where, e)
        return None
    return compiled_cost(compiled, where)


# -- kernel cost models (the Pallas zero-FLOP override) ------------------------

#: kernel name -> callable(**meta) -> modeled HBM bytes per dispatch
_KERNEL_COSTS: Dict[str, Callable[..., float]] = {}


def register_kernel_cost(kernel: str, fn: Callable[..., float]) -> None:
    """Register the modeled HBM bytes of one dispatch of a hand kernel.

    Pallas custom calls are opaque to XLA's cost analysis (zero FLOPs,
    zero bytes); the kernel's own module registers an analytic bytes
    model here at import, and every consumer — live accounting, bench
    rows, the profile CLI — resolves through :func:`kernel_cost`, so the
    modeled number has exactly one owner."""
    _KERNEL_COSTS[kernel] = fn


def kernel_cost(kernel: str, **meta) -> Optional[float]:
    """Modeled HBM bytes for one dispatch of ``kernel`` under ``meta``
    (shape/dtype facts the call site knows); None when no model is
    registered."""
    fn = _KERNEL_COSTS.get(kernel)
    if fn is None:
        return None
    return float(fn(**meta))


def registered_kernels() -> Tuple[str, ...]:
    return tuple(sorted(_KERNEL_COSTS))


# -- trace-time kernel-byte collection -----------------------------------------
# A Pallas launch site runs ONCE per trace, but the compiled executable
# dispatches many times. The executor / instrumented-jit wraps its trace
# in collect_kernel_bytes(); launch sites call note_kernel_bytes(), the
# collector absorbs the modeled bytes, and the owner re-emits them PER
# DISPATCH (kernels.bytes_total + the account() extra) — so the counter
# keeps one semantic everywhere. Outside any collector (eager execution)
# the site counts directly: one call == one dispatch there.

_trace_collect = threading.local()


class collect_kernel_bytes:
    """Context manager around one trace/lower: collects the kernel bytes
    recorded by launch sites inside. ``per_kernel`` (kernel -> bytes of
    one dispatch) is set at exit."""

    def __init__(self):
        self.per_kernel: Dict[str, float] = {}

    def __enter__(self):
        stack = getattr(_trace_collect, "stack", None)
        if stack is None:
            stack = _trace_collect.stack = []
        stack.append({})
        return self

    def __exit__(self, *exc):
        self.per_kernel = _trace_collect.stack.pop()
        return False


def record_kernel_bytes(kernel: str, nbytes: Optional[float]) -> bool:
    """Record one launch's modeled bytes with the innermost collector.
    Returns False when no collector is active (the caller is executing
    eagerly and owns its own counting)."""
    stack = getattr(_trace_collect, "stack", None)
    if not stack:
        return False
    if nbytes:
        d = stack[-1]
        d[kernel] = d.get(kernel, 0.0) + float(nbytes)
    return True


def note_kernel_bytes(kernel: str, nbytes: Optional[float]) -> None:
    """What a kernel launch site calls with one dispatch's modeled bytes:
    under a trace collector they are absorbed (re-emitted per dispatch by
    the owner); eagerly they count straight into ``kernels.bytes_total``.

    Boundary: a launch traced under a plain user-owned ``jax.jit`` (no
    Executor/:func:`instrument` owner, no collector) counts its trace
    exactly once, so N compiled dispatches contribute one increment —
    wrap such callables in :func:`instrument` to get per-dispatch
    re-emission."""
    if record_kernel_bytes(kernel, nbytes):
        return
    if nbytes:
        from . import count
        count("kernels.bytes_total", nbytes, kernel=kernel)


def emit_kernel_bytes(kb: Optional[Dict[str, float]]) -> float:
    """Re-emit one dispatch's collected kernel bytes into
    ``kernels.bytes_total`` and return their sum (the ``account()``
    extra) — the ONE owner of the per-dispatch re-emission both the
    fluid Executor and :class:`CostInstrumentedJit` call."""
    if not kb:
        return 0.0
    from . import count
    extra = 0.0
    for k, v in kb.items():
        if v:
            extra += v
            count("kernels.bytes_total", v, kernel=k)
    return extra


# -- accounting + derived gauges -----------------------------------------------

#: minimum window between derived-gauge recomputes (seconds): utilization
#: over sub-millisecond deltas is noise
_GAUGE_WINDOW_S = 0.25


class _Deriver:
    """Per-registry derivation state: turns counter deltas into the live
    roofline gauges."""

    __slots__ = ("t0", "flops0", "bytes0")

    def __init__(self, now: float):
        self.t0 = now
        self.flops0 = 0.0
        self.bytes0 = 0.0


# weak-keyed on the registry object: a gc'd registry drops its derivation
# state with it (an id()-keyed dict would leak an entry per registry AND
# let a recycled id inherit a dead registry's t0/counter baselines)
_derivers: "weakref.WeakKeyDictionary[Any, _Deriver]" = \
    weakref.WeakKeyDictionary()
_derive_lock = threading.Lock()


def account(cost: Optional[Cost], extra_bytes: float = 0.0,
            registry=None, now: Optional[float] = None) -> None:
    """Accumulate one dispatch's cost into the device counters and
    refresh the derived roofline gauges.

    No-op without an installed session (the obs zero-cost discipline).
    ``extra_bytes`` carries kernel-modeled bytes the executable's own
    analysis cannot see (see :func:`kernel_cost`)."""
    from . import session
    s = session()
    if s is None and registry is None:
        return
    reg = registry if registry is not None else s.registry
    flops = (cost.flops or 0.0) if cost is not None else 0.0
    nbytes = ((cost.bytes or 0.0) if cost is not None else 0.0) + extra_bytes
    if flops:
        reg.counter("fluid.device_flops_total").inc(flops)
    if nbytes:
        reg.counter("fluid.device_bytes_total").inc(nbytes)
    derive_gauges(reg, now=now)


def derive_gauges(registry, now: Optional[float] = None,
                  min_window: float = _GAUGE_WINDOW_S) -> None:
    """Set ``roofline.mfu`` / ``roofline.hbm_bw_util`` from the device
    counters' deltas since the last derivation (rate-limited). Peaks
    unknown (off-TPU, no env override) -> the gauge is never set: a
    dashboard reads absence, not a made-up zero."""
    if now is None:
        now = time.monotonic()
    d = _derivers.get(registry)
    if d is not None and now - d.t0 < min_window:
        return          # steady-state fast path: no global lock per token
    with _derive_lock:
        d = _derivers.get(registry)
        if d is None:
            _derivers[registry] = d = _Deriver(now)
            d.flops0 = registry.counter("fluid.device_flops_total").get()
            d.bytes0 = registry.counter("fluid.device_bytes_total").get()
            return
        dt = now - d.t0
        if dt < min_window:
            return
        flops = registry.counter("fluid.device_flops_total").get()
        nbytes = registry.counter("fluid.device_bytes_total").get()
        dflops, dbytes = flops - d.flops0, nbytes - d.bytes0
        d.t0, d.flops0, d.bytes0 = now, flops, nbytes
    # >1.0 is physically impossible — a collapsed window or an
    # over-counting byte model. attach_mfu/attach_hbm_bw null the bench
    # column in that case; the gauge twin SKIPS the set (the last honest
    # reading stands) rather than fabricate a saturated chip.
    peak_f = peak_flops_per_sec()
    if peak_f and dflops >= 0:
        mfu = dflops / dt / peak_f
        if mfu <= 1.0:
            registry.gauge("roofline.mfu").set(mfu)
    peak_b = peak_hbm_bytes_per_sec()
    if peak_b and dbytes >= 0:
        util = dbytes / dt / peak_b
        if util <= 1.0:
            registry.gauge("roofline.hbm_bw_util").set(util)


def _reset_derivers() -> None:
    """Test hook: forget derivation state between injected registries."""
    with _derive_lock:
        _derivers.clear()


# -- the instrumented-jit wrapper ----------------------------------------------

def _signature(args, kwargs=None) -> Tuple:
    """Hashable aval signature of a pytree of arrays (shape/dtype per
    leaf) — the wrapper's AOT entries are keyed on it exactly like jit's
    internal cache, so shape-polymorphic callers (a trailing partial
    batch) compile one AOT executable per shape family. Keyword
    arguments ride the same tree (dicts are pytrees), so wrapped
    callables keep jit's full calling convention."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    # dtype objects hash/compare directly — no per-leaf str() on a path
    # a decode loop hits every token
    return (treedef,
            tuple((getattr(x, "shape", ()), getattr(x, "dtype", type(x)))
                  for x in leaves))


class CostInstrumentedJit:
    """Wrap a ``jax.jit`` callable so the cost ledger sees every dispatch.

    First call per argument signature AOT-compiles
    (``jitted.lower(...).compile()``) — paying the compile ONCE, exactly
    where jit would — records the executable's :class:`Cost` in
    :attr:`ledger`, and executes through the compiled object from then
    on. A signature that warmed up on the plain jit path while the
    plane was OFF re-pays one compile at its first plane-on call (jit's
    internal executable is unreachable for cost analysis; the
    persistent XLA compile cache makes it a deserialize). Any lowering/compile/argument mismatch falls back to the plain
    jitted callable for that signature (counted via
    :func:`cost_failure`), so instrumentation can never break a step.

    ``extra_bytes`` (optional ``fn(*args) -> float``) models the HBM
    bytes of hand kernels inside the executable (zero to XLA's own
    analysis); it is resolved per call and added at accounting time.
    """

    def __init__(self, jitted, label: str,
                 extra_bytes: Optional[Callable[..., float]] = None):
        self._jitted = jitted
        self._label = label
        self._extra_bytes = extra_bytes
        #: signature -> (callable, Cost|None); public for the ledger tests
        self.ledger: Dict[Tuple, Tuple[Any, Optional[Cost]]] = {}
        #: signature -> {kernel: bytes/dispatch} collected at trace time
        #: from note_kernel_bytes sites inside the traced function
        self.kernel_bytes: Dict[Tuple, Dict[str, float]] = {}
        self._lock = threading.Lock()

    def cost_of(self, *args, **kwargs) -> Optional[Cost]:
        entry = self.ledger.get(_signature(args, kwargs))
        return entry[1] if entry is not None else None

    def _entry(self, args, kwargs):
        key = _signature(args, kwargs)
        entry = self.ledger.get(key)
        if entry is not None:
            return entry[0], entry[1], self.kernel_bytes.get(key)
        with self._lock:
            entry = self.ledger.get(key)
            if entry is not None:
                return entry[0], entry[1], self.kernel_bytes.get(key)
            try:
                with collect_kernel_bytes() as col:
                    lowered = self._jitted.lower(*args, **kwargs)
                if col.per_kernel:
                    self.kernel_bytes[key] = col.per_kernel
                compiled = lowered.compile()
                entry = (compiled, compiled_cost(compiled, self._label))
            except Exception as e:
                cost_failure(self._label, e)
                entry = (self._jitted, None)
            self.ledger[key] = entry
            return entry[0], entry[1], self.kernel_bytes.get(key)

    def __call__(self, *args, **kwargs):
        from . import is_active
        active = is_active()
        if not active:
            # plane off: reuse an executable the ledger already holds, but
            # NEVER pay a new signature's AOT compile while off (the
            # zero-cost discipline _CompiledEntry enforces the same way)
            entry = (self.ledger.get(_signature(args, kwargs))
                     if self.ledger else None)
            if entry is None:
                return self._jitted(*args, **kwargs)
            call, cost = entry
            kb = None
        else:
            call, cost, kb = self._entry(args, kwargs)
        try:
            out = call(*args, **kwargs)
        except TypeError as e:
            if call is self._jitted:
                raise
            # AOT argument strictness (weak types, committed devices) the
            # signature key cannot see: fall back to jit for this
            # signature — the error fires BEFORE dispatch, so donated
            # buffers are still intact and the retry is safe
            cost_failure(f"{self._label} (aot call)", e)
            self.ledger[_signature(args, kwargs)] = (self._jitted, cost)
            out = self._jitted(*args, **kwargs)
        if active:
            extra = (self._extra_bytes(*args, **kwargs)
                     if self._extra_bytes is not None else 0.0) or 0.0
            account(cost, extra_bytes=extra + emit_kernel_bytes(kb))
        return out


def instrument(fn, label: str, *,
               extra_bytes: Optional[Callable[..., float]] = None,
               **jit_kwargs) -> CostInstrumentedJit:
    """``jax.jit`` + cost ledger in one call: jit ``fn`` (unless already
    jitted) and wrap it in :class:`CostInstrumentedJit`."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn, **jit_kwargs)
    return CostInstrumentedJit(jitted, label, extra_bytes=extra_bytes)
