"""ObsSession: the installable unit binding a registry to a tracer.

Mirrors :class:`paddle_tpu.faults.FaultPlan`'s lifecycle exactly — one
session installed at a time, ``install()``/``uninstall()``/``installed()``
context manager, and module-level hooks (paddle_tpu/obs/__init__.py) that
are a single ``is None`` check when nothing is installed. ``faults`` is the
chaos plane; this is its twin that makes the chaos (and everything else)
visible.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Optional

from .metrics import MetricsRegistry
from .trace import Tracer


class ObsSession:
    """One observation window: metrics + trace + an injectable clock.

    Args:
      registry: metrics home; defaults to the process-global
        ``paddle_tpu.obs.REGISTRY``. Tests pass a fresh
        :class:`MetricsRegistry` so counts are isolated.
      tracer: span collector; defaults to a new :class:`Tracer`.
      clock: convenience — forwarded to a default-constructed tracer so
        ``ObsSession(clock=fake)`` is enough for deterministic spans.
      process: human name for this process in merged multi-process views
        (the Chrome ``process_name`` lane, the merged-registry ``worker``
        label default). Defaults to ``PADDLE_TPU_OBS_PROCESS`` or a
        ``<script>:<pid>`` tag.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None,
                 clock: Optional[Callable[[], float]] = None,
                 process: Optional[str] = None):
        if registry is None:
            from . import REGISTRY
            registry = REGISTRY
        self.registry = registry
        self.tracer = tracer or Tracer(clock=clock)
        if process is None:
            import os
            import sys
            process = os.environ.get("PADDLE_TPU_OBS_PROCESS") or (
                f"{os.path.basename(sys.argv[0] or 'python')}:"
                f"{self.tracer.pid}")
        self.process = process

    # -- lifecycle ----------------------------------------------------------
    def install(self) -> "ObsSession":
        from . import _install
        _install(self)
        return self

    def uninstall(self) -> None:
        from . import _uninstall
        _uninstall(self)

    @contextlib.contextmanager
    def installed(self):
        self.install()
        try:
            yield self
        finally:
            self.uninstall()

    # -- recording ----------------------------------------------------------
    def span(self, name: str, metric: Optional[str] = None,
             metric_labels: Optional[Dict[str, Any]] = None,
             remote: Optional[Dict[str, Any]] = None, **attrs):
        """Trace span; ``metric=`` additionally lands the duration in that
        histogram (one timing source for both views, same clock);
        ``remote=`` records a cross-process parent (obs/context.py)."""
        sp = self.tracer.span(name, remote=remote, **attrs)
        if metric is None:
            return sp
        return _MeteredSpan(sp, self.registry, metric, metric_labels)

    # -- output -------------------------------------------------------------
    def meta(self) -> Dict[str, Any]:
        """The dump's identity block — ONE implementation shared by
        :meth:`dump` and the flight recorder so the two artifact schemas
        cannot drift."""
        from .context import trace_id
        meta = {"created_unix": time.time(), "pid": self.tracer.pid,
                "process": self.process, "trace_id": trace_id(),
                # maps this tracer's (monotonic) span timestamps onto the
                # wall clock so merge_dumps can align processes: a span at
                # ts T happened at unix time clock_origin_unix + T
                "clock_origin_unix": time.time() - self.tracer.clock()}
        if self.tracer.dropped:
            # the trace is truncated at max_events; say so in the artifact
            meta["events_dropped"] = self.tracer.dropped
        return meta

    def dump(self) -> Dict[str, Any]:
        """The canonical export shape (see obs/export.py)."""
        out = {"meta": self.meta(),
               "metrics": self.registry.collect(),
               "events": self.tracer.snapshot()}
        from . import request_ledger
        led = request_ledger()
        if led is not None:
            # request timelines ride every dump artifact (flight rings,
            # --obs_out files), so obs trace works from files alone
            out["requests"] = led.export()
        return out

    def save(self, path: str) -> str:
        """Persist as JSONL — the artifact ``paddle_tpu obs`` consumes."""
        from .export import write_jsonl
        return write_jsonl(path, self.dump())

    def summary(self, stats=None) -> str:
        from .export import summary
        return summary(self.dump(), stats=stats)


class _MeteredSpan:
    """Span that also observes its duration into a histogram on exit."""

    __slots__ = ("_span", "_registry", "_metric", "_labels")

    def __init__(self, span, registry: MetricsRegistry, metric: str,
                 labels: Optional[Dict[str, Any]] = None):
        self._span = span
        self._registry = registry
        self._metric = metric
        self._labels = labels or {}

    def __enter__(self):
        self._span.__enter__()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        out = self._span.__exit__(exc_type, exc, tb)
        self._registry.histogram(self._metric).observe(
            self._span.duration, **self._labels)
        return out

    @property
    def id(self):
        """Underlying span id — what wire context stamps into requests."""
        return self._span.id
