"""Span tracer: structured timing events with parent/child nesting.

The tracing half of the observability plane. A :class:`Tracer` records
*spans* — named intervals with monotonic timestamps, the recording thread's
id, and the id of the enclosing span on the same thread — plus *instant*
point events. The event stream exports to Chrome ``trace_event`` JSON
(viewable in Perfetto / chrome://tracing, where same-thread containment
renders the nesting) via :mod:`paddle_tpu.obs.export`.

Two disciplines inherited from the rest of the runtime:

* **injectable clock** — tests drive a fake counter so span durations are
  exact and nothing sleeps (the utils/retry.py clock discipline);
* **per-thread parent stack** — nesting is attributed by the *recording*
  thread (checkpoint writers and prefetch workers each get their own
  lane), matching how Perfetto lays tracks out.

Unlike ``utils.profiler`` (which drives the XLA device profiler), these
spans are host-side and structured: they survive as plain dicts, so the
JSONL dump, the Chrome exporter and test assertions all read one format.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional


class Tracer:
    """Collects span/instant events; thread-safe; clock injectable.

    ``max_events`` bounds host memory: a long training run records ~5
    events per batch, and an unbounded list would eventually OOM the job
    the tracer is observing. Past the cap new events are dropped and
    tallied in :attr:`dropped` (surfaced in the dump meta) — the trace
    keeps the run's beginning, the metrics registry keeps counting
    everything."""

    def __init__(self, clock: Optional[Callable[[], float]] = None,
                 max_events: int = 250_000, ring_size: int = 0):
        self.clock = clock or time.perf_counter
        self.events: List[Dict[str, Any]] = []
        self.max_events = max_events
        self.dropped = 0
        # RLock, not Lock: the flight recorder's SIGTERM handler runs on
        # the main thread and snapshots this tracer — if the signal lands
        # while that same thread is inside _record's critical section, a
        # plain Lock would deadlock the dying process instead of dumping
        self._lock = threading.RLock()
        self._local = threading.local()
        self._next_id = 1
        self.pid = os.getpid()
        #: flight-recorder tail (obs/flight.py): a bounded deque of the LAST
        #: ring_size events — the main list keeps the run's *beginning* when
        #: it fills, the ring keeps its *end*, which is what a post-mortem
        #: wants. None (the default) costs one is-None check per record.
        self.ring: Optional[Deque[Dict[str, Any]]] = (
            collections.deque(maxlen=ring_size) if ring_size else None)

    # -- internals ----------------------------------------------------------
    def _stack(self) -> List[int]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _new_id(self) -> int:
        with self._lock:
            i = self._next_id
            self._next_id += 1
            return i

    def _record(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            if self.ring is not None:
                # the ring ALWAYS appends (evicting its oldest) — a crash
                # after max_events must still leave the final spans behind
                self.ring.append(ev)
            if len(self.events) >= self.max_events:
                self.dropped += 1
                return
            self.events.append(ev)

    # -- recording ----------------------------------------------------------
    def span(self, name: str, remote: Optional[Dict[str, Any]] = None,
             **attrs) -> "_Span":
        """Context manager recording one interval event on exit.

        ``remote`` is a sanitized wire context (obs/context.py): the span
        event then carries a ``remote`` field naming its cross-process
        parent — the client span the request travelled in."""
        return _Span(self, name, attrs, remote=remote)

    def instant(self, name: str, **attrs) -> None:
        """Point event (the trace analog of a log line)."""
        stack = self._stack()
        self._record({"kind": "instant", "name": name, "ts": self.clock(),
                      "tid": threading.get_ident(), "pid": self.pid,
                      "parent": stack[-1] if stack else None,
                      "args": attrs or {}})

    def enable_ring(self, ring_size: int) -> None:
        """(Re)size the flight-recorder tail; 0 disables it."""
        with self._lock:
            self.ring = (collections.deque(self.ring or (),
                                           maxlen=ring_size)
                         if ring_size else None)

    # -- reading ------------------------------------------------------------
    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [e for e in self.events if e["kind"] == "span"]

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.events)

    def ring_snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.ring) if self.ring is not None else []

    def reset(self) -> None:
        with self._lock:
            self.events.clear()
            if self.ring is not None:
                self.ring.clear()
            self.dropped = 0


class _Span:
    """One live span; records its event when the ``with`` block exits, so a
    span that raises still lands in the trace (with ``error`` noted)."""

    __slots__ = ("_tracer", "name", "attrs", "id", "parent", "remote",
                 "_t0", "_dur")

    def __init__(self, tracer: Tracer, name: str, attrs: Dict[str, Any],
                 remote: Optional[Dict[str, Any]] = None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.id = tracer._new_id()
        self.parent: Optional[int] = None
        self.remote = remote
        self._t0 = 0.0
        self._dur: Optional[float] = None

    def __enter__(self) -> "_Span":
        stack = self._tracer._stack()
        self.parent = stack[-1] if stack else None
        stack.append(self.id)
        self._t0 = self._tracer.clock()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = self._tracer.clock()
        self._dur = t1 - self._t0
        stack = self._tracer._stack()
        # tolerate a foreign unwind (a generator suspended mid-span): pop
        # our own id wherever it sits instead of corrupting siblings
        if stack and stack[-1] == self.id:
            stack.pop()
        elif self.id in stack:
            stack.remove(self.id)
        args = dict(self.attrs)
        if exc_type is not None:
            args["error"] = exc_type.__name__
        ev = {
            "kind": "span", "name": self.name, "ts": self._t0,
            "dur": self._dur, "tid": threading.get_ident(),
            "pid": self._tracer.pid, "id": self.id, "parent": self.parent,
            "args": args}
        if self.remote is not None:
            ev["remote"] = self.remote
        self._tracer._record(ev)
        return False

    @property
    def duration(self) -> float:
        """Elapsed seconds so far; the recorded duration once exited."""
        if self._dur is not None:
            return self._dur
        return self._tracer.clock() - self._t0


class NullSpan:
    """Shared no-op stand-in returned by the module hooks when no session
    is installed — stateless, so ONE instance serves every call site
    (the faults `_PLAN is None` zero-cost discipline)."""

    __slots__ = ()

    def __enter__(self) -> "NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


NULL_SPAN = NullSpan()
