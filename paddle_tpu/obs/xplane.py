"""Device timelines in the obs trace model — ``.xplane.pb`` without xprof.

``jax.profiler`` traces land as XSpace protobufs (``.xplane.pb``): per
plane (``/device:TPU:0``, ``/host:CPU``) a set of lines (``XLA Ops``,
``Steps``, host threads), each a list of timed events whose names resolve
through per-plane metadata tables. The heavyweight consumer is xprof's
``hlo_stats`` (benchmarks/trace_conv_mfu.py used it bench-side only); the
obs plane needs three much smaller things, *off-TPU testable*:

1. **Parse** — a minimal protobuf *wire-format* decoder for exactly the
   XSpace message shapes (no generated proto code, no xprof import), so
   a checked-in fixture drives the whole pipeline in CI
   (tests/fixtures/tiny.xplane.pb).
2. **Merge** — :func:`xplane_dump` converts device planes into the
   standard obs dump shape, so ``paddle_tpu obs export --format=chrome
   --xplane trace.pb`` stitches device op lanes into the same Perfetto
   timeline as the host spans (one process lane per plane,
   ``merge_dumps`` clock alignment via the trace's own epoch).
3. **Attribute** — :func:`site_of` inverts the fluid Executor's
   ``jax.named_scope`` stamps (``b{B}_op{I}_{type}``,
   executor._scope_tag) back to the analysis plane's
   ``block B, op #I (type)`` sites, and :func:`op_totals` aggregates
   per-op self time — the ``paddle_tpu profile`` top-k report.

Timestamps: ``XLine.timestamp_ns`` is wall-clock nanoseconds (TF
``EnvTime``), so device lanes align with obs dumps' ``clock_origin_unix``
to the same epoch; traces whose clocks disagree still render, just
shifted (best-effort, documented in docs/design/observability.md).

The optional xprof path (:func:`hlo_stats_rows`) keeps trace_conv_mfu's
rich per-HLO roofline columns where that toolchain exists.
"""

from __future__ import annotations

import re
import struct
from typing import Any, Dict, Iterable, List, Optional, Tuple

# -- protobuf wire format (decode) ---------------------------------------------
# XSpace schema (tensorflow/core/profiler/protobuf/xplane.proto), fields
# we touch:
#   XSpace  { repeated XPlane planes = 1; }
#   XPlane  { int64 id=1; string name=2; repeated XLine lines=3;
#             map<int64, XEventMetadata> event_metadata=4;
#             map<int64, XStatMetadata> stat_metadata=5;
#             repeated XStat stats=6; }
#   XLine   { int64 id=1; string name=2; int64 timestamp_ns=3;
#             repeated XEvent events=4; int64 duration_ps=9;
#             int64 display_id=10; string display_name=11; }
#   XEvent  { int64 metadata_id=1; int64 offset_ps=2; int64 duration_ps=3;
#             repeated XStat stats=4; }
#   XEventMetadata { int64 id=1; string name=2; string display_name=4; }
#   XStatMetadata  { int64 id=1; string name=2; }
#   XStat   { int64 metadata_id=1; double double_value=2;
#             uint64 uint64_value=3; int64 int64_value=4;
#             string str_value=5; bytes bytes_value=6; uint64 ref_value=7; }


def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes) -> Iterable[Tuple[int, int, Any]]:
    """Yield (field_no, wire_type, raw value) over one message's bytes.
    Unknown wire types terminate the walk (torn tail tolerance — the
    profiler writes the file in one pass, but we never throw on bytes we
    merely don't understand)."""
    i, n = 0, len(buf)
    while i < n:
        try:
            key, i = _varint(buf, i)
        except IndexError:
            return
        field, wt = key >> 3, key & 7
        if wt == 0:                       # varint
            try:
                val, i = _varint(buf, i)
            except IndexError:
                return
        elif wt == 1:                     # 64-bit
            if i + 8 > n:
                return
            val = buf[i:i + 8]
            i += 8
        elif wt == 2:                     # length-delimited
            try:
                ln, i = _varint(buf, i)
            except IndexError:
                return
            if i + ln > n:
                return
            val = buf[i:i + ln]
            i += ln
        elif wt == 5:                     # 32-bit
            if i + 4 > n:
                return
            val = buf[i:i + 4]
            i += 4
        else:
            return
        yield field, wt, val


def _signed(v: int) -> int:
    """proto int64 rides the wire as two's-complement varint."""
    return v - (1 << 64) if v >= 1 << 63 else v


def _decode_stat(buf: bytes) -> Tuple[int, Any]:
    mid, val = 0, None
    for field, wt, raw in _fields(buf):
        if field == 1 and wt == 0:
            mid = raw
        elif field == 2 and wt == 1:
            val = struct.unpack("<d", raw)[0]
        elif field == 3 and wt == 0:
            val = raw
        elif field == 4 and wt == 0:
            val = _signed(raw)
        elif field in (5, 6) and wt == 2:
            try:
                val = raw.decode("utf-8", "replace")
            except Exception:
                val = raw
        elif field == 7 and wt == 0:
            val = ("ref", raw)            # resolved via stat_metadata later
    return mid, val


def _decode_event(buf: bytes) -> Dict[str, Any]:
    ev = {"metadata_id": 0, "offset_ps": 0, "duration_ps": 0, "stats": []}
    for field, wt, raw in _fields(buf):
        if field == 1 and wt == 0:
            ev["metadata_id"] = raw
        elif field == 2 and wt == 0:
            ev["offset_ps"] = _signed(raw)
        elif field == 3 and wt == 0:
            ev["duration_ps"] = _signed(raw)
        elif field == 4 and wt == 2:
            ev["stats"].append(_decode_stat(raw))
    return ev


def _decode_line(buf: bytes) -> Dict[str, Any]:
    line = {"id": 0, "name": "", "display_name": "", "timestamp_ns": 0,
            "events": []}
    for field, wt, raw in _fields(buf):
        if field == 1 and wt == 0:
            line["id"] = raw
        elif field == 2 and wt == 2:
            line["name"] = raw.decode("utf-8", "replace")
        elif field == 11 and wt == 2:
            line["display_name"] = raw.decode("utf-8", "replace")
        elif field == 3 and wt == 0:
            line["timestamp_ns"] = _signed(raw)
        elif field == 4 and wt == 2:
            line["events"].append(_decode_event(raw))
    return line


def _decode_meta_entry(buf: bytes, name_field: int = 2,
                       display_field: Optional[int] = None
                       ) -> Tuple[int, Dict[str, str]]:
    """One map<int64, X*Metadata> entry: {key=1, value=2} wrapping the
    metadata message."""
    key, meta = 0, {"name": "", "display_name": ""}
    for field, wt, raw in _fields(buf):
        if field == 1 and wt == 0:
            key = raw
        elif field == 2 and wt == 2:
            for f2, wt2, raw2 in _fields(raw):
                if f2 == 1 and wt2 == 0 and not key:
                    key = raw2
                elif f2 == name_field and wt2 == 2:
                    meta["name"] = raw2.decode("utf-8", "replace")
                elif display_field and f2 == display_field and wt2 == 2:
                    meta["display_name"] = raw2.decode("utf-8", "replace")
    return key, meta


def _decode_plane(buf: bytes) -> Dict[str, Any]:
    plane = {"id": 0, "name": "", "lines": [], "event_meta": {},
             "stat_meta": {}}
    for field, wt, raw in _fields(buf):
        if field == 1 and wt == 0:
            plane["id"] = raw
        elif field == 2 and wt == 2:
            plane["name"] = raw.decode("utf-8", "replace")
        elif field == 3 and wt == 2:
            plane["lines"].append(_decode_line(raw))
        elif field == 4 and wt == 2:
            k, meta = _decode_meta_entry(raw, name_field=2, display_field=4)
            plane["event_meta"][k] = meta
        elif field == 5 and wt == 2:
            k, meta = _decode_meta_entry(raw, name_field=2)
            plane["stat_meta"][k] = meta["name"]
    return plane


def read_xspace(src) -> Dict[str, Any]:
    """Parse an XSpace: a ``.xplane.pb`` path or raw bytes ->
    ``{"planes": [...]}`` with names/stats resolved per plane."""
    if isinstance(src, (bytes, bytearray)):
        data = bytes(src)
    else:
        with open(src, "rb") as f:
            data = f.read()
    planes = []
    for field, wt, raw in _fields(data):
        if field == 1 and wt == 2:
            planes.append(_decode_plane(raw))
    # resolve event/stat names in place
    for p in planes:
        emeta, smeta = p["event_meta"], p["stat_meta"]
        for line in p["lines"]:
            for ev in line["events"]:
                m = emeta.get(ev["metadata_id"], {})
                ev["name"] = m.get("display_name") or m.get("name") or \
                    f"event#{ev['metadata_id']}"
                ev["long_name"] = m.get("name") or ""
                ev["stats"] = {smeta.get(mid, f"stat#{mid}"): val
                               for mid, val in ev["stats"]}
    return {"planes": planes}


# -- protobuf wire format (encode: fixtures + tests only) ----------------------

def _enc_varint(v: int) -> bytes:
    out = bytearray()
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_field(field: int, wt: int, payload: bytes) -> bytes:
    head = _enc_varint((field << 3) | wt)
    if wt == 2:
        return head + _enc_varint(len(payload)) + payload
    return head + payload


def _enc_str(field: int, s: str) -> bytes:
    return _enc_field(field, 2, s.encode())


def _enc_int(field: int, v: int) -> bytes:
    return _enc_field(field, 0, _enc_varint(v))


def encode_xspace(planes: List[Dict[str, Any]]) -> bytes:
    """Encode a tiny XSpace — the fixture generator for off-TPU tests
    (tests/fixtures/make_xplane_fixture.py writes
    tests/fixtures/tiny.xplane.pb through this). Input shape::

        [{"name": "/device:TPU:0",
          "lines": [{"name": "XLA Ops", "timestamp_ns": ...,
                     "events": [{"name": "fusion.1", "offset_ps": ...,
                                 "duration_ps": ...}, ...]}]}]
    """
    out = b""
    for p in planes:
        names: Dict[str, int] = {}
        body = _enc_str(2, p["name"])
        for line in p.get("lines", ()):
            for ev in line.get("events", ()):
                names.setdefault(ev["name"], len(names) + 1)
        for name, mid in names.items():
            meta = _enc_int(1, mid) + _enc_str(2, name)
            entry = _enc_int(1, mid) + _enc_field(2, 2, meta)
            body += _enc_field(4, 2, entry)
        for li, line in enumerate(p.get("lines", ()), 1):
            lbody = _enc_int(1, li) + _enc_str(2, line["name"]) + \
                _enc_int(3, int(line.get("timestamp_ns", 0)))
            for ev in line.get("events", ()):
                ebody = (_enc_int(1, names[ev["name"]])
                         + _enc_int(2, int(ev.get("offset_ps", 0)))
                         + _enc_int(3, int(ev.get("duration_ps", 0))))
                lbody += _enc_field(4, 2, ebody)
            body += _enc_field(3, 2, lbody)
        out += _enc_field(1, 2, body)
    return out


# -- device extraction ---------------------------------------------------------

#: planes that are chip timelines (vs host threads / task environment)
DEVICE_PLANE_RE = re.compile(r"^/device:")


def device_planes(space: Dict[str, Any],
                  pattern: Optional[str] = None) -> List[Dict[str, Any]]:
    rx = re.compile(pattern) if pattern else DEVICE_PLANE_RE
    return [p for p in space.get("planes", ()) if rx.search(p["name"])]


def plane_events(plane: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flat resolved events of one plane: absolute ns timestamps."""
    out = []
    for line in plane["lines"]:
        t0 = line.get("timestamp_ns", 0)
        lname = line.get("display_name") or line.get("name") or \
            f"line#{line.get('id', 0)}"
        for ev in line["events"]:
            # integer ns throughout: float ns at wall-clock epoch scale
            # (~1.7e18) quantizes to ~256 ns and mis-nests adjacent
            # events in the self-time computation
            out.append({"name": ev["name"], "long_name": ev.get("long_name",
                                                                ""),
                        "line": lname, "line_id": line.get("id", 0),
                        "ts_ns": t0 + ev["offset_ps"] // 1000,
                        "dur_ns": ev["duration_ps"] // 1000,
                        "stats": ev.get("stats", {})})
    out.sort(key=lambda e: e["ts_ns"])
    return out


# -- obs-dump conversion (the chrome-merge bridge) -----------------------------

#: pid block device lanes render under — far above real OS pids so a
#: merged trace can't collide a plane with a host process lane
DEVICE_PID_BASE = 900000


def xplane_dump(space: Dict[str, Any], *, device_only: bool = True,
                base_pid: int = DEVICE_PID_BASE,
                anchor_unix: Optional[float] = None) -> Dict[str, Any]:
    """Convert a parsed XSpace into the standard obs dump shape
    (meta/metrics/events) so ``obs.merge_dumps`` + ``obs.chrome_trace``
    stitch device lanes into the host timeline: one process lane per
    plane, one tid per line, spans named by the resolved op.

    Events are rebased to the trace's earliest timestamp. XLine clocks
    are backend-dependent (wall-clock on some, trace-relative on the CPU
    runtime), so alignment with obs host spans is explicit:
    ``anchor_unix`` sets the dump's ``clock_origin_unix`` — the CLI
    anchors device lanes at the earliest host dump's origin (coarse
    best-effort; the lanes always render, alignment is advisory). With
    no anchor the field is the trace's own epoch second."""
    planes = (device_planes(space) if device_only
              else list(space.get("planes", ())))
    if device_only and not planes:
        # host-only trace (CPU backend): fall back to every plane rather
        # than an empty dump — the lanes still show where time went
        planes = list(space.get("planes", ()))
    events: List[Dict[str, Any]] = []
    processes: Dict[str, str] = {}
    # one plane_events() pass per plane — flatten+sort is the dominant
    # cost on real traces, so compute it once and reuse for both the
    # global t0 scan and the emit loop
    per_plane = [list(plane_events(p)) for p in planes]
    t0_ns = min((ev["ts_ns"] for evs in per_plane for ev in evs),
                default=0.0)
    for pi, plane in enumerate(planes):
        pid = base_pid + pi
        processes[str(pid)] = plane["name"]
        for ev in per_plane[pi]:
            site = site_of(ev)
            args = {"line": ev["line"]}
            if site:
                args["site"] = site
            events.append({"kind": "span", "name": ev["name"],
                           "ts": (ev["ts_ns"] - t0_ns) / 1e9,
                           "dur": ev["dur_ns"] / 1e9,
                           "pid": pid, "tid": int(ev["line_id"]),
                           "args": args})
    origin = anchor_unix if anchor_unix is not None else t0_ns / 1e9
    return {"meta": {"process": "device", "pid": base_pid,
                     "processes": processes,
                     "clock_origin_unix": origin},
            "metrics": [], "events": events}


# -- per-op aggregation + site attribution -------------------------------------

#: the fluid Executor's jax.named_scope stamp (executor._scope_tag):
#: b<block>_op<idx>_<type> — embedded anywhere in the HLO op's name or
#: metadata once XLA has fused/renamed around it
_SITE_RE = re.compile(r"\bb(\d+)_op(\d+)_([A-Za-z0-9_]+?)(?:[./\s]|$)")


def site_of(event: Dict[str, Any]) -> Optional[str]:
    """Attribute one profiled op back to its Program site: invert the
    executor's named-scope stamp to the analysis plane's canonical
    ``block B, op #I (type)`` string (analysis.diagnostics.op_site)."""
    hay = " ".join([event.get("name", ""), event.get("long_name", "")]
                   + [str(v) for v in (event.get("stats") or {}).values()
                      if isinstance(v, str)])
    m = _SITE_RE.search(hay)
    if not m:
        return None
    from ..analysis.diagnostics import op_site
    # the stamp's op-type tail may carry fused suffixes; strip trailing
    # underscores the scope sanitizer introduced
    return op_site(int(m.group(1)), int(m.group(2)),
                   m.group(3).strip("_") or None)


#: the profiler's own session machinery as it appears in host python
#: lines ("$profiler.py:91 start_trace", "$profiler.py:226 trace", ...)
_PROFILER_FRAME_RE = re.compile(
    r"profiler\.py:\d+ \w*trace$|^\$?jax\.profiler")


def _drop_envelopes(evs: List[Dict[str, Any]],
                    frac: float = 0.98) -> List[Dict[str, Any]]:
    """Drop pure envelope events — ones spanning (almost) the whole line
    while containing other events. On the host-plane fallback the frame
    wrapping the trace session (contextmanager __enter__, the profiler
    context itself) inherits every idle second as "self time" and buries
    the report; its children carry the real work and still count."""
    if len(evs) < 2:
        return evs
    lo = min(e["ts_ns"] for e in evs)
    hi = max(e["ts_ns"] + e["dur_ns"] for e in evs)
    extent = hi - lo
    if extent <= 0:
        return evs

    def _is_envelope(e):
        if e["dur_ns"] < frac * extent:
            return False
        # spanning the line is not enough: a single dominant op that
        # contains nothing else is real work, not a session frame
        return any(o is not e
                   and o["ts_ns"] >= e["ts_ns"]
                   and o["ts_ns"] + o["dur_ns"] <= e["ts_ns"] + e["dur_ns"]
                   for o in evs)

    return [e for e in evs if not _is_envelope(e)]


def _self_times(events: List[Dict[str, Any]]) -> List[float]:
    """Self time (ns) per event of ONE line: total duration minus the
    duration of events nested inside it (containment by time range)."""
    order = sorted(range(len(events)),
                   key=lambda i: (events[i]["ts_ns"], -events[i]["dur_ns"]))
    self_ns = [0.0] * len(events)
    stack: List[int] = []
    for i in order:
        ev = events[i]
        while stack and (events[stack[-1]]["ts_ns"]
                         + events[stack[-1]]["dur_ns"]) <= ev["ts_ns"]:
            stack.pop()
        if stack:
            self_ns[stack[-1]] -= ev["dur_ns"]
        self_ns[i] += ev["dur_ns"]
        stack.append(i)
    return self_ns


def op_totals(space: Dict[str, Any], *, device_only: bool = True
              ) -> List[Dict[str, Any]]:
    """Aggregate per-op totals over the (device) planes: one row per op
    name with occurrences, total/self time, and the Program site when a
    named-scope stamp survives in the op's metadata. Sorted by self time
    descending — the ``paddle_tpu profile`` top-k table's rows."""
    planes = (device_planes(space) if device_only
              else list(space.get("planes", ())))
    if device_only and not planes:
        planes = list(space.get("planes", ()))
    agg: Dict[str, Dict[str, Any]] = {}
    for plane in planes:
        lines = plane["lines"]
        # a device plane carries BOTH the op-level line and envelope
        # lines ("XLA Modules", "Steps") covering the same wall time —
        # aggregate the op-level detail only, or every op would count
        # twice inside its module's span
        op_lines = [l for l in lines
                    if (l.get("display_name") or l["name"]) == "XLA Ops"]
        if op_lines:
            lines = op_lines
        for line in lines:
            evs = [{"name": e["name"], "long_name": e.get("long_name", ""),
                    "stats": e.get("stats", {}),
                    # integer ns: see plane_events on float quantization
                    "ts_ns": line.get("timestamp_ns", 0)
                    + e["offset_ps"] // 1000,
                    "dur_ns": e["duration_ps"] // 1000}
                   for e in line["events"]
                   # the profiler's own session envelopes span the whole
                   # trace on the host-plane fallback; their "self time"
                   # is idle, not an op
                   if not _PROFILER_FRAME_RE.search(e["name"])]
            evs = _drop_envelopes(evs)
            selfs = _self_times(evs)
            for ev, sns in zip(evs, selfs):
                row = agg.get(ev["name"])
                if row is None:
                    row = agg[ev["name"]] = {
                        "op": ev["name"], "count": 0, "total_ns": 0.0,
                        "self_ns": 0.0, "site": site_of(ev)}
                elif row["site"] is None:
                    row["site"] = site_of(ev)
                row["count"] += 1
                row["total_ns"] += ev["dur_ns"]
                row["self_ns"] += sns
    return sorted(agg.values(), key=lambda r: -r["self_ns"])


def top_ops_report(space: Dict[str, Any], *, topk: int = 15,
                   steps: int = 1) -> str:
    """The human top-k table ``paddle_tpu profile`` prints: per-op self
    time (amortized over ``steps`` profiled steps), share of device
    time, and the attributed ``block B, op #I (type)`` site."""
    rows = op_totals(space)
    total = sum(r["self_ns"] for r in rows) or 1.0
    lines = [f"{'#':>3} {'self ms/step':>12} {'%dev':>6} {'count':>7}  "
             f"{'op':<44} site",
             "-" * 100]
    for i, r in enumerate(rows[:topk], 1):
        name = r["op"] if len(r["op"]) <= 44 else r["op"][:41] + "..."
        lines.append(
            f"{i:>3} {r['self_ns'] / 1e6 / max(steps, 1):>12.3f} "
            f"{100 * r['self_ns'] / total:>5.1f}% {r['count']:>7}  "
            f"{name:<44} {r['site'] or '-'}")
    dev_ms = total / 1e6 / max(steps, 1)
    lines.append(f"device step: {dev_ms:.3f} ms over {len(rows)} distinct "
                 f"ops ({steps} profiled steps)")
    return "\n".join(lines)


# -- the optional xprof path (rich per-HLO roofline columns) -------------------

def hlo_stats_rows(xplane_path: str) -> Optional[List[Dict[str, Any]]]:
    """xprof's ``hlo_stats`` rows (model_flop_rate, measured_memory_bw,
    bound_by, ...) when that toolchain is importable; None otherwise.
    benchmarks/trace_conv_mfu.py consumes this for its roofline ceilings
    — the raw parser above carries the CI path."""
    try:
        import json
        import os
        os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION",
                              "python")
        from xprof.convert import raw_to_tool_data as r
    except Exception:
        return None
    data, _ = r.xspace_to_tool_data([xplane_path], "hlo_stats", {})
    d = json.loads(data)
    cols = [c["id"] for c in d["cols"]]
    return [dict(zip(cols, [c.get("v") for c in row["c"]]))
            for row in d["rows"]]
