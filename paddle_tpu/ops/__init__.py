from . import (activations, beam_search, conv, crf, ctc, loss, math, metrics,
               detection, nce, norm, pallas_kernels, pool, random, rnn,
               sequence, sparse)

__all__ = ["math", "activations", "loss", "conv", "pool", "norm", "random",
           "rnn", "sequence", "crf", "ctc", "beam_search", "metrics", "sparse",
           "detection", "nce", "pallas_kernels"]
