from . import activations, conv, loss, math, norm, pool, random

__all__ = ["math", "activations", "loss", "conv", "pool", "norm", "random"]
