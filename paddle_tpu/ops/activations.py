"""Activation functions.

The reference registers 17 activations with hand-written forward/backward pairs
(paddle/gserver/activations/ActivationFunction.cpp:97-441) and a gen-2 op family
(paddle/operators/activation_op.cc, ~20 registrations). Here each is a pure function —
JAX autodiff provides the backward, XLA fuses them into adjacent matmuls (the fusion the
reference had to do by hand in hl_* kernels).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.registry import Registry

ACTIVATIONS = Registry("activation")


def _reg(name):
    def deco(fn):
        ACTIVATIONS.register(name, fn)
        return fn
    return deco


@_reg("linear")
@_reg("identity")
def identity(x):
    return x


@_reg("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@_reg("tanh")
def tanh(x):
    return jnp.tanh(x)


@_reg("relu")
def relu(x):
    return jax.nn.relu(x)


@_reg("brelu")
def brelu(x, t_min=0.0, t_max=24.0):
    """bounded relu (ref ActivationFunction.cpp brelu, operators/activation_op.cc BRelu)."""
    return jnp.clip(x, t_min, t_max)


@_reg("relu6")
def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


@_reg("leaky_relu")
def leaky_relu(x, alpha=0.02):
    return jax.nn.leaky_relu(x, alpha)


@_reg("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@_reg("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@_reg("softrelu")
def softrelu(x, threshold=40.0):
    """log(1+exp(x)) with clipping (ref: softrelu in ActivationFunction.cpp)."""
    return jnp.log1p(jnp.exp(jnp.clip(x, -threshold, threshold)))


softplus = ACTIVATIONS.register("softplus", jax.nn.softplus)


@_reg("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@_reg("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    """scaled tanh (ref: stanh)."""
    return scale_b * jnp.tanh(scale_a * x)


@_reg("hard_sigmoid")
def hard_sigmoid(x, slope=0.2, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@_reg("hard_shrink")
def hard_shrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@_reg("soft_shrink")
def soft_shrink(x, lam=0.5):
    return jnp.where(x > lam, x - lam, jnp.where(x < -lam, x + lam, 0.0))


@_reg("thresholded_relu")
def thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


@_reg("abs")
def abs_act(x):
    return jnp.abs(x)


@_reg("square")
def square(x):
    return jnp.square(x)


@_reg("sqrt")
def sqrt(x):
    return jnp.sqrt(x)


@_reg("exponential")
@_reg("exp")
def exponential(x):
    return jnp.exp(x)


@_reg("log")
def log(x):
    return jnp.log(x)


@_reg("reciprocal")
def reciprocal(x):
    return 1.0 / x


@_reg("pow")
def pow_act(x, factor=1.0):
    return jnp.power(x, factor)


@_reg("swish")
def swish(x, beta=1.0):
    return x * jax.nn.sigmoid(beta * x)


@_reg("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@_reg("log_softmax")
def log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def sequence_softmax(x, lengths, axis=1):
    """softmax over valid timesteps of a padded sequence batch [B, T, ...]
    (ref: sequence_softmax in ActivationFunction.cpp / operators/sequence_softmax_op.cc)."""
    from ..core.lod import sequence_mask
    mask = sequence_mask(lengths, x.shape[axis], jnp.bool_)
    shape = [1] * x.ndim
    shape[0], shape[axis] = mask.shape
    mask = mask.reshape(shape)
    neg = jnp.finfo(x.dtype).min
    z = jnp.where(mask, x, neg)
    out = jax.nn.softmax(z, axis=axis)
    return jnp.where(mask, out, 0.0)


def get(name: str):
    return ACTIVATIONS.get(name)
