"""On-device beam search.

The reference runs beam search on CPU with per-step Python callbacks
(gserver/gradientmachines/RecurrentGradientMachine.cpp:1020 ``beamSearch`` over
``Path`` objects; gen-2 operators/beam_search_op.cc + beam_search_decode_op.cc).
That design can't fly on TPU (SURVEY §7 hard parts): here the beam is a fixed-capacity
masked top-k loop inside ``lax.scan``/``while_loop`` — all candidates live in [B, K]
tensors, finished beams are frozen with -inf masking, and the user-callback capability
becomes a ``constraint_fn`` logits-mask hook (token-constraint masking).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


#: Named constraint hooks, so the (JSON-serializable) Program IR can refer
#: to a registered Python masking function by name — the registration role
#: of the reference's BeamSearchControlCallbacks objects
#: (RecurrentGradientMachine.h:106-123), which were likewise attached at
#: generation time rather than stored in the model config.
CONSTRAINTS: dict = {}


def register_constraint(name: str, fn: Optional[Callable] = None):
    """Register ``fn(logits [B, K, V], step) -> logits`` under ``name``.
    Usable as a decorator: ``@register_constraint("no_digits")``."""
    if fn is None:
        def deco(f):
            CONSTRAINTS[name] = f
            return f
        return deco
    CONSTRAINTS[name] = fn
    return fn


def _gather_beams(tree, idx):
    """Reindex the beam axis (1) of every leaf by idx [B, K_new]."""
    def g(x):
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)
    return jax.tree_util.tree_map(g, tree)


def beam_search(init_cell, step_fn: Callable, *, batch_size: int, beam_size: int,
                max_len: int, vocab_size: int, bos_id: int, eos_id: int,
                length_penalty: float = 0.0,
                constraint_fn: Optional[Callable] = None) -> Tuple[jax.Array, jax.Array]:
    """Generic seq2seq beam decode.

    step_fn(cell, tokens [B*K]) -> (log_probs [B*K, V], new_cell) — one decoder step.
    init_cell leaves are [B, ...] and get tiled across beams.
    constraint_fn(logits [B, K, V], step) -> logits — the reference's beam-search
    callback hook (``BeamSearchControlCallbacks``) as a masking function.

    Returns (tokens [B, K, max_len], scores [B, K]) sorted best-first.
    """
    B, K, V = batch_size, beam_size, vocab_size
    neg_inf = jnp.float32(-1e9)

    cell = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x[:, None], (B, K) + x.shape[1:]), init_cell)
    tokens = jnp.full((B, K, max_len), eos_id, jnp.int32)
    cur = jnp.full((B, K), bos_id, jnp.int32)
    # only beam 0 alive initially so identical initial beams don't duplicate
    log_probs = jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.full((B, K - 1), neg_inf)], axis=1)
    finished = jnp.zeros((B, K), jnp.bool_)

    def body(state, t):
        tokens, cur, log_probs, finished, cell = state
        flat_cell = jax.tree_util.tree_map(
            lambda x: x.reshape((B * K,) + x.shape[2:]), cell)
        logp, new_flat_cell = step_fn(flat_cell, cur.reshape(B * K))
        logp = logp.reshape(B, K, V)
        new_cell = jax.tree_util.tree_map(
            lambda x: x.reshape((B, K) + x.shape[1:]), new_flat_cell)
        if constraint_fn is not None:
            logp = constraint_fn(logp, t)

        # finished beams: only allow EOS with prob 1 (score frozen)
        eos_only = jnp.full((V,), neg_inf).at[eos_id].set(0.0)
        logp = jnp.where(finished[..., None], eos_only[None, None, :], logp)

        cand = log_probs[..., None] + logp                      # [B, K, V]
        flat = cand.reshape(B, K * V)
        top_scores, top_idx = lax.top_k(flat, K)                # [B, K]
        beam_idx = top_idx // V
        tok_idx = (top_idx % V).astype(jnp.int32)

        tokens = _gather_beams(tokens, beam_idx)
        tokens = tokens.at[:, :, t].set(tok_idx)
        new_cell = _gather_beams(new_cell, beam_idx)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1) | (tok_idx == eos_id)
        return (tokens, tok_idx, top_scores, finished, new_cell), None

    state = (tokens, cur, log_probs, finished, cell)
    (tokens, cur, log_probs, finished, cell), _ = lax.scan(
        body, state, jnp.arange(max_len))

    if length_penalty > 0.0:
        # GNMT-style normalization over emitted lengths
        lens = jnp.sum((tokens != eos_id).astype(jnp.float32), axis=-1) + 1.0
        norm = jnp.power((5.0 + lens) / 6.0, length_penalty)
        scored = log_probs / norm
    else:
        scored = log_probs
    order = jnp.argsort(-scored, axis=1)
    tokens = _gather_beams(tokens, order)
    scored = jnp.take_along_axis(scored, order, axis=1)
    return tokens, scored


def greedy_search(init_cell, step_fn: Callable, *, batch_size: int, max_len: int,
                  bos_id: int, eos_id: int) -> Tuple[jax.Array, jax.Array]:
    """One-way (greedy) generation — ref RecurrentGradientMachine::oneWaySearch:1037."""
    B = batch_size

    def body(state, t):
        cur, done, cell, score = state
        logp, cell = step_fn(cell, cur)
        nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)
        step_score = jnp.max(logp, axis=-1)
        nxt = jnp.where(done, eos_id, nxt)
        score = score + jnp.where(done, 0.0, step_score)
        done = done | (nxt == eos_id)
        return (nxt, done, cell, score), nxt

    cur = jnp.full((B,), bos_id, jnp.int32)
    done = jnp.zeros((B,), jnp.bool_)
    score = jnp.zeros((B,), jnp.float32)
    (_, _, _, score), toks = lax.scan(body, (cur, done, init_cell, score),
                                      jnp.arange(max_len))
    return jnp.swapaxes(toks, 0, 1), score
