"""Convolution ops.

Replaces the reference's conv stack — im2col+gemm (paddle/function/GemmConvOp.cpp,
operators/conv_op.cc), cuDNN conv (gserver/layers/CudnnConvLayer.cpp,
operators/conv_cudnn_op.cc), depthwise (function/DepthwiseConvOp.cpp), transpose conv
(operators/conv_transpose_op.cc) — with ``lax.conv_general_dilated``, which XLA lowers
straight onto the MXU. Layout is NHWC (TPU-native; the reference is NCHW — the Python
layer API accepts either and we transpose at the boundary).
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

IntOr2 = Union[int, Sequence[int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    return tuple(v)  # type: ignore


def _padding(pad: Union[str, IntOr2]) -> Union[str, Sequence[Tuple[int, int]]]:
    if isinstance(pad, str):
        return pad.upper()
    p = _pair(pad)
    return [(p[0], p[0]), (p[1], p[1])]


def conv2d(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
           padding: Union[str, IntOr2] = 0, dilation: IntOr2 = 1,
           groups: int = 1) -> jax.Array:
    """NHWC conv. w: [kh, kw, cin/groups, cout]. (ref: operators/conv_op.cc conv2d)."""
    return lax.conv_general_dilated(
        x, w,
        window_strides=_pair(stride),
        padding=_padding(padding),
        rhs_dilation=_pair(dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
    )


def conv7s2_space_to_depth(x: jax.Array, w7: jax.Array) -> jax.Array:
    """The 7x7/stride-2/pad-3 stem conv computed via an EXACT space-to-depth
    rewrite (MLPerf-style conv0 transform).

    A direct 7x7 conv over few input channels (ImageNet's 3) feeds the MXU a
    contraction depth of 3 — measured ~9 TF/s on v5e, 4.6% of peak
    (docs/design/conv_mfu.md). Over a 2x2 space-to-depth view of x the same
    convolution is a 4x4/s1 conv with contraction depth 16*cin: with a
    leading zero pad (tap i' = i+1 in 0..7) and i' = 2a+p, out[h] =
    sum x[2(h+a-2)+p] — a 4-cell window over the S2D grid. The kernel is
    the SAME [7,7,cin,cout] parameter regrouped at trace time, so
    checkpoints and init are unchanged; equivalence is tested to f32 noise.
    Requires even H, W (falls back to callers' direct conv otherwise).
    """
    B, H, W, C = x.shape
    assert H % 2 == 0 and W % 2 == 0 and w7.shape[:2] == (7, 7)
    cout = w7.shape[-1]
    xp = jnp.pad(x, ((0, 0), (4, 4), (4, 4), (0, 0)))
    hc, wc = (H + 8) // 2, (W + 8) // 2
    x2 = xp.reshape(B, hc, 2, wc, 2, C).transpose(
        0, 1, 3, 2, 4, 5).reshape(B, hc, wc, 4 * C)
    w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
    w2 = w8.reshape(4, 2, 4, 2, C, cout).transpose(
        0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * C, cout)
    out = conv2d(x2, w2, stride=1, padding=0)
    return out[:, :H // 2, :W // 2]


def conv7s2(x: jax.Array, w7: jax.Array) -> jax.Array:
    """7x7/stride-2/pad-3 conv, routed through the space-to-depth rewrite
    when H and W are even (its precondition), direct conv otherwise. Owns
    the parity dispatch so every stem call site stays a one-liner; callers
    apply their own bias/norm/activation."""
    if x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
        return conv7s2_space_to_depth(x, w7)
    return conv2d(x, w7, stride=2, padding=3)


def depthwise_conv2d(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
                     padding: Union[str, IntOr2] = 0) -> jax.Array:
    """w: [kh, kw, 1, channels*mult] with groups=channels
    (ref: function/DepthwiseConvOp.cpp)."""
    c = x.shape[-1]
    return conv2d(x, w, stride=stride, padding=padding, groups=c)


def conv2d_transpose(x: jax.Array, w: jax.Array, *, stride: IntOr2 = 1,
                     padding: Union[str, IntOr2] = 0) -> jax.Array:
    """Gradient-of-conv as forward op (ref: operators/conv_transpose_op.cc).

    w: [kh, kw, cin, cout] — HWIO w.r.t. the forward (upsampling) direction."""
    s = _pair(stride)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _pair(padding)
        kh, kw = w.shape[0], w.shape[1]
        # conv_transpose padding: SAME-style inversion of forward conv padding
        pad = [(kh - 1 - p[0], kh - 1 - p[0]), (kw - 1 - p[1], kw - 1 - p[1])]
    return lax.conv_transpose(x, w, strides=s, padding=pad,
                              dimension_numbers=("NHWC", "HWIO", "NHWC"))


def conv3d(x: jax.Array, w: jax.Array, *, stride=1, padding=0, dilation=1,
           groups: int = 1) -> jax.Array:
    """NDHWC 3-D conv (ref: operators/conv3d via conv_op.cc)."""
    def _t3(v):
        return (v, v, v) if isinstance(v, int) else tuple(v)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _t3(padding)
        pad = [(pi, pi) for pi in p]
    return lax.conv_general_dilated(
        x, w, window_strides=_t3(stride), padding=pad, rhs_dilation=_t3(dilation),
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"), feature_group_count=groups)


def row_conv(x: jax.Array, w: jax.Array) -> jax.Array:
    """Lookahead row convolution over time (ref: function/RowConvOp.cpp,
    operators/row_conv_op.cc). x: [B, T, D], w: [context, D]."""
    context = w.shape[0]
    xpad = jnp.pad(x, ((0, 0), (0, context - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for i in range(context):
        out = out + xpad[:, i:i + x.shape[1], :] * w[i][None, None, :]
    return out


def im2col(x: jax.Array, kernel: IntOr2, stride: IntOr2 = 1,
           padding: IntOr2 = 0) -> jax.Array:
    """Patch extraction (ref: function/Im2Col.h, operators/math/im2col.cc) — exposed for
    block_expand-style layers. x: [B, H, W, C] -> [B, oh, ow, kh*kw*C]."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    B, H, W, C = x.shape
    oh = (H + 2 * ph - kh) // sh + 1
    ow = (W + 2 * pw - kw) // sw + 1
    patches = lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=(sh, sw),
        padding=[(ph, ph), (pw, pw)], dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # conv_general_dilated_patches emits features channel-major (C, kh, kw);
    # reorder to the documented patch-major (kh, kw, C) layout.
    patches = patches.reshape(B, oh, ow, C, kh, kw)
    patches = jnp.transpose(patches, (0, 1, 2, 4, 5, 3))
    return patches.reshape(B, oh, ow, kh * kw * C)


def bilinear_interp(x: jax.Array, out_h: int, out_w: int) -> jax.Array:
    """Bilinear resize NHWC (ref: operators/bilinear_interp_op.cc,
    gserver BilinearInterpLayer.cpp)."""
    B, H, W, C = x.shape
    ry = (H - 1) / max(out_h - 1, 1)
    rx = (W - 1) / max(out_w - 1, 1)
    ys = jnp.arange(out_h) * ry
    xs = jnp.arange(out_w) * rx
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = (ys - y0)[None, :, None, None]
    wx = (xs - x0)[None, None, :, None]
    g = lambda yi, xi: x[:, yi][:, :, xi]
    top = g(y0, x0) * (1 - wx) + g(y0, x1) * wx
    bot = g(y1, x0) * (1 - wx) + g(y1, x1) * wx
    return top * (1 - wy) + bot * wy


def maxout(x: jax.Array, groups: int) -> jax.Array:
    """Maxout over channel groups NHWC (ref: operators/maxout_op.cc,
    gserver MaxOutLayer.cpp): C -> C/groups channels."""
    B, H, W, C = x.shape
    return jnp.max(x.reshape(B, H, W, C // groups, groups), axis=-1)
