"""Linear-chain CRF: forward (log-likelihood) and Viterbi decoding.

Replaces the reference's CPU-only CRF (gserver/layers/LinearChainCRF.cpp, CRFLayer.cpp,
CRFDecodingLayer.cpp; gen-2 operators/linear_chain_crf_op.cc, crf_decoding_op.cc) with
masked ``lax.scan`` dynamic programs that run on-device (the reference keeps CRF on
CPU — SURVEY §7 lists it as a Pallas candidate; the scan form already fuses well).

Transition parameterization follows the reference (LinearChainCRF.cpp): a
[num_tags + 2, num_tags] matrix whose row 0 holds start weights a_j, row 1 holds end
weights b_i, and rows 2.. hold pairwise w[i][j] (i prev, j next). We keep
(start [N], end [N], trans [N, N]) as separate arrays — equivalent content.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.lod import sequence_mask


def crf_log_norm(emissions: jax.Array, lengths: jax.Array, start: jax.Array,
                 end: jax.Array, trans: jax.Array) -> jax.Array:
    """log Z per sequence. emissions: [B, T, N]."""
    B, T, N = emissions.shape
    mask = sequence_mask(lengths, T, emissions.dtype)
    alpha0 = start[None, :] + emissions[:, 0, :]

    def step(alpha, inp):
        e_t, m_t = inp
        # [B, N_prev, 1] + [N_prev, N_next] -> logsumexp over prev
        scores = alpha[:, :, None] + trans[None, :, :] + e_t[:, None, :]
        new = jax.scipy.special.logsumexp(scores, axis=1)
        m = m_t[:, None]
        return m * new + (1.0 - m) * alpha, None

    es = jnp.swapaxes(emissions, 0, 1)[1:]       # [T-1, B, N]
    ms = jnp.swapaxes(mask, 0, 1)[1:]            # [T-1, B]
    alpha, _ = lax.scan(step, alpha0, (es, ms))
    return jax.scipy.special.logsumexp(alpha + end[None, :], axis=-1)


def crf_score(emissions: jax.Array, tags: jax.Array, lengths: jax.Array,
              start: jax.Array, end: jax.Array, trans: jax.Array) -> jax.Array:
    """Score of a given tag path per sequence. tags: [B, T] int."""
    B, T, N = emissions.shape
    mask = sequence_mask(lengths, T, emissions.dtype)
    e = jnp.take_along_axis(emissions, tags[..., None], axis=-1)[..., 0]  # [B, T]
    emit = jnp.sum(e * mask, axis=1)
    s = start[tags[:, 0]]
    pair = trans[tags[:, :-1], tags[:, 1:]]       # [B, T-1]
    pair = jnp.sum(pair * mask[:, 1:], axis=1)
    last_idx = jnp.maximum(lengths - 1, 0)
    last_tag = jnp.take_along_axis(tags, last_idx[:, None], axis=1)[:, 0]
    return s + emit + pair + end[last_tag]


def crf_loss(emissions, tags, lengths, start, end, trans) -> jax.Array:
    """Negative log-likelihood per sequence (ref: CRFLayer forward cost)."""
    return (crf_log_norm(emissions, lengths, start, end, trans)
            - crf_score(emissions, tags, lengths, start, end, trans))


def crf_decode(emissions: jax.Array, lengths: jax.Array, start: jax.Array,
               end: jax.Array, trans: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Viterbi decode -> (best_tags [B, T], best_score [B])
    (ref: CRFDecodingLayer.cpp, operators/crf_decoding_op.cc)."""
    B, T, N = emissions.shape
    mask = sequence_mask(lengths, T, emissions.dtype)
    delta0 = start[None, :] + emissions[:, 0, :]

    def fwd(delta, inp):
        e_t, m_t = inp
        scores = delta[:, :, None] + trans[None, :, :] + e_t[:, None, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, N]
        new = jnp.max(scores, axis=1)
        m = m_t[:, None]
        delta_new = m * new + (1.0 - m) * delta
        # on masked steps, backpointer = identity so backtrace passes through
        ident = jnp.broadcast_to(jnp.arange(N)[None, :], (B, N))
        bp = jnp.where(m_t[:, None] > 0, best_prev, ident)
        return delta_new, bp

    es = jnp.swapaxes(emissions, 0, 1)[1:]
    ms = jnp.swapaxes(mask, 0, 1)[1:]
    delta, bps = lax.scan(fwd, delta0, (es, ms))          # bps: [T-1, B, N]
    final = delta + end[None, :]
    best_last = jnp.argmax(final, axis=-1)                 # [B]
    best_score = jnp.max(final, axis=-1)

    def back(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    # processing bps[t] with carry tag_{t+1} yields prev=tag_t and emits tag_{t+1};
    # so ys = tags[1:] and the final carry is tag_0.
    tag0, tags_tail = lax.scan(back, best_last, bps, reverse=True)
    tags = jnp.concatenate([tag0[None, :], tags_tail], axis=0)  # [T, B]
    tags = jnp.swapaxes(tags, 0, 1)
    return tags * mask.astype(tags.dtype), best_score
