"""CTC loss — masked log-space forward algorithm.

Replaces the reference's warp-ctc integration (gserver/layers/WarpCTCLayer.cpp,
CTCLayer.cpp, vendored warpctc) with an on-device ``lax.scan`` dynamic program: the
extended label sequence (blanks interleaved) lives in a fixed [B, 2*L+1] tensor,
per-step transitions are branch-free selects, and variable input/label lengths are
masked — no CPU round-trip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG = -1e30


def ctc_loss(log_probs: jax.Array, input_lengths: jax.Array, labels: jax.Array,
             label_lengths: jax.Array, blank: int = 0) -> jax.Array:
    """Per-sequence CTC negative log-likelihood.

    log_probs: [B, T, V] log-softmax outputs; labels: [B, L] (padded with any value).
    """
    B, T, V = log_probs.shape
    L = labels.shape[1]
    S = 2 * L + 1

    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((B, S), blank, labels.dtype)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(S)[None, :] < (2 * label_lengths + 1)[:, None]

    # can we skip from s-2 to s? only when ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, ext.dtype), ext[:, :-2]], axis=1)
    can_skip = (jnp.arange(S)[None, :] % 2 == 1) & (ext != ext_m2)

    emit = jnp.take_along_axis(
        log_probs[:, :, :], ext[:, None, :].astype(jnp.int32), axis=2)  # [B, T, S]

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(emit[:, 0, 0])
    has_label = label_lengths > 0
    alpha0 = alpha0.at[:, 1].set(jnp.where(has_label, emit[:, 0, 1], NEG))
    alpha0 = jnp.where(ext_valid, alpha0, NEG)

    def lse(a, b):
        m = jnp.maximum(a, b)
        return m + jnp.log(jnp.exp(a - m) + jnp.exp(b - m))

    def step(alpha, inp):
        emit_t, t = inp
        a_m1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        a_m2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        merged = lse(alpha, a_m1)
        merged = jnp.where(can_skip, lse(merged, a_m2), merged)
        new = merged + emit_t
        new = jnp.where(ext_valid, new, NEG)
        # freeze once past input length
        active = (t < input_lengths)[:, None]
        return jnp.where(active, new, alpha), None

    emits = jnp.swapaxes(emit, 0, 1)[1:]  # [T-1, B, S]
    ts = jnp.arange(1, T)
    alpha, _ = lax.scan(step, alpha0, (emits, ts))

    # final prob: alpha at positions 2*label_len and 2*label_len - 1
    idx_last = (2 * label_lengths).astype(jnp.int32)
    idx_prev = jnp.maximum(idx_last - 1, 0)
    a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
    ll = lse(a_last, jnp.where(label_lengths > 0, a_prev, NEG))
    return -ll


def ctc_greedy_decode(log_probs: jax.Array, input_lengths: jax.Array,
                      blank: int = 0):
    """Best-path decode: argmax per step, collapse repeats, drop blanks.

    Returns (tokens [B, T] padded with blank at tail, lengths [B])."""
    B, T, V = log_probs.shape
    path = jnp.argmax(log_probs, axis=-1)  # [B, T]
    from ..core.lod import sequence_mask
    valid = sequence_mask(input_lengths, T, jnp.bool_)
    prev = jnp.concatenate([jnp.full((B, 1), -1, path.dtype), path[:, :-1]], axis=1)
    keep = valid & (path != blank) & (path != prev)
    # stable compaction: order = cumsum of keep
    order = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    out = jnp.full((B, T), blank, path.dtype)
    # scatter kept tokens to their compacted slots
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    safe_order = jnp.where(keep, order, T - 1)
    out = out.at[rows, safe_order].set(jnp.where(keep, path, blank).astype(path.dtype))
    lengths = jnp.sum(keep.astype(jnp.int32), axis=1)
    # positions >= length reset to blank (the scatter above may have left junk at T-1)
    pos = jnp.arange(T)[None, :]
    out = jnp.where(pos < lengths[:, None], out, blank)
    return out, lengths
