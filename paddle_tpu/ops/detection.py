"""Object-detection ops — the SSD suite.

Re-provisions the reference's detection layers/ops (gserver/layers/
PriorBox.cpp, MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp + detection
utils; gen-2 operators/ equivalents) TPU-style: everything fixed-shape and
masked; NMS is an O(K^2) masked suppression over a static top-K candidate set
(data-dependent loops won't compile — SURVEY.md §7 hard parts).

Boxes are [xmin, ymin, xmax, ymax] normalized to [0, 1] throughout.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


# ------------------------------------------------------------------ priors ---

def prior_box(feature_hw: Tuple[int, int], image_hw: Tuple[int, int],
              min_size: float, max_size: Optional[float] = None,
              aspect_ratios: Sequence[float] = (2.0,),
              flip: bool = True, clip: bool = True,
              variance: Sequence[float] = (0.1, 0.1, 0.2, 0.2)
              ) -> Tuple[jax.Array, jax.Array]:
    """SSD prior (anchor) boxes for one feature map (PriorBox.cpp semantics).

    Returns (boxes [H*W*P, 4], variances [H*W*P, 4]); P priors per cell:
    min + (sqrt(min*max) if max_size) + one per aspect ratio (x2 if flip).
    """
    H, W = feature_hw
    img_h, img_w = image_hw
    sizes_w, sizes_h = [], []
    s = min_size
    sizes_w.append(s / img_w)
    sizes_h.append(s / img_h)
    if max_size is not None:
        sp = (min_size * max_size) ** 0.5
        sizes_w.append(sp / img_w)
        sizes_h.append(sp / img_h)
    for ar in aspect_ratios:
        for a in ((ar, 1.0 / ar) if flip else (ar,)):
            sizes_w.append(min_size * (a ** 0.5) / img_w)
            sizes_h.append(min_size / (a ** 0.5) / img_h)
    P = len(sizes_w)
    cy, cx = jnp.meshgrid(
        (jnp.arange(H) + 0.5) / H, (jnp.arange(W) + 0.5) / W, indexing="ij")
    cx = jnp.broadcast_to(cx[..., None], (H, W, P))
    cy = jnp.broadcast_to(cy[..., None], (H, W, P))
    w2 = jnp.asarray(sizes_w) / 2.0
    h2 = jnp.asarray(sizes_h) / 2.0
    boxes = jnp.stack([cx - w2, cy - h2, cx + w2, cy + h2], axis=-1)
    boxes = boxes.reshape(-1, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    variances = jnp.broadcast_to(jnp.asarray(variance), boxes.shape)
    return boxes, variances


# ------------------------------------------------------------------- iou ----

def iou_matrix(a: jax.Array, b: jax.Array) -> jax.Array:
    """Pairwise IoU: a [N, 4], b [M, 4] -> [N, M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0.0) * jnp.clip(a[:, 3] - a[:, 1], 0.0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0.0) * jnp.clip(b[:, 3] - b[:, 1], 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


# --------------------------------------------------------------- encoding ---

def encode_boxes(gt: jax.Array, priors: jax.Array,
                 variances: jax.Array) -> jax.Array:
    """Ground truth -> regression targets relative to priors (SSD encoding)."""
    p_wh = jnp.maximum(priors[:, 2:] - priors[:, :2], 1e-8)
    p_c = (priors[:, :2] + priors[:, 2:]) / 2
    g_wh = jnp.maximum(gt[:, 2:] - gt[:, :2], 1e-8)
    g_c = (gt[:, :2] + gt[:, 2:]) / 2
    d_c = (g_c - p_c) / (p_wh * variances[:, :2])
    d_wh = jnp.log(g_wh / p_wh) / variances[:, 2:]
    return jnp.concatenate([d_c, d_wh], axis=-1)


def decode_boxes(loc: jax.Array, priors: jax.Array,
                 variances: jax.Array) -> jax.Array:
    """Regression output -> boxes (DetectionOutputLayer decode)."""
    p_wh = priors[:, 2:] - priors[:, :2]
    p_c = (priors[:, :2] + priors[:, 2:]) / 2
    c = loc[..., :2] * variances[:, :2] * p_wh + p_c
    wh = jnp.exp(loc[..., 2:] * variances[:, 2:]) * p_wh
    return jnp.concatenate([c - wh / 2, c + wh / 2], axis=-1)


# --------------------------------------------------------------- matching ---

def match_priors(priors: jax.Array, gt_boxes: jax.Array, gt_mask: jax.Array,
                 threshold: float = 0.5) -> Tuple[jax.Array, jax.Array]:
    """Match each prior to a gt box (MultiBoxLossLayer matching).

    gt_boxes [G, 4] padded, gt_mask [G] 1.0 for real boxes.
    Returns (matched_gt_idx [N], positive_mask [N]): best-gt per prior above
    threshold, with each gt's single best prior force-matched.
    """
    iou = iou_matrix(priors, gt_boxes) * gt_mask[None, :]
    best_gt = jnp.argmax(iou, axis=1)
    best_iou = jnp.max(iou, axis=1)
    pos = best_iou >= threshold
    # force-match: the best prior for each (real) gt becomes positive for it
    best_prior = jnp.argmax(iou, axis=0)                    # [G]
    N, G = iou.shape
    # padded gts (iou all zero) argmax to prior 0 — route them out of bounds
    # so mode="drop" discards them instead of racing a real match at index 0
    # (XLA scatter order with duplicate indices is unspecified)
    best_prior = jnp.where(gt_mask > 0, best_prior, N)
    forced = jnp.zeros((N,), jnp.int32).at[best_prior].set(
        jnp.arange(G, dtype=jnp.int32), mode="drop")
    force_mask = jnp.zeros((N,), bool).at[best_prior].set(
        True, mode="drop")
    matched = jnp.where(force_mask, forced, best_gt)
    pos = pos | force_mask
    return matched, pos


def multibox_loss(loc_pred: jax.Array, conf_logits: jax.Array,
                  priors: jax.Array, variances: jax.Array,
                  gt_boxes: jax.Array, gt_labels: jax.Array,
                  gt_mask: jax.Array, *, neg_pos_ratio: float = 3.0,
                  overlap_threshold: float = 0.5,
                  background_id: int = 0) -> jax.Array:
    """SSD loss for ONE image (vmap over the batch):
    smooth-L1 on matched locs + softmax CE with hard-negative mining
    (MultiBoxLossLayer.cpp semantics). conf_logits [N, C]; gt_labels [G]
    (0 = background id reserved).
    """
    from .loss import smooth_l1
    matched, pos = match_priors(priors, gt_boxes, gt_mask, overlap_threshold)
    n_pos = jnp.sum(pos.astype(jnp.float32))

    # localization: smooth L1 over positive priors. Targets for negatives are
    # replaced by the prediction itself (zero loss) BEFORE the loss — padded
    # gt slots hold arbitrary bytes and NaN * 0 would still poison the sum.
    targets = encode_boxes(gt_boxes[matched], priors, variances)
    targets = jnp.where(pos[:, None], targets,
                        jax.lax.stop_gradient(loc_pred))
    loc_l = jnp.sum(smooth_l1(loc_pred, targets), axis=-1)
    loc_loss = jnp.sum(loc_l * pos) / jnp.maximum(n_pos, 1.0)

    # classification with hard negative mining
    labels = jnp.where(pos, gt_labels[matched], background_id)
    logp = jax.nn.log_softmax(conf_logits)
    ce = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    neg_ce = jnp.where(pos, -jnp.inf, ce)                  # candidates: negatives
    n_neg = jnp.minimum(neg_pos_ratio * jnp.maximum(n_pos, 1.0),
                        jnp.sum(1.0 - pos.astype(jnp.float32)))
    # take the hardest n_neg negatives via rank threshold (static shape)
    order = jnp.argsort(-neg_ce)
    rank = jnp.zeros_like(order).at[order].set(jnp.arange(order.shape[0]))
    neg = (~pos) & (rank < n_neg)
    conf_loss = jnp.sum(ce * (pos | neg)) / jnp.maximum(n_pos, 1.0)
    return loc_loss + conf_loss


# ------------------------------------------------------------------- nms ----

def nms(boxes: jax.Array, scores: jax.Array, *, iou_threshold: float = 0.45,
        score_threshold: float = 0.01, top_k: int = 100
        ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Masked fixed-shape NMS (DetectionOutputLayer::applyNMSFast analog).

    boxes [N, 4], scores [N] -> (boxes [top_k, 4], scores [top_k],
    valid [top_k]) sorted by score; suppressed/empty slots have valid=0.
    """
    N = scores.shape[0]
    k = min(top_k, N)
    sc, idx = jax.lax.top_k(jnp.where(scores >= score_threshold, scores,
                                      -jnp.inf), k)
    bx = boxes[idx]
    iou = iou_matrix(bx, bx)

    def body(i, keep):
        # drop i if a higher-scored kept candidate overlaps too much
        sup = (iou[:, i] > iou_threshold) & keep & (jnp.arange(k) < i)
        keep_i = keep[i] & ~jnp.any(sup)
        return keep.at[i].set(keep_i)

    keep0 = sc > -jnp.inf
    keep = jax.lax.fori_loop(0, k, body, keep0)
    return bx, jnp.where(keep, sc, 0.0), keep.astype(jnp.float32)


def detection_output(loc_pred: jax.Array, conf_logits: jax.Array,
                     priors: jax.Array, variances: jax.Array, *,
                     num_classes: int, background_id: int = 0,
                     iou_threshold: float = 0.45,
                     score_threshold: float = 0.01, keep_top_k: int = 100):
    """Decode + per-class NMS for ONE image (DetectionOutputLayer.cpp).

    Returns (boxes [C-1, K, 4], scores [C-1, K], valid [C-1, K]) for the
    non-background classes (vmap over batch outside).
    """
    boxes = decode_boxes(loc_pred, priors, variances)
    probs = jax.nn.softmax(conf_logits, axis=-1)
    out_b, out_s, out_v = [], [], []
    for c in range(num_classes):
        if c == background_id:
            continue
        b, s, v = nms(boxes, probs[:, c], iou_threshold=iou_threshold,
                      score_threshold=score_threshold, top_k=keep_top_k)
        out_b.append(b)
        out_s.append(s)
        out_v.append(v)
    return (jnp.stack(out_b), jnp.stack(out_s), jnp.stack(out_v))
