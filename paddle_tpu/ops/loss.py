"""Loss functions.

Covers the reference's cost-layer zoo (paddle/gserver/layers/CostLayer.cpp — 20+ losses)
and the gen-2 loss operators (cross_entropy_op.cc, softmax_with_cross_entropy_op.cc,
huber_loss_op.cc, rank_loss_op.cc, margin_rank_loss_op.cc, smooth_l1_loss_op.cc,
squared_l2_loss_op.cc, modified_huber_loss_op.cc, sigmoid_cross_entropy_with_logits_op.cc,
hinge_loss_op.cc, log_loss_op.cc). All return per-example losses [B] (or [B, 1]) like
the reference; reduce with ``mean`` for the scalar cost.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _one_hot_like(labels, logits):
    return jax.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)


def cross_entropy(probs: jax.Array, label: jax.Array, soft_label: bool = False,
                  eps: float = 1e-8) -> jax.Array:
    """-log p[label] over probabilities (ref: operators/cross_entropy_op.cc)."""
    if soft_label:
        return -jnp.sum(label * jnp.log(probs + eps), axis=-1)
    p = jnp.take_along_axis(probs, label[..., None].astype(jnp.int32), axis=-1)
    return -jnp.log(p[..., 0] + eps)


def softmax_with_cross_entropy(logits: jax.Array, label: jax.Array,
                               soft_label: bool = False) -> jax.Array:
    """Fused, numerically-stable version (ref: softmax_with_cross_entropy_op.cc)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    if soft_label:
        return -jnp.sum(label * logp, axis=-1)
    lp = jnp.take_along_axis(logp, label[..., None].astype(jnp.int32), axis=-1)
    return -lp[..., 0]


def sigmoid_cross_entropy_with_logits(x: jax.Array, label: jax.Array) -> jax.Array:
    """ref: sigmoid_cross_entropy_with_logits_op.cc (elementwise)."""
    return jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))


def square_error(x: jax.Array, label: jax.Array) -> jax.Array:
    """Sum-of-squares cost (ref: CostLayer.cpp SumOfSquaresCostLayer,
    operators/squared_l2_distance_op.cc)."""
    d = x - label
    return 0.5 * jnp.sum(jnp.square(d).reshape(d.shape[0], -1), axis=-1)


def smooth_l1(x: jax.Array, label: jax.Array, sigma: float = 1.0) -> jax.Array:
    """ref: smooth_l1_loss_op.cc."""
    s2 = sigma * sigma
    d = jnp.abs(x - label)
    per = jnp.where(d < 1.0 / s2, 0.5 * s2 * jnp.square(d), d - 0.5 / s2)
    return jnp.sum(per.reshape(per.shape[0], -1), axis=-1)


def huber_regression(x: jax.Array, label: jax.Array, delta: float = 1.0) -> jax.Array:
    """ref: huber_loss_op.cc / CostLayer.cpp HuberRegressionLoss."""
    d = jnp.abs(x - label)
    per = jnp.where(d <= delta, 0.5 * jnp.square(d), delta * (d - 0.5 * delta))
    return jnp.sum(per.reshape(per.shape[0], -1), axis=-1)


def huber_classification(x: jax.Array, label: jax.Array) -> jax.Array:
    """Two-class huber (ref: CostLayer.cpp HuberTwoClassification); label in {0,1}."""
    y = 2.0 * label - 1.0
    z = x[..., 0] if x.ndim > 1 else x
    a = y * z
    return jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))


def modified_huber(x: jax.Array, label: jax.Array) -> jax.Array:
    """ref: modified_huber_loss_op.cc; label in {0,1}."""
    y = 2.0 * label - 1.0
    a = y * (x[..., 0] if x.ndim > 1 else x)
    return jnp.where(a < -1.0, -4.0 * a, jnp.square(jnp.maximum(1.0 - a, 0.0)))


def hinge(x: jax.Array, label: jax.Array) -> jax.Array:
    """ref: hinge_loss_op.cc; label in {0,1}."""
    y = 2.0 * label - 1.0
    return jnp.maximum(0.0, 1.0 - y * (x[..., 0] if x.ndim > 1 else x))


def log_loss(prob: jax.Array, label: jax.Array, eps: float = 1e-7) -> jax.Array:
    """ref: log_loss_op.cc."""
    p = prob[..., 0] if prob.ndim > 1 else prob
    return -label * jnp.log(p + eps) - (1.0 - label) * jnp.log(1.0 - p + eps)


def rank_loss(left: jax.Array, right: jax.Array, label: jax.Array) -> jax.Array:
    """Pairwise RankNet loss (ref: rank_loss_op.cc, CostLayer.cpp RankingCost).

    label = 1 if left should rank higher."""
    d = left - right
    d = d[..., 0] if d.ndim > 1 else d
    return jnp.log1p(jnp.exp(d)) - label * d


def margin_rank_loss(left: jax.Array, right: jax.Array, label: jax.Array,
                     margin: float = 0.0) -> jax.Array:
    """ref: margin_rank_loss_op.cc; label in {-1, 1}."""
    l_ = left[..., 0] if left.ndim > 1 else left
    r_ = right[..., 0] if right.ndim > 1 else right
    return jnp.maximum(0.0, -label * (l_ - r_) + margin)


def multi_binary_label_cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Multi-label sigmoid CE summed over classes (ref: CostLayer.cpp
    MultiBinaryLabelCrossEntropy)."""
    return jnp.sum(sigmoid_cross_entropy_with_logits(logits, labels), axis=-1)


def soft_binary_class_cross_entropy(p: jax.Array, label: jax.Array,
                                    eps: float = 1e-8) -> jax.Array:
    """ref: CostLayer.cpp SoftBinaryClassCrossEntropy."""
    per = -label * jnp.log(p + eps) - (1.0 - label) * jnp.log(1.0 - p + eps)
    return jnp.sum(per.reshape(per.shape[0], -1), axis=-1)


def squared_l2_norm(x: jax.Array) -> jax.Array:
    """ref: squared_l2_norm_op.cc — scalar."""
    return jnp.sum(jnp.square(x))


def kldiv_loss(logp: jax.Array, target: jax.Array) -> jax.Array:
    return jnp.sum(target * (jnp.log(jnp.maximum(target, 1e-12)) - logp), axis=-1)


def nce_loss(logits_pos: jax.Array, logits_neg: jax.Array) -> jax.Array:
    """Noise-contrastive estimation surface (ref: gserver/layers/NCELayer.cpp,
    operators/nce_op.cc): positive logit [B], negative logits [B, K]."""
    pos = sigmoid_cross_entropy_with_logits(logits_pos, jnp.ones_like(logits_pos))
    neg = sigmoid_cross_entropy_with_logits(logits_neg, jnp.zeros_like(logits_neg))
    return pos + jnp.sum(neg, axis=-1)


def masked_seq_loss(per_step_loss: jax.Array, lengths: jax.Array) -> jax.Array:
    """Average per-sequence loss over valid steps of a padded [B, T] loss tensor —
    the LoD-aware cost reduction used by sequence models."""
    from ..core.lod import sequence_mask
    m = sequence_mask(lengths, per_step_loss.shape[1], per_step_loss.dtype)
    return jnp.sum(per_step_loss * m, axis=1) / jnp.maximum(lengths.astype(per_step_loss.dtype), 1.0)
