"""Dense math ops.

TPU-native replacement for the reference's BLAS path: ``Matrix::mul`` -> gemm
(paddle/math/MathFunctions.h:63, cuda/src/hl_cuda_cublas.cc:225) and the gen-2
``mul``/``matmul``/elementwise operator families (paddle/operators/mul_op.cc,
matmul_op.cc, elementwise_*_op.cc). Everything lowers to HLO; matmuls target the MXU —
keep them batched and (optionally) bfloat16 via the ``precision`` policy.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def matmul(x: jax.Array, y: jax.Array, *, transpose_x: bool = False,
           transpose_y: bool = False, precision=None) -> jax.Array:
    """Batched matmul (ref: operators/matmul_op.cc semantics).

    Leading batch dims broadcast; 1-D operands get the usual vector promotion.
    """
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y, precision=precision)


def mul(x: jax.Array, y: jax.Array, *, x_num_col_dims: int = 1,
        y_num_col_dims: int = 1) -> jax.Array:
    """Flattening matmul (ref: operators/mul_op.cc): collapse x's leading
    ``x_num_col_dims`` dims to rows and the rest to cols, similarly for y."""
    import math as _math
    xs, ys = x.shape, y.shape
    xm = x.reshape((_math.prod(xs[:x_num_col_dims]), -1))
    ym = y.reshape((_math.prod(ys[:y_num_col_dims]), -1))
    out = jnp.matmul(xm, ym)
    return out.reshape(xs[:x_num_col_dims] + ys[y_num_col_dims:])


def fc(x: jax.Array, w: jax.Array, b: Optional[jax.Array] = None) -> jax.Array:
    """x @ w + b — the FullyConnectedLayer forward (gserver/layers/FullyConnectedLayer.cpp)."""
    out = jnp.matmul(x.reshape((x.shape[0], -1)), w)
    if b is not None:
        out = out + b
    return out


# elementwise family (ref: operators/elementwise_{add,sub,mul,div}_op.cc with axis
# broadcast semantics; XLA broadcasting subsumes the axis attribute)
def _ewise(op, x, y, axis: int = -1):
    if x.ndim != y.ndim and axis != -1 and y.ndim > 0:
        # ref semantics: y's shape aligns to x's dims starting at `axis`
        shape = [1] * x.ndim
        for i, s in enumerate(y.shape):
            shape[axis + i] = s
        y = y.reshape(shape)
    return op(x, y)


elementwise_add = partial(_ewise, jnp.add)
elementwise_sub = partial(_ewise, jnp.subtract)
elementwise_mul = partial(_ewise, jnp.multiply)
elementwise_div = partial(_ewise, jnp.divide)
elementwise_max = partial(_ewise, jnp.maximum)
elementwise_min = partial(_ewise, jnp.minimum)
elementwise_pow = partial(_ewise, jnp.power)


def scale(x, scale_factor=1.0, bias=0.0, bias_after_scale=True):
    """ref: operators/scale_op.cc."""
    if bias_after_scale:
        return x * scale_factor + bias
    return (x + bias) * scale_factor


def clip(x, min_val, max_val):
    """ref: operators/clip_op.cc."""
    return jnp.clip(x, min_val, max_val)


def clip_by_norm(x, max_norm):
    """ref: operators/clip_by_norm_op.cc."""
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / jnp.maximum(norm, 1e-12)), x)


# reductions (ref: operators/reduce_op.cc registers sum/mean/max/min)
def reduce_sum(x, axis=None, keepdims=False):
    return jnp.sum(x, axis=axis, keepdims=keepdims)


def reduce_mean(x, axis=None, keepdims=False):
    return jnp.mean(x, axis=axis, keepdims=keepdims)


def reduce_max(x, axis=None, keepdims=False):
    return jnp.max(x, axis=axis, keepdims=keepdims)


def reduce_min(x, axis=None, keepdims=False):
    return jnp.min(x, axis=axis, keepdims=keepdims)


def mean(x):
    """ref: operators/mean_op.cc."""
    return jnp.mean(x)


# shape ops (ref: reshape/transpose/concat/split/expand/pad/crop/cast ops)
def reshape(x, shape):
    return jnp.reshape(x, shape)


def transpose(x, axes=None):
    return jnp.transpose(x, axes)


def concat(xs: Sequence[jax.Array], axis: int = 0):
    return jnp.concatenate(xs, axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    idx = list(jnp.cumsum(jnp.array(num_or_sections))[:-1])
    return jnp.split(x, [int(i) for i in idx], axis=axis)


def expand(x, expand_times: Sequence[int]):
    """ref: operators/expand_op.cc (tile)."""
    return jnp.tile(x, expand_times)


def pad(x, paddings, pad_value=0.0):
    """ref: operators/pad_op.cc; paddings is [(lo, hi)] per dim."""
    return jnp.pad(x, paddings, constant_values=pad_value)


def crop(x, offsets: Sequence[int], shape: Sequence[int]):
    """ref: operators/crop_op.cc."""
    return lax.dynamic_slice(x, list(offsets), list(shape))


def cast(x, dtype):
    return x.astype(dtype)


def gather(x, index, axis=0):
    """ref: operators/gather_op.cc."""
    return jnp.take(x, index, axis=axis)


def scatter(x, index, updates, overwrite=True):
    """ref: operators/scatter_op.cc — writes rows of ``updates`` at ``index``."""
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def cos_sim(x, y, eps: float = 1e-8):
    """Row-wise cosine similarity (ref: function/CosSimOp.cpp, operators/cos_sim_op.cc)."""
    nx = jnp.sqrt(jnp.sum(jnp.square(x), -1) + eps)
    ny = jnp.sqrt(jnp.sum(jnp.square(y), -1) + eps)
    return jnp.sum(x * y, -1) / (nx * ny)


def l2_normalize(x, axis=-1, eps=1e-12):
    """ref: operators/norm_op.cc."""
    return x / jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)


def top_k(x, k: int):
    """ref: operators/top_k_op.cc — returns (values, indices) over last dim."""
    return lax.top_k(x, k)


def argmax(x, axis=-1):
    """ref: gserver/layers/MaxIdLayer.cpp."""
    return jnp.argmax(x, axis=axis)


def interpolation(x, y, w):
    """out = w*x + (1-w)*y (ref: gserver/layers/InterpolationLayer.cpp)."""
    w = w.reshape(w.shape + (1,) * (x.ndim - w.ndim))
    return w * x + (1.0 - w) * y


def sum_op(xs: Sequence[jax.Array]):
    """ref: operators/sum_op.cc — adds N tensors."""
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out
