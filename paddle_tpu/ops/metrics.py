"""Metric ops — streaming-friendly building blocks.

Replaces the gen-2 metric operators (operators/accuracy_op.cc, auc_op.cc,
precision_recall_op.cc, chunk_eval_op.cc) and feeds the evaluator zoo
(paddle_tpu.trainer.evaluator, analog of gserver/evaluators/). Each returns raw
counts so evaluators can accumulate across batches exactly like the reference's
streaming Evaluators (Evaluator.h:42 start/eval/finish protocol).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def accuracy(logits_or_pred: jax.Array, labels: jax.Array,
             weights: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Returns (num_correct, num_total) (ref: operators/accuracy_op.cc;
    gserver ClassificationErrorEvaluator reports 1-acc)."""
    pred = (jnp.argmax(logits_or_pred, -1) if logits_or_pred.ndim > labels.ndim
            else logits_or_pred)
    correct = (pred == labels).astype(jnp.float32)
    if weights is not None:
        return jnp.sum(correct * weights), jnp.sum(weights)
    return jnp.sum(correct), jnp.asarray(correct.size, jnp.float32)


def top_k_accuracy(logits: jax.Array, labels: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    _, idx = jax.lax.top_k(logits, k)
    hit = jnp.any(idx == labels[..., None], axis=-1).astype(jnp.float32)
    return jnp.sum(hit), jnp.asarray(hit.size, jnp.float32)


def auc_histogram(probs: jax.Array, labels: jax.Array, num_thresholds: int = 200
                  ) -> Tuple[jax.Array, jax.Array]:
    """Histogram counts for streaming AUC (ref: operators/auc_op.cc uses
    thresholded TP/FP accumulation; gserver AucEvaluator).

    Returns (pos_hist, neg_hist) of shape [num_thresholds]: counts of
    positive/negative examples per probability bin. AUC is computed from the
    accumulated histograms by the evaluator."""
    p = jnp.clip(probs, 0.0, 1.0)
    bin_idx = jnp.minimum((p * num_thresholds).astype(jnp.int32), num_thresholds - 1)
    pos = jnp.zeros((num_thresholds,)).at[bin_idx].add(labels.astype(jnp.float32))
    neg = jnp.zeros((num_thresholds,)).at[bin_idx].add(1.0 - labels.astype(jnp.float32))
    return pos, neg


def auc_from_histogram(pos_hist: jax.Array, neg_hist: jax.Array) -> jax.Array:
    """Trapezoidal AUC over the ROC built from per-bin counts."""
    # descending threshold: cumulative sums from the top bin
    tp = jnp.cumsum(pos_hist[::-1])
    fp = jnp.cumsum(neg_hist[::-1])
    tot_p = jnp.maximum(tp[-1], 1e-12)
    tot_n = jnp.maximum(fp[-1], 1e-12)
    tpr = jnp.concatenate([jnp.zeros((1,)), tp / tot_p])
    fpr = jnp.concatenate([jnp.zeros((1,)), fp / tot_n])
    return jnp.sum((fpr[1:] - fpr[:-1]) * (tpr[1:] + tpr[:-1]) / 2.0)


def precision_recall_counts(pred: jax.Array, labels: jax.Array, num_classes: int
                            ) -> jax.Array:
    """Per-class [TP, FP, FN] counts (ref: operators/precision_recall_op.cc,
    gserver PrecisionRecallEvaluator). pred/labels: [B] ints.

    Returns [num_classes, 3]."""
    onehot_p = jax.nn.one_hot(pred, num_classes)
    onehot_l = jax.nn.one_hot(labels, num_classes)
    tp = jnp.sum(onehot_p * onehot_l, axis=0)
    fp = jnp.sum(onehot_p * (1.0 - onehot_l), axis=0)
    fn = jnp.sum((1.0 - onehot_p) * onehot_l, axis=0)
    return jnp.stack([tp, fp, fn], axis=1)


def chunk_count(pred_tags: jax.Array, label_tags: jax.Array, lengths: jax.Array,
                scheme: str = "IOB", num_chunk_types: int = 1
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Chunk (NER span) counting for F1 (ref: gserver ChunkEvaluator.cpp,
    operators/chunk_eval_op.cc). IOB scheme with tag = chunk_type*2 + {0:B, 1:I}.

    Returns (num_correct_chunks, num_pred_chunks, num_label_chunks)."""
    from ..core.lod import sequence_mask
    B, T = pred_tags.shape
    mask = sequence_mask(lengths, T, jnp.bool_)

    def starts(tags):
        # B-tag, or I-tag whose previous tag is a different chunk type / not adjacent
        is_b = (tags % 2) == 0
        ctype = tags // 2
        prev = jnp.concatenate([jnp.full((B, 1), -1, tags.dtype), tags[:, :-1]], axis=1)
        prev_ctype = prev // 2
        is_i = (tags % 2) == 1
        broken = is_i & ((prev < 0) | (prev_ctype != ctype))
        return (is_b | broken) & mask

    ps, ls = starts(pred_tags), starts(label_tags)
    n_pred = jnp.sum(ps.astype(jnp.float32))
    n_label = jnp.sum(ls.astype(jnp.float32))

    # correct chunk: both start at same pos with same type, tags agree across the
    # label chunk's span, and the pred chunk ends where the label chunk ends
    same = (pred_tags == label_tags) & mask
    both_start = ps & ls

    def seg_all_equal(start_mask, eq):
        # running AND of eq, reset at each label-chunk start
        def step(carry, inp):
            e_t, s_t = inp
            run = jnp.where(s_t, e_t, carry & e_t)
            return run, run
        eqT = jnp.swapaxes(eq, 0, 1)
        sT = jnp.swapaxes(start_mask, 0, 1)
        _, runs = jax.lax.scan(step, jnp.ones((B,), jnp.bool_), (eqT, sT))
        return jnp.swapaxes(runs, 0, 1)  # [B, T] running-equal within label chunk

    run_eq = seg_all_equal(ls, same)
    # a label chunk ends where the next position starts a new label chunk or is invalid
    nxt_start = jnp.concatenate([ls[:, 1:], jnp.ones((B, 1), jnp.bool_)], axis=1)
    nxt_invalid = jnp.concatenate([~mask[:, 1:], jnp.ones((B, 1), jnp.bool_)], axis=1)
    chunk_end = mask & (nxt_start | nxt_invalid)
    # pred must also end its chunk at the same place
    pnxt_start = jnp.concatenate([ps[:, 1:], jnp.ones((B, 1), jnp.bool_)], axis=1)
    p_end = mask & (pnxt_start | nxt_invalid)
    correct = jnp.sum((chunk_end & p_end & run_eq & both_start_propagate(both_start, ls, B, T)).astype(jnp.float32))
    return correct, n_pred, n_label


def both_start_propagate(both_start, label_starts, B, T):
    """Propagate 'chunk started aligned' from each label-chunk start to its end."""
    def step(carry, inp):
        b_t, s_t = inp
        run = jnp.where(s_t, b_t, carry)
        return run, run
    bT = jnp.swapaxes(both_start, 0, 1)
    sT = jnp.swapaxes(label_starts, 0, 1)
    _, runs = jax.lax.scan(step, jnp.zeros((B,), jnp.bool_), (bT, sT))
    return jnp.swapaxes(runs, 0, 1)


def edit_distance(pred: jax.Array, pred_len, label: jax.Array, label_len):
    """Levenshtein distance per row (CTCErrorEvaluator.cpp's core).

    pred [B, Tp] int ids (padded), label [B, Tl]; returns [B] distances.
    DP over fixed padded shapes with masking — XLA-friendly (no dynamic
    shapes), one fori_loop over the pred axis.
    """
    import jax as _jax
    B, Tp = pred.shape
    Tl = label.shape[1]
    pred_len = pred_len.astype(jnp.int32)
    label_len = label_len.astype(jnp.int32)

    # dp[j] = distance between pred[:i] and label[:j], updated row by row
    init = jnp.broadcast_to(jnp.arange(Tl + 1, dtype=jnp.float32),
                            (B, Tl + 1))

    def row(i, dp):
        ins = dp[:, 0] + 1.0
        first = jnp.where(i < pred_len, ins, dp[:, 0])

        def col(j, carry):
            dp_new, diag = carry       # diag = old dp[:, j-1]
            old = dp[:, j]
            sub = diag + jnp.where(pred[:, i] == label[:, j - 1], 0.0, 1.0)
            val = jnp.minimum(jnp.minimum(old + 1.0, dp_new[:, j - 1] + 1.0),
                              sub)
            # freeze beyond the true lengths
            val = jnp.where((i < pred_len) & (j <= label_len), val, old)
            return dp_new.at[:, j].set(val), old

        dp_new = dp.at[:, 0].set(first)
        dp_new, _ = _jax.lax.fori_loop(1, Tl + 1, col, (dp_new, dp[:, 0]))
        return dp_new

    dp = _jax.lax.fori_loop(0, Tp, row, init)
    return jnp.take_along_axis(dp, label_len[:, None], axis=1)[:, 0]


def pnpair_counts(scores: jax.Array, labels: jax.Array, query_ids: jax.Array):
    """PnpairEvaluator.cpp: among same-query pairs with different labels,
    count (correctly ordered, wrongly ordered, ties) by score.

    scores/labels/query_ids: [N]. Returns (pos, neg, spe) scalars.
    """
    s_i, s_j = scores[:, None], scores[None, :]
    l_i, l_j = labels[:, None], labels[None, :]
    q_i, q_j = query_ids[:, None], query_ids[None, :]
    cand = (q_i == q_j) & (l_i > l_j)         # ordered pairs: i should rank higher
    pos = jnp.sum(cand & (s_i > s_j))
    neg = jnp.sum(cand & (s_i < s_j))
    spe = jnp.sum(cand & (s_i == s_j))
    return pos, neg, spe


def average_precision(scores, matched, n_gt):
    """11-point / area AP for one class given decision scores and 0/1 match
    flags (DetectionMAPEvaluator.cpp integral mode). Host-side numpy."""
    import numpy as _np
    scores = _np.asarray(scores, _np.float64)
    matched = _np.asarray(matched, _np.float64)
    if n_gt <= 0 or scores.size == 0:
        return 0.0
    order = _np.argsort(-scores)
    tp = _np.cumsum(matched[order])
    fp = _np.cumsum(1.0 - matched[order])
    rec = tp / n_gt
    prec = tp / _np.maximum(tp + fp, 1e-12)
    # integral AP: sum precision deltas over recall steps
    ap = 0.0
    prev_r = 0.0
    for r, p in zip(rec, prec):
        ap += p * (r - prev_r)
        prev_r = r
    return float(ap)
