"""Large-vocabulary output approximations: NCE and hierarchical sigmoid.

Reference: gserver/layers/NCELayer.cpp (noise-contrastive estimation with
sampled negatives) and HierarchicalSigmoidLayer.cpp (binary-tree softmax);
gen-2 operators/nce_op.cc. TPU-style: the negative sample set is drawn
host-side or via jax.random with static sample count; all gathers are dense
[B, S] lookups that batch into one MXU matmul.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def nce_loss(hidden: jax.Array, labels: jax.Array, weight: jax.Array,
             bias: Optional[jax.Array], rng: jax.Array, *,
             num_neg_samples: int = 10,
             sample_dist: Optional[jax.Array] = None) -> jax.Array:
    """Noise-contrastive estimation loss (NCELayer.cpp / nce_op.cc).

    hidden [B, D]; labels [B] target class ids; weight [V, D]; bias [V].
    Negatives drawn per-batch from ``sample_dist`` (default uniform).
    Returns mean loss over the batch.
    """
    B, D = hidden.shape
    V = weight.shape[0]
    if sample_dist is None:
        neg = jax.random.randint(rng, (num_neg_samples,), 0, V)
        logq_neg = jnp.full((num_neg_samples,), -jnp.log(V))
        logq_pos = jnp.full((B,), -jnp.log(V))
    else:
        neg = jax.random.categorical(rng, jnp.log(sample_dist),
                                     shape=(num_neg_samples,))
        logq_neg = jnp.log(sample_dist[neg] + 1e-20)
        logq_pos = jnp.log(sample_dist[labels] + 1e-20)

    def logit(ids_vecs, h):
        return jnp.einsum("bd,sd->bs", h, ids_vecs)

    w_pos = weight[labels]                                  # [B, D]
    s_pos = jnp.sum(hidden * w_pos, axis=-1)
    w_neg = weight[neg]                                     # [S, D]
    s_neg = logit(w_neg, hidden)                            # [B, S]
    if bias is not None:
        s_pos = s_pos + bias[labels]
        s_neg = s_neg + bias[neg][None, :]
    # NCE with k negatives: sigmoid classification of data vs noise with the
    # log-k*q(w) correction
    k = float(num_neg_samples)
    pos_logit = s_pos - (jnp.log(k) + logq_pos)
    neg_logit = s_neg - (jnp.log(k) + logq_neg[None, :])
    loss_pos = jax.nn.softplus(-pos_logit)                  # -log sigmoid(x)
    loss_neg = jnp.sum(jax.nn.softplus(neg_logit), axis=-1)
    return jnp.mean(loss_pos + loss_neg)


# ---------------------------------------------------------------- hsigmoid ---

def build_huffman_codes(num_classes: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Complete-binary-tree codes (the reference uses the same implicit tree:
    class c's path follows the bits of c+1, HierarchicalSigmoidLayer.cpp).

    Returns (paths [V, L] inner-node ids, codes [V, L] 0/1 with -1 padding).
    """
    import numpy as np
    L = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
    paths = np.zeros((num_classes, L), np.int32)
    codes = np.full((num_classes, L), -1, np.int32)
    for c in range(num_classes):
        node = c + num_classes  # leaves occupy [V, 2V); inner nodes [1, V)
        bits = []
        while node > 1:
            bits.append((node // 2, node & 1))
            node //= 2
        bits.reverse()
        for i, (parent, bit) in enumerate(bits[:L]):
            paths[c, i] = parent
            codes[c, i] = bit
    return jnp.asarray(paths), jnp.asarray(codes)


def hsigmoid_loss(hidden: jax.Array, labels: jax.Array, inner_w: jax.Array,
                  inner_b: Optional[jax.Array], paths: jax.Array,
                  codes: jax.Array) -> jax.Array:
    """Hierarchical-sigmoid NLL. inner_w [2V, D] (rows for inner nodes);
    paths/codes from :func:`build_huffman_codes`. O(log V) per example."""
    p = paths[labels]                                       # [B, L]
    c = codes[labels]                                       # [B, L]
    w = inner_w[p]                                          # [B, L, D]
    logits = jnp.einsum("bld,bd->bl", w, hidden)
    if inner_b is not None:
        logits = logits + inner_b[p]
    # code bit 1 -> right child: P = sigmoid(logit); bit 0 -> 1 - sigmoid
    mask = (c >= 0).astype(logits.dtype)
    signed = jnp.where(c == 1, logits, -logits)
    nll = jax.nn.softplus(-signed) * mask                   # -log sigmoid(±x)
    return jnp.mean(jnp.sum(nll, axis=-1))


def hsigmoid_logprobs(hidden: jax.Array, inner_w: jax.Array,
                      inner_b: Optional[jax.Array], paths: jax.Array,
                      codes: jax.Array) -> jax.Array:
    """Full log-distribution [B, V] (for small-V eval/testing)."""
    V = paths.shape[0]
    w = inner_w[paths]                                      # [V, L, D]
    logits = jnp.einsum("vld,bd->bvl", w, hidden)
    if inner_b is not None:
        logits = logits + inner_b[paths][None]
    mask = (codes >= 0).astype(logits.dtype)[None]
    signed = jnp.where(codes[None] == 1, logits, -logits)
    return -jnp.sum(jax.nn.softplus(-signed) * mask, axis=-1)
