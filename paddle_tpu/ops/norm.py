"""Normalization ops.

Replaces the reference's three batch-norm implementations (gserver/layers/
BatchNormalizationLayer.cpp, CudnnBatchNormLayer.cpp, MKLDNNBatchNormLayer.cpp; gen-2
operators/batch_norm_op.cc), cross-map response normalization (function/
CrossMapNormalOp.cpp, operators/lrn_op.cc), and layer_norm with pure-XLA computations.
Batch norm is functional: train mode returns updated running stats explicitly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def batch_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               running_mean: jax.Array, running_var: jax.Array, *,
               train: bool, momentum: float = 0.9, eps: float = 1e-5,
               axis_mask: Optional[Tuple[int, ...]] = None):
    """Batch normalization over all axes but the last (channel-last layout).

    Returns (y, new_mean, new_var); in eval mode new stats are the running stats
    unchanged. (ref: operators/batch_norm_op.cc, moving-average update with
    ``momentum`` as in BatchNormBaseLayer.cpp)."""
    red = axis_mask if axis_mask is not None else tuple(range(x.ndim - 1))
    if train:
        # ONE pass over x: shifted sum and sum-of-squares reduce together
        # (XLA fuses them into a single HBM read) with f32 accumulation even
        # for bf16 activations — jnp.mean+jnp.var was 2-3 bf16 passes and
        # measured ~40% of a ResNet-50 forward on v5e
        # (docs/design/conv_mfu.md). Shifting by the RUNNING mean keeps the
        # E[d^2]-E[d]^2 form numerically safe: the cancellation term
        # (mean-shift)^2 is ~0 once the running stats track the batch, so
        # the raw-moment formula's catastrophic f32 cancellation at
        # |mean| >> std cannot occur
        xf = x.astype(jnp.float32)
        n = 1
        for a in red:
            n *= x.shape[a]
        shift = jax.lax.stop_gradient(running_mean.astype(jnp.float32))
        d = xf - shift
        s1 = jnp.sum(d, axis=red)
        s2 = jnp.sum(d * d, axis=red)
        dm = s1 / n
        mean = shift + dm
        var = jnp.maximum(s2 / n - dm * dm, 0.0)
        new_mean = (momentum * running_mean.astype(jnp.float32)
                    + (1.0 - momentum) * mean).astype(running_mean.dtype)
        new_var = (momentum * running_var.astype(jnp.float32)
                   + (1.0 - momentum) * var).astype(running_var.dtype)
    else:
        mean = running_mean.astype(jnp.float32)
        var = running_var.astype(jnp.float32)
        new_mean, new_var = running_mean, running_var
    inv = jax.lax.rsqrt(var + eps)
    # scale-shift form: y = x*a + b is one FMA that fuses into the producing
    # conv's epilogue, and keeps y in x's dtype (no f32 upcast of the tensor)
    a = gamma.astype(jnp.float32) * inv
    b = beta.astype(jnp.float32) - mean * a
    y = x * a.astype(x.dtype) + b.astype(x.dtype)
    return y, new_mean, new_var


def layer_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               eps: float = 1e-5, axis: int = -1) -> jax.Array:
    """ref: operators/layer_norm_op.cc (later fluid; standard form)."""
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta


def lrn(x: jax.Array, size: int = 5, alpha: float = 1e-4, beta: float = 0.75,
        k: float = 1.0) -> jax.Array:
    """Local response norm across channels, NHWC (ref: operators/lrn_op.cc,
    function/CrossMapNormalOp.cpp)."""
    sq = jnp.square(x)
    half = size // 2
    pad = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    acc = jnp.zeros_like(x)
    for i in range(size):
        acc = acc + pad[..., i:i + x.shape[-1]]
    return x / jnp.power(k + alpha * acc, beta)


def cross_map_norm(x, size=5, scale=1e-4, pow_=0.75):
    """gen-1 naming (gserver/layers/NormLayer.cpp CMRProjectionNormLayer)."""
    return lrn(x, size=size, alpha=scale, beta=pow_, k=1.0)


def group_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array, groups: int,
               eps: float = 1e-5) -> jax.Array:
    shape = x.shape
    C = shape[-1]
    xg = x.reshape(shape[:-1] + (groups, C // groups))
    red = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.var(xg, axis=red, keepdims=True)
    xn = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(shape)
    return xn * gamma + beta
