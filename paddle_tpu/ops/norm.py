"""Normalization ops.

Replaces the reference's three batch-norm implementations (gserver/layers/
BatchNormalizationLayer.cpp, CudnnBatchNormLayer.cpp, MKLDNNBatchNormLayer.cpp; gen-2
operators/batch_norm_op.cc), cross-map response normalization (function/
CrossMapNormalOp.cpp, operators/lrn_op.cc), and layer_norm with pure-XLA computations.
Batch norm is functional: train mode returns updated running stats explicitly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def batch_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               running_mean: jax.Array, running_var: jax.Array, *,
               train: bool, momentum: float = 0.9, eps: float = 1e-5,
               axis_mask: Optional[Tuple[int, ...]] = None):
    """Batch normalization over all axes but the last (channel-last layout).

    Returns (y, new_mean, new_var); in eval mode new stats are the running stats
    unchanged. (ref: operators/batch_norm_op.cc, moving-average update with
    ``momentum`` as in BatchNormBaseLayer.cpp)."""
    red = axis_mask if axis_mask is not None else tuple(range(x.ndim - 1))
    if train:
        mean = jnp.mean(x, axis=red)
        var = jnp.var(x, axis=red)
        new_mean = momentum * running_mean + (1.0 - momentum) * mean
        new_var = momentum * running_var + (1.0 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var
    inv = jax.lax.rsqrt(var + eps)
    y = (x - mean) * inv * gamma + beta
    return y, new_mean, new_var


def layer_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
               eps: float = 1e-5, axis: int = -1) -> jax.Array:
    """ref: operators/layer_norm_op.cc (later fluid; standard form)."""
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * gamma + beta


def lrn(x: jax.Array, size: int = 5, alpha: float = 1e-4, beta: float = 0.75,
        k: float = 1.0) -> jax.Array:
    """Local response norm across channels, NHWC (ref: operators/lrn_op.cc,
    function/CrossMapNormalOp.cpp)."""
    sq = jnp.square(x)
    half = size // 2
    pad = jnp.pad(sq, ((0, 0), (0, 0), (0, 0), (half, half)))
    acc = jnp.zeros_like(x)
    for i in range(size):
        acc = acc + pad[..., i:i + x.shape[-1]]
    return x / jnp.power(k + alpha * acc, beta)


def cross_map_norm(x, size=5, scale=1e-4, pow_=0.75):
    """gen-1 naming (gserver/layers/NormLayer.cpp CMRProjectionNormLayer)."""
    return lrn(x, size=size, alpha=scale, beta=pow_, k=1.0)


def group_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array, groups: int,
               eps: float = 1e-5) -> jax.Array:
    shape = x.shape
    C = shape[-1]
    xg = x.reshape(shape[:-1] + (groups, C // groups))
    red = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.var(xg, axis=red, keepdims=True)
    xn = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(shape)
    return xn * gamma + beta
