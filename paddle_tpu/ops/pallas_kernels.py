"""Pallas TPU kernels for the hot ops XLA doesn't fuse optimally.

The reference hand-writes CUDA for its hot ops (fused LSTM cells
cuda/src/hl_cuda_lstm.cu, attention-era building blocks); the TPU analog is a
Pallas kernel that keeps the whole inner loop in VMEM next to the MXU/VPU
(/opt/skills/guides/pallas_guide.md).

* :func:`flash_attention` — blockwise-softmax attention: Q tiles stream over
  KV tiles entirely in VMEM; the [T, T] score matrix never touches HBM. This
  is the single biggest HBM-bandwidth win for long sequences and the kernel
  under ring attention's per-chip step.

Kernels run with ``interpret=True`` off-TPU so the same code is testable on the
CPU mesh (tests/test_pallas.py); numerics match the jnp reference path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float,
               causal: bool, seq_len: int, true_len: int):
    """One (batch*head, q-block) program: stream KV tiles, online softmax.

    q_ref: [1, block_q, D]; k_ref/v_ref: [1, T, D]; o_ref: [1, block_q, D].
    """
    _, block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[0] * scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    n_k = seq_len // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < true_len            # mask padded keys
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_new = acc * corr + pv
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc0, m0, l0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention. q/k/v: [B, T, H, D] -> [B, T, H, D].

    T is padded to a block multiple internally; padded keys are masked in the
    kernel. Differentiable: the VJP recomputes attention via the dense jnp
    path (a dedicated backward kernel is future work — forward is where the
    [T, T] HBM blowup lives).
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = not _on_tpu()
    return _flash(q, k, v, causal, scale_v, block_q, block_k, bool(interpret))


def _attention_reference(q, k, v, causal, scale):
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    B, T, H, D = q.shape
    import math
    blk_q = min(block_q, max(8, T))
    blk_k = min(block_k, max(8, T))
    # padded length must tile exactly under BOTH block sizes (the kernel
    # iterates seq_len // block_k tiles)
    step = math.lcm(blk_q, blk_k)
    Tp = -(-T // step) * step
    pad = Tp - T

    # [B, T, H, D] -> [B*H, T, D]
    def to_bh(x):
        x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        return x

    qb, kb, vb = to_bh(q), to_bh(k), to_bh(v)
    kernel = functools.partial(_fa_kernel, block_k=blk_k, scale=scale,
                               causal=causal, seq_len=Tp, true_len=T)
    grid = (B * H, Tp // blk_q)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Tp, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Tp, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype),
        interpret=interpret,
    )(qb, kb, vb)
    out = out[:, :T]
    return jnp.moveaxis(out.reshape(B, H, T, D), 1, 2)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _attention_reference(q, k, v, causal,
                                                          scale), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)
