"""Pallas TPU kernels for the hot ops XLA doesn't fuse optimally.

The reference hand-writes CUDA for its hot ops (fused LSTM cells
cuda/src/hl_cuda_lstm.cu, attention-era building blocks); the TPU analog is a
Pallas kernel that keeps the whole inner loop in VMEM next to the MXU/VPU
(/opt/skills/guides/pallas_guide.md).

* :func:`flash_attention` — blockwise-softmax attention: Q tiles stream over
  KV tiles entirely in VMEM; the [T, T] score matrix never touches HBM. This
  is the single biggest HBM-bandwidth win for long sequences and the kernel
  under ring attention's per-chip step.
* Backward is real Pallas too: a dq kernel (grid over Q blocks, streaming KV
  tiles) and a dk/dv kernel (grid over KV blocks, streaming Q tiles), both
  recomputing the probability tiles in VMEM from the saved logsumexp — the
  [T, T] matrix never exists in HBM in either direction.
* :func:`flash_attention_with_lse` — forward-only variant returning the
  per-row logsumexp, the building block ring attention uses to merge partial
  attention results across ring steps (parallel/ring_attention.py).

Kernels run with ``interpret=True`` off-TPU so the same code is testable on the
CPU mesh (tests/test_pallas.py); numerics match the jnp reference path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fa_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                   scale: float, causal: bool, seq_len: int, true_len: int):
    """One (batch*head, q-block) program: stream KV tiles, online softmax.

    q_ref: [1, block_q, D]; k_ref/v_ref: [1, T, D]; o_ref: [1, block_q, D];
    lse_ref: [1, block_q, 1] (f32 logsumexp residual for the backward pass;
    kept 3D with a trailing unit dim so the block obeys TPU tiling rules).
    """
    _, block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    n_k = seq_len // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < true_len            # mask padded keys
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_new = acc * corr + pv
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_k, body, (acc0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                      *, block_k: int, scale: float, causal: bool,
                      seq_len: int, true_len: int):
    """dq for one (batch*head, q-block): recompute p tiles from saved lse.

    dS = P * (dO·Vᵀ − delta);   dQ = scale · dS·K.
    """
    _, block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                                # [block_q, 1]
    delta = delta_ref[0]                            # [block_q, 1]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    n_k = seq_len // block_k

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < true_len
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse)                        # [block_q, block_k]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq = dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dq

    dq = jax.lax.fori_loop(0, n_k, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_ref, dv_ref, *, block_q: int, scale: float,
                       causal: bool, seq_len: int, true_len: int):
    """dk/dv for one (batch*head, kv-block): stream Q tiles.

    dV = Pᵀ·dO;   dK = scale · dSᵀ·Q.
    Padded query rows contribute nothing because dO (and hence delta) is
    zero-padded, making dS vanish there; padded key columns are masked.
    """
    _, block_k, d = k_ref.shape
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    valid_k = k_pos < true_len

    n_q = seq_len // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        valid = valid_k
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse)                        # [block_q, block_k]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)             # scale folded into q
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# layout helpers + pallas_call wrappers
# ---------------------------------------------------------------------------

def _blocks(T: int, block_q: int, block_k: int) -> Tuple[int, int, int]:
    blk_q = min(block_q, max(8, T))
    blk_k = min(block_k, max(8, T))
    # padded length must tile exactly under BOTH block sizes
    step = math.lcm(blk_q, blk_k)
    Tp = -(-T // step) * step
    return blk_q, blk_k, Tp


def _to_bh(x, Tp):
    """[B, T, H, D] -> [B*H, Tp, D] (zero pad)."""
    B, T, H, D = x.shape
    x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
    if Tp > T:
        x = jnp.pad(x, ((0, 0), (0, Tp - T), (0, 0)))
    return x


def _from_bh(x, B, T, H, D):
    """[B*H, Tp, D] -> [B, T, H, D]."""
    return jnp.moveaxis(x[:, :T].reshape(B, H, T, D), 1, 2)


def _row_to_bh(x, Tp):
    """[B, T, H] -> [B*H, Tp, 1] (zero pad; trailing unit dim for TPU tiling)."""
    B, T, H = x.shape
    x = jnp.moveaxis(x, 2, 1).reshape(B * H, T)
    if Tp > T:
        x = jnp.pad(x, ((0, 0), (0, Tp - T)))
    return x[..., None]


def _fa_fwd_call(q, k, v, causal, scale, block_q, block_k, interpret):
    """Returns (o [B,T,H,D], lse [B,T,H] f32)."""
    B, T, H, D = q.shape
    blk_q, blk_k, Tp = _blocks(T, block_q, block_k)
    qb, kb, vb = _to_bh(q, Tp), _to_bh(k, Tp), _to_bh(v, Tp)
    kernel = functools.partial(_fa_fwd_kernel, block_k=blk_k, scale=scale,
                               causal=causal, seq_len=Tp, true_len=T)
    grid = (B * H, Tp // blk_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Tp, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Tp, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb)
    o = _from_bh(out, B, T, H, D)
    lse = jnp.moveaxis(lse[:, :T, 0].reshape(B, H, T), 1, 2)
    return o, lse


def _fa_bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                 interpret, delta=None):
    """Returns (dq, dk, dv) with the same [B,T,H,D] layout as q/k/v."""
    B, T, H, D = q.shape
    blk_q, blk_k, Tp = _blocks(T, block_q, block_k)
    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)
    qb, kb, vb, dob = (_to_bh(x, Tp) for x in (q, k, v, do))
    lseb, deltab = _row_to_bh(lse, Tp), _row_to_bh(delta, Tp)

    q_spec = pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0))
    full_spec = pl.BlockSpec((1, Tp, D), lambda bh, i: (bh, 0, 0))
    row_q_spec = pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0))
    row_full_spec = pl.BlockSpec((1, Tp, 1), lambda bh, i: (bh, 0, 0))
    k_spec = pl.BlockSpec((1, blk_k, D), lambda bh, ki: (bh, ki, 0))

    dq_kernel = functools.partial(_fa_bwd_dq_kernel, block_k=blk_k,
                                  scale=scale, causal=causal, seq_len=Tp,
                                  true_len=T)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, Tp // blk_q),
        in_specs=[q_spec, full_spec, full_spec, q_spec, row_q_spec,
                  row_q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype),
        interpret=interpret,
    )(qb, kb, vb, dob, lseb, deltab)

    dkv_kernel = functools.partial(_fa_bwd_dkv_kernel, block_q=blk_q,
                                   scale=scale, causal=causal, seq_len=Tp,
                                   true_len=T)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Tp // blk_k),
        in_specs=[full_spec, k_spec, k_spec, full_spec, row_full_spec,
                  row_full_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((B * H, Tp, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, Tp, D), v.dtype)],
        interpret=interpret,
    )(qb, kb, vb, dob, lseb, deltab)

    return (_from_bh(dq, B, T, H, D), _from_bh(dk, B, T, H, D),
            _from_bh(dv, B, T, H, D))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention. q/k/v: [B, T, H, D] -> [B, T, H, D].

    T is padded to a block multiple internally; padded keys are masked in the
    kernel. Fully differentiable: the VJP runs dedicated Pallas dq and dk/dv
    kernels that recompute probability tiles in VMEM from the saved logsumexp
    — no [T, T] matrix in HBM in either direction.
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = not _on_tpu()
    return _flash(q, k, v, causal, scale_v, block_q, block_k, bool(interpret))


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                             causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: int = 128, block_k: int = 128,
                             interpret: Optional[bool] = None):
    """Forward-only attention returning ``(o, lse)`` with lse: [B, T, H] f32.

    Building block for ring attention: partial results over disjoint KV shards
    merge exactly via logaddexp (parallel/ring_attention.py). Not
    differentiable — ring attention installs its own VJP that reuses the
    Pallas backward kernels per ring step.
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = not _on_tpu()
    return _fa_fwd_call(q, k, v, causal, scale_v, block_q, block_k,
                        bool(interpret))


def flash_block_grads(q, k, v, o, lse, do, *, causal: bool = False,
                      scale: Optional[float] = None, block_q: int = 128,
                      block_k: int = 128, interpret: Optional[bool] = None,
                      delta=None):
    """Raw (dq, dk, dv) for one attention block given saved (o, lse).

    Used by ring attention's hand-written backward, where each ring step is
    one such block with externally-merged softmax statistics. Pass ``delta``
    (= rowsum(dO·O), [B,T,H] f32) to avoid recomputing it per step — it is
    loop-invariant across ring steps.
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = not _on_tpu()
    return _fa_bwd_call(q, k, v, o, lse, do, causal, scale_v, block_q,
                        block_k, bool(interpret), delta=delta)


def _attention_reference(q, k, v, causal, scale):
    s = jnp.einsum("bthd,bshd->bhts", q, k) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    o, _ = _fa_fwd_call(q, k, v, causal, scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    o, lse = _fa_fwd_call(q, k, v, causal, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _fa_bwd_call(q, k, v, o, lse, g, causal, scale, block_q, block_k,
                        interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Fused LSTM sequence kernel — the hl_cuda_lstm.cu analog: the entire T-step
# recurrence runs inside ONE kernel with the recurrent weights and the h/c
# state resident in VMEM, so the per-step state never round-trips HBM the way
# a lax.scan's carry does. The input projection x@W stays outside (one big
# MXU matmul); the kernel consumes the precomputed gates input [B, T, 4H].
# ---------------------------------------------------------------------------

def _lstm_seq_kernel(xw_ref, len_ref, u_ref, b_ref, h0_ref, c0_ref,
                     out_ref, ht_ref, ct_ref, *, T: int, H: int,
                     forget_bias: float):
    """One batch-tile program: xw [T, Bb, 4H] (TIME-MAJOR — dynamic indexing
    is only legal on the leading, untiled dim), lengths [Bb, 1] f32 (mask
    computed in-kernel: no dynamic lane loads), u [H, 4H], b [1, 4H],
    h0/c0 [Bb, H] -> out [T, Bb, H], hT/cT [Bb, H]."""
    u = u_ref[...].astype(jnp.float32)
    bias = b_ref[...].astype(jnp.float32)
    lens = len_ref[...].astype(jnp.float32)          # [Bb, 1]
    h0 = h0_ref[...].astype(jnp.float32)
    c0 = c0_ref[...].astype(jnp.float32)

    def step(t, carry):
        h, c = carry
        xw_t = xw_ref[t].astype(jnp.float32)
        gates = xw_t + jax.lax.dot(h, u,
                                   preferred_element_type=jnp.float32) + bias
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H] + forget_bias)
        g = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)   # [Bb, 1]
        h = m * h_new + (1.0 - m) * h
        c = m * c_new + (1.0 - m) * c
        out_ref[t] = (m * h).astype(out_ref.dtype)
        return h, c

    h, c = jax.lax.fori_loop(0, T, step, (h0, c0))
    ht_ref[...] = h.astype(ht_ref.dtype)
    ct_ref[...] = c.astype(ct_ref.dtype)


def lstm_sequence_fused(xw: jax.Array, lengths: jax.Array, u: jax.Array,
                        b: Optional[jax.Array] = None,
                        h0: Optional[jax.Array] = None,
                        c0: Optional[jax.Array] = None, *,
                        forget_bias: float = 0.0, block_b: int = 8,
                        interpret: Optional[bool] = None):
    """Masked LSTM over a whole sequence in one Pallas kernel.

    xw: precomputed x@W [B, T, 4H]; lengths: [B] int; u: [H, 4H];
    returns (out [B, T, H], hT [B, H], cT [B, H]).

    Forward-path kernel (inference / frozen encoders): gradients flow through
    the lax.scan implementation in ops/rnn.py, which computes identical math
    — use this where the reference used the fused hl_lstm forward kernels.
    """
    B, T, G = xw.shape
    if G % 4:
        raise ValueError(f"xw last dim {G} must be 4*H (i/f/g/o gates)")
    H = G // 4
    if interpret is None:
        interpret = not _on_tpu()
    if b is None:
        b = jnp.zeros((G,), xw.dtype)
    if h0 is None:
        h0 = jnp.zeros((B, H), xw.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), xw.dtype)
    blk = min(block_b, B)
    Bp = -(-B // blk) * blk
    lens = lengths.astype(jnp.float32).reshape(B, 1)
    if Bp > B:
        pad = Bp - B
        xw = jnp.pad(xw, ((0, pad), (0, 0), (0, 0)))
        lens = jnp.pad(lens, ((0, pad), (0, 0)))
        h0 = jnp.pad(h0, ((0, pad), (0, 0)))
        c0 = jnp.pad(c0, ((0, pad), (0, 0)))
    xw_tm = jnp.swapaxes(xw, 0, 1)               # time-major [T, Bp, 4H]
    b2 = b.reshape(1, G)

    kernel = functools.partial(_lstm_seq_kernel, T=T, H=H,
                               forget_bias=forget_bias)
    out, ht, ct = pl.pallas_call(
        kernel,
        grid=(Bp // blk,),
        in_specs=[
            pl.BlockSpec((T, blk, G), lambda i: (0, i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((H, G), lambda i: (0, 0)),
            pl.BlockSpec((1, G), lambda i: (0, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((T, blk, H), lambda i: (0, i, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
        ],
        interpret=bool(interpret),
    )(xw_tm, lens, u, b2, h0, c0)
    return jnp.swapaxes(out, 0, 1)[:B], ht[:B], ct[:B]


def _gru_seq_kernel(xw_ref, len_ref, u_ref, h0_ref, out_ref, ht_ref,
                    *, T: int, H: int):
    """Fused whole-sequence GRU (hl_gpu_gru.cuh analog) — one batch-tile
    program, time-major xw [T, Bb, 3H] with the BIAS PRE-ADDED (Mosaic
    rejects sliced-bias broadcasts; the bias is a per-gate constant, so it
    folds into the input projection), u [H, 3H] packed [u_z | u_r | u_c],
    gate order z, r, candidate (the reference's layout)."""
    u = u_ref[...].astype(jnp.float32)
    uz, ur, uc = u[:, :H], u[:, H:2 * H], u[:, 2 * H:]
    lens = len_ref[...].astype(jnp.float32)
    h0 = h0_ref[...].astype(jnp.float32)

    def step(t, h):
        xw_t = xw_ref[t].astype(jnp.float32)
        xz, xr, xc = xw_t[:, :H], xw_t[:, H:2 * H], xw_t[:, 2 * H:]
        z = jax.nn.sigmoid(
            xz + jax.lax.dot(h, uz, preferred_element_type=jnp.float32))
        r = jax.nn.sigmoid(
            xr + jax.lax.dot(h, ur, preferred_element_type=jnp.float32))
        c = jnp.tanh(
            xc + jax.lax.dot(r * h, uc,
                             preferred_element_type=jnp.float32))
        h_new = (1.0 - z) * h + z * c
        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)
        h = m * h_new + (1.0 - m) * h
        out_ref[t] = (m * h).astype(out_ref.dtype)
        return h

    h = jax.lax.fori_loop(0, T, step, h0)
    ht_ref[...] = h.astype(ht_ref.dtype)


def gru_sequence_fused(xw: jax.Array, lengths: jax.Array, u: jax.Array,
                       b: Optional[jax.Array] = None,
                       h0: Optional[jax.Array] = None, *,
                       block_b: int = 8,
                       interpret: Optional[bool] = None):
    """Masked GRU over a whole sequence in one Pallas kernel; see
    lstm_sequence_fused for the design notes. xw: x@W [B, T, 3H];
    returns (out [B, T, H], hT [B, H])."""
    B, T, G = xw.shape
    if G % 3:
        raise ValueError(f"xw last dim {G} must be 3*H (z/r/candidate gates)")
    H = G // 3
    if interpret is None:
        interpret = not _on_tpu()
    if b is not None:
        xw = xw + b                       # bias folds into the projection
    if h0 is None:
        h0 = jnp.zeros((B, H), xw.dtype)
    blk = min(block_b, B)
    Bp = -(-B // blk) * blk
    lens = lengths.astype(jnp.float32).reshape(B, 1)
    if Bp > B:
        pad = Bp - B
        xw = jnp.pad(xw, ((0, pad), (0, 0), (0, 0)))
        lens = jnp.pad(lens, ((0, pad), (0, 0)))
        h0 = jnp.pad(h0, ((0, pad), (0, 0)))
    xw_tm = jnp.swapaxes(xw, 0, 1)

    kernel = functools.partial(_gru_seq_kernel, T=T, H=H)
    out, ht = pl.pallas_call(
        kernel,
        grid=(Bp // blk,),
        in_specs=[
            pl.BlockSpec((T, blk, G), lambda i: (0, i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((H, G), lambda i: (0, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((T, blk, H), lambda i: (0, i, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
        ],
        interpret=bool(interpret),
    )(xw_tm, lens, u, h0)
    return jnp.swapaxes(out, 0, 1)[:B], ht[:B]
