"""Pallas TPU kernels for the hot ops XLA doesn't fuse optimally.

The reference hand-writes CUDA for its hot ops (fused LSTM cells
cuda/src/hl_cuda_lstm.cu, attention-era building blocks); the TPU analog is a
Pallas kernel that keeps the whole inner loop in VMEM next to the MXU/VPU
(/opt/skills/guides/pallas_guide.md).

* :func:`flash_attention` — blockwise-softmax attention: Q tiles stream over
  KV tiles entirely in VMEM; the [T, T] score matrix never touches HBM. This
  is the single biggest HBM-bandwidth win for long sequences and the kernel
  under ring attention's per-chip step.
* Backward is real Pallas too: a dq kernel (grid over Q blocks, streaming KV
  tiles) and a dk/dv kernel (grid over KV blocks, streaming Q tiles), both
  recomputing the probability tiles in VMEM from the saved logsumexp — the
  [T, T] matrix never exists in HBM in either direction.
* :func:`flash_attention_with_lse` — forward-only variant returning the
  per-row logsumexp, the building block ring attention uses to merge partial
  attention results across ring steps (parallel/ring_attention.py).

Kernels run with ``interpret=True`` off-TPU so the same code is testable on the
CPU mesh (tests/test_pallas.py); numerics match the jnp reference path.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG = -1e30


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _k_block_hi(n_k: int, qi, block_q: int, block_k: int, kv_len,
                causal: bool, has_lens: bool):
    """Upper k-block bound shared by the forward and dq kernels: skip
    k-blocks the masks zero out ENTIRELY — causally, blocks past the
    q-block's last row; by length, blocks at/past kv_len. Statically gated
    on n_k > 1: a dynamic fori_loop bound lowers to a while loop whose
    control overhead measurably LOSES when there is only one k-block
    anyway (the T<=1024 default-block case, measured -8..20%); with
    several blocks the diagonal walk saves up to half the streamed tiles.
    Skipped blocks contribute p == 0 exactly, so fwd lse and the bwd
    recomputation stay consistent by construction."""
    hi = n_k
    if n_k > 1:
        if causal:
            hi = jnp.minimum(hi, ((qi + 1) * block_q + block_k - 1)
                             // block_k)
        if has_lens:
            hi = jnp.minimum(hi, (kv_len + block_k - 1) // block_k)
    return hi


def _fa_fwd_kernel(q_ref, k_ref, v_ref, len_ref, o_ref, lse_ref, *,
                   block_k: int, scale: float, causal: bool, seq_len: int,
                   true_len: int, has_lens: bool):
    """One (batch*head, q-block) program: stream KV tiles, online softmax.

    q_ref: [1, block_q, D]; k_ref/v_ref: [1, T, D]; o_ref: [1, block_q, D];
    lse_ref: [1, block_q, 1] (f32 logsumexp residual for the backward pass;
    kept 3D with a trailing unit dim so the block obeys TPU tiling rules).
    len_ref: [1, 1, 1] int32 — THIS sample's true kv length (variable-length
    / LoD masking: keys at or past it never enter the softmax).
    """
    _, block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    kv_len = jnp.minimum(len_ref[0, 0, 0], true_len)

    n_k = seq_len // block_k

    def body(ki, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < kv_len              # mask padded + over-length keys
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_new = acc * corr + pv
        return acc_new, m_new, l_new

    hi = _k_block_hi(n_k, qi, block_q, block_k, kv_len, causal, has_lens)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, hi, body, (acc0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------

def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      len_ref, dq_ref, *, block_k: int, scale: float,
                      causal: bool, seq_len: int, true_len: int,
                      has_lens: bool):
    """dq for one (batch*head, q-block): recompute p tiles from saved lse.

    dS = P * (dO·Vᵀ − delta);   dQ = scale · dS·K.
    """
    _, block_q, d = q_ref.shape
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]                                # [block_q, 1]
    delta = delta_ref[0]                            # [block_q, 1]
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    kv_len = jnp.minimum(len_ref[0, 0, 0], true_len)

    n_k = seq_len // block_k

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_k), 1)
        valid = k_pos < kv_len
        if causal:
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse)                        # [block_q, block_k]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq = dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dq

    # same skipping as the forward (see _k_block_hi: skipped blocks have
    # p == 0 and contribute nothing to dq)
    hi = _k_block_hi(n_k, qi, block_q, block_k, kv_len, causal, has_lens)
    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       len_ref, dk_ref, dv_ref, *, block_q: int, scale: float,
                       causal: bool, seq_len: int, true_len: int,
                       n_k_blocks: int):
    """dk/dv for one (batch*head, kv-block): stream Q tiles.

    dV = Pᵀ·dO;   dK = scale · dSᵀ·Q.
    Padded query rows contribute nothing because dO (and hence delta) is
    zero-padded, making dS vanish there; padded key columns are masked.
    """
    _, block_k, d = k_ref.shape
    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    valid_k = k_pos < jnp.minimum(len_ref[0, 0, 0], true_len)

    n_q = seq_len // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        valid = valid_k
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, _NEG)
        p = jnp.exp(s - lse)                        # [block_q, block_k]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    # causal skip from the other side: q-blocks that end strictly before
    # this k-block's first key are fully below the diagonal — p == 0 rows
    # only, no dk/dv contribution. Statically gated on BOTH grids being
    # multi-block: with a single k-block ki == 0 always and lo == 0, so a
    # dynamic lower bound would be pure while-loop overhead (measured -8%)
    lo = ((ki * block_k) // block_q
          if (causal and n_q > 1 and n_k_blocks > 1) else 0)
    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, n_q, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)             # scale folded into q
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# layout helpers + pallas_call wrappers
# ---------------------------------------------------------------------------

def _blocks(T: int, S: int, block_q: int,
            block_k: int) -> Tuple[int, int, int, int]:
    """Block sizes + padded lengths for q (len T) and kv (len S). The two
    sides pad independently — cross-attention / half-block calls (zigzag
    ring steps) have S != T."""
    blk_q = min(block_q, max(8, T))
    blk_k = min(block_k, max(8, S))
    Tp = -(-T // blk_q) * blk_q
    Sp = -(-S // blk_k) * blk_k
    return blk_q, blk_k, Tp, Sp


def _to_bh(x, Tp):
    """[B, T, H, D] -> [B*H, Tp, D] (zero pad)."""
    B, T, H, D = x.shape
    x = jnp.moveaxis(x, 2, 1).reshape(B * H, T, D)
    if Tp > T:
        x = jnp.pad(x, ((0, 0), (0, Tp - T), (0, 0)))
    return x


def _from_bh(x, B, T, H, D):
    """[B*H, Tp, D] -> [B, T, H, D]."""
    return jnp.moveaxis(x[:, :T].reshape(B, H, T, D), 1, 2)


def _row_to_bh(x, Tp):
    """[B, T, H] -> [B*H, Tp, 1] (zero pad; trailing unit dim for TPU tiling)."""
    B, T, H = x.shape
    x = jnp.moveaxis(x, 2, 1).reshape(B * H, T)
    if Tp > T:
        x = jnp.pad(x, ((0, 0), (0, Tp - T)))
    return x[..., None]


def _lens_to_bh(kv_lens, B, H, S):
    """Per-sample kv lengths -> [B*H, 1, 1] int32 (full length when None).

    3D with two trailing unit dims: a block whose last two dims EQUAL the
    array dims satisfies the TPU tiling rule, where a (1, 1) block over a
    [B*H, 1] array does not (Mosaic requires the second-to-last block dim
    to divide 8 or equal the array dim)."""
    if kv_lens is None:
        lens = jnp.full((B,), S, jnp.int32)
    else:
        lens = jnp.clip(kv_lens.astype(jnp.int32), 0, S)
    return jnp.repeat(lens, H)[:, None, None]


def _fa_fwd_call(q, k, v, causal, scale, block_q, block_k, interpret,
                 kv_lens=None):
    """Returns (o [B,T,H,D], lse [B,T,H] f32). k/v may be shorter or longer
    than q (S != T) for cross-attention-shaped blocks; ``causal`` assumes
    S == T. ``kv_lens`` [B] masks each sample's keys past its true length
    (variable-length batches / cross-attention over padded sources)."""
    B, T, H, D = q.shape
    S = k.shape[1]
    blk_q, blk_k, Tp, Sp = _blocks(T, S, block_q, block_k)
    qb, kb, vb = _to_bh(q, Tp), _to_bh(k, Sp), _to_bh(v, Sp)
    lensb = _lens_to_bh(kv_lens, B, H, S)
    kernel = functools.partial(_fa_fwd_kernel, block_k=blk_k, scale=scale,
                               causal=causal, seq_len=Sp, true_len=S,
                               has_lens=kv_lens is not None)
    grid = (B * H, Tp // blk_q)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, Sp, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, Sp, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, 1, 1), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tp, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qb, kb, vb, lensb)
    o = _from_bh(out, B, T, H, D)
    lse = jnp.moveaxis(lse[:, :T, 0].reshape(B, H, T), 1, 2)
    return o, lse


def _fa_bwd_call(q, k, v, o, lse, do, causal, scale, block_q, block_k,
                 interpret, delta=None, kv_lens=None):
    """Returns (dq, dk, dv); dq follows q's [B,T,H,D], dk/dv follow k/v's
    [B,S,H,D] (S != T for the zigzag half-block steps)."""
    B, T, H, D = q.shape
    S = k.shape[1]
    blk_q, blk_k, Tp, Sp = _blocks(T, S, block_q, block_k)
    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1)
    qb, dob = _to_bh(q, Tp), _to_bh(do, Tp)
    kb, vb = _to_bh(k, Sp), _to_bh(v, Sp)
    lseb, deltab = _row_to_bh(lse, Tp), _row_to_bh(delta, Tp)
    lensb = _lens_to_bh(kv_lens, B, H, S)

    q_spec = pl.BlockSpec((1, blk_q, D), lambda bh, qi: (bh, qi, 0))
    q_full_spec = pl.BlockSpec((1, Tp, D), lambda bh, i: (bh, 0, 0))
    kv_full_spec = pl.BlockSpec((1, Sp, D), lambda bh, i: (bh, 0, 0))
    row_q_spec = pl.BlockSpec((1, blk_q, 1), lambda bh, qi: (bh, qi, 0))
    row_full_spec = pl.BlockSpec((1, Tp, 1), lambda bh, i: (bh, 0, 0))
    k_spec = pl.BlockSpec((1, blk_k, D), lambda bh, ki: (bh, ki, 0))
    len_spec = pl.BlockSpec((1, 1, 1), lambda bh, i: (bh, 0, 0))

    # dq: grid over q blocks, stream kv tiles (loop bound Sp, mask keys >= S)
    dq_kernel = functools.partial(_fa_bwd_dq_kernel, block_k=blk_k,
                                  scale=scale, causal=causal, seq_len=Sp,
                                  true_len=S,
                                  has_lens=kv_lens is not None)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, Tp // blk_q),
        in_specs=[q_spec, kv_full_spec, kv_full_spec, q_spec, row_q_spec,
                  row_q_spec, len_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype),
        interpret=interpret,
    )(qb, kb, vb, dob, lseb, deltab, lensb)

    # dk/dv: grid over kv blocks, stream q tiles (loop bound Tp; padded q
    # rows have zero do/delta so they contribute nothing); mask keys >= S
    dkv_kernel = functools.partial(_fa_bwd_dkv_kernel, block_q=blk_q,
                                   scale=scale, causal=causal, seq_len=Tp,
                                   true_len=S, n_k_blocks=Sp // blk_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, Sp // blk_k),
        in_specs=[q_full_spec, k_spec, k_spec, q_full_spec, row_full_spec,
                  row_full_spec, len_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[jax.ShapeDtypeStruct((B * H, Sp, D), k.dtype),
                   jax.ShapeDtypeStruct((B * H, Sp, D), v.dtype)],
        interpret=interpret,
    )(qb, kb, vb, dob, lseb, deltab, lensb)

    return (_from_bh(dq, B, T, H, D), _from_bh(dk, B, S, H, D),
            _from_bh(dv, B, S, H, D))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _default_blocks(block_q: Optional[int],
                    block_k: Optional[int]) -> Tuple[int, int]:
    """Measured on v5e (GPT-2-small shapes, fwd+bwd): 128x128 tiles spend
    ~5x the kernel's time on per-program overhead; 512/1024 sits within 10%
    of the best sweep point while keeping the dq/dkv working sets well
    inside the 16MB VMEM budget. _blocks() still clamps to the actual
    sequence lengths, so short sequences are unaffected."""
    return block_q or 512, block_k or 1024


# below this sequence length the Pallas kernels' per-program overhead beats
# their HBM saving on this chip (128-tile flash measured 5x slower than
# 512/1024 tiles; at S<=256 the whole [T,S] score tile fits comfortably in
# VMEM through XLA fusion anyway) — a masked dense einsum is faster
SHORT_SEQ_DENSE = 256


def decode_route(L: int, route: Optional[str] = None) -> str:
    """The route :func:`decode_attention` / :func:`paged_decode_attention`
    will take for a read of L rows — exposed so cost accounting
    (obs/roofline.py kernel models) can ask WITHOUT dispatching: modeled
    kernel bytes apply only on the kernel route; the dense route's bytes
    are already visible to XLA's own cost analysis.

    A MEASURED crossover from the autotune cache (``paddle_tpu tune``,
    paddle_tpu.tune) replaces the ``SHORT_SEQ_DENSE`` heuristic when one
    exists for this device_kind: the tuned ``kernel_min_len`` (null =
    the dense route won at every measured length) decides, and off-TPU
    hosts then honor it through the interpreter — both routes share one
    masked-softmax formulation, so the swap never changes tokens."""
    if route is not None:
        return route
    from .. import tune
    thr = tune.decode_kernel_min_len()
    if thr is not tune.MISS:
        return "kernel" if thr is not None and L >= thr else "dense"
    return "kernel" if _on_tpu() and L >= SHORT_SEQ_DENSE else "dense"


def _dense_attention(q, k, v, causal, scale, kv_lens):
    """Masked dense attention for short sequences — same semantics as the
    flash kernels (causal + per-sample kv_lens), ordinary autodiff."""
    T, S = q.shape[1], k.shape[1]
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if kv_lens is not None:
        ok = (jnp.arange(S)[None, :]
              < jnp.clip(kv_lens, 0, S)[:, None])[:, None, None, :]
        s = jnp.where(ok, s, _NEG)
    if causal:
        mask = jnp.tril(jnp.ones((T, S), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bshd->bthd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = False, scale: Optional[float] = None,
                    kv_lens: Optional[jax.Array] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Fused attention. q: [B, T, H, D], k/v: [B, S, H, D] -> [B, T, H, D]
    (S != T = cross attention).

    T is padded to a block multiple internally; padded keys are masked in the
    kernel. ``kv_lens`` [B] int additionally masks each sample's keys at or
    past its true length — the variable-length (LoD) batch and padded-source
    cross-attention path; grads for masked keys are exactly zero. Fully
    differentiable: the VJP runs dedicated Pallas dq and dk/dv kernels that
    recompute probability tiles in VMEM from the saved logsumexp — no [T, S]
    matrix in HBM in either direction.

    Short sequences (max(T, S) < SHORT_SEQ_DENSE, no explicit blocks given)
    auto-route to a masked dense einsum: below that point the kernels'
    per-program overhead exceeds their HBM saving (measured — the NMT
    len-64 shapes; docs/design/nmt_roofline.md), and XLA's fusion keeps the
    small score tensor out of HBM anyway.
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    valid = None
    if kv_lens is not None:
        # a fully-masked sample (kv_lens == 0) has no softmax support: both
        # paths would emit garbage rows. Attend key 0 (finite everywhere),
        # then zero those samples' outputs — the multiply also zeroes their
        # incoming cotangent, so no gradient reaches any key of theirs.
        valid = (kv_lens > 0)
        kv_lens = jnp.maximum(kv_lens, 1)
    if (block_q is None and block_k is None
            and max(q.shape[1], k.shape[1]) < SHORT_SEQ_DENSE):
        o = _dense_attention(q, k, v, causal, scale_v, kv_lens)
    else:
        block_q, block_k = _default_blocks(block_q, block_k)
        if interpret is None:
            interpret = not _on_tpu()
        o = _flash(q, k, v, kv_lens, causal, scale_v, block_q, block_k,
                   bool(interpret))
    if valid is not None:
        o = o * valid[:, None, None, None].astype(o.dtype)
    return o


def flash_attention_with_lse(q: jax.Array, k: jax.Array, v: jax.Array, *,
                             causal: bool = False,
                             scale: Optional[float] = None,
                             block_q: Optional[int] = None,
                             block_k: Optional[int] = None,
                             interpret: Optional[bool] = None):
    """Forward-only attention returning ``(o, lse)`` with lse: [B, T, H] f32.

    Building block for ring attention: partial results over disjoint KV shards
    merge exactly via logaddexp (parallel/ring_attention.py). Not
    differentiable — ring attention installs its own VJP that reuses the
    Pallas backward kernels per ring step.
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    block_q, block_k = _default_blocks(block_q, block_k)
    if interpret is None:
        interpret = not _on_tpu()
    return _fa_fwd_call(q, k, v, causal, scale_v, block_q, block_k,
                        bool(interpret))


def flash_block_grads(q, k, v, o, lse, do, *, causal: bool = False,
                      scale: Optional[float] = None,
                      block_q: Optional[int] = None,
                      block_k: Optional[int] = None,
                      interpret: Optional[bool] = None,
                      delta=None):
    """Raw (dq, dk, dv) for one attention block given saved (o, lse).

    Used by ring attention's hand-written backward, where each ring step is
    one such block with externally-merged softmax statistics. Pass ``delta``
    (= rowsum(dO·O), [B,T,H] f32) to avoid recomputing it per step — it is
    loop-invariant across ring steps.
    """
    D = q.shape[-1]
    scale_v = scale if scale is not None else D ** -0.5
    block_q, block_k = _default_blocks(block_q, block_k)
    if interpret is None:
        interpret = not _on_tpu()
    return _fa_bwd_call(q, k, v, o, lse, do, causal, scale_v, block_q,
                        block_k, bool(interpret), delta=delta)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, kv_lens, causal, scale, block_q, block_k, interpret):
    o, _ = _fa_fwd_call(q, k, v, causal, scale, block_q, block_k, interpret,
                        kv_lens=kv_lens)
    return o


def _flash_fwd(q, k, v, kv_lens, causal, scale, block_q, block_k, interpret):
    o, lse = _fa_fwd_call(q, k, v, causal, scale, block_q, block_k, interpret,
                          kv_lens=kv_lens)
    return o, (q, k, v, kv_lens, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, kv_lens, o, lse = res
    dq, dk, dv = _fa_bwd_call(q, k, v, o, lse, g, causal, scale, block_q,
                              block_k, interpret, kv_lens=kv_lens)
    return dq, dk, dv, None                  # int lens: no cotangent


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Fused decode-step attention — the KV-cache read is the bytes term that
# dominates incremental decode (BENCH_r05: ~18% of the v5e's 819 GB/s).
# One program per SAMPLE streams that sample's live cache rows through VMEM
# once, in the cache's natural [L, H, D] layout (no head transpose in HBM),
# masks rows past the write position, and runs the f32 softmax read there.
# The int8 path dequantizes rows in VMEM from per-(row, head) scales, so the
# HBM cache term halves (2 bytes -> 1 + scale overhead) while the matmuls
# stay f32 — the quantized-KV numerics contract of docs/design/kernels.md.
# ---------------------------------------------------------------------------

def _decode_attn_kernel(q_ref, k_ref, v_ref, pos_ref, o_ref, *, scale: float):
    """One sample: q [1, H, D], k/v [1, L, H, D], pos [1, 1, 1] int32 ->
    o [1, H, D] f32. Rows j <= pos are live (row pos holds THIS step's k/v,
    appended before the read)."""
    q = q_ref[0].astype(jnp.float32) * scale            # [H, D]
    k = k_ref[0].astype(jnp.float32)                    # [L, H, D]
    v = v_ref[0].astype(jnp.float32)
    _decode_attn_body(q, k, v, pos_ref[0, 0, 0], o_ref)


def _decode_attn_q_kernel(q_ref, k_ref, ks_ref, v_ref, vs_ref, pos_ref,
                          o_ref, *, scale: float):
    """int8-KV variant: k/v int8 [1, L, H, D] with per-(row, head) f32
    scales [1, L, H]; rows dequantize in VMEM, never materializing an f32
    cache in HBM."""
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32) * ks_ref[0][..., None]
    v = v_ref[0].astype(jnp.float32) * vs_ref[0][..., None]
    _decode_attn_body(q, k, v, pos_ref[0, 0, 0], o_ref)


def _decode_attn_body(q, k, v, pos, o_ref):
    """Shared masked-softmax read: head-batched dots, softmax over live
    rows. Identical formulation to _dense_decode_attention so the kernel
    and reference routes agree to the ulp on the same inputs."""
    L = k.shape[0]
    # [H, L]: contract D, batch H
    s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (1,))),
                            preferred_element_type=jnp.float32)
    j = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
    s = jnp.where(j <= pos, s, _NEG)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=1, keepdims=True)
    # [H, D]: contract L, batch H
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((0,), (1,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = o / l


def quantize_kv(x: jax.Array):
    """Symmetric int8 rows for the KV cache: x [..., D] ->
    (q int8 [..., D], scale f32 [...]) with x ~= q * scale per row.

    Per-(position, head) scales: one f32 per D-vector — 2 extra bytes per
    64-element bf16 row vs the 64 saved, so the cache read genuinely
    halves. scale = amax/127 keeps the codebook symmetric (no zero-point),
    matching the in-kernel dequant ``q.astype(f32) * scale``."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


def _dense_decode_attention(q, k, v, pos, scale, k_scale, v_scale):
    """Reference-math route (short caches / off-TPU): same masked-softmax
    formulation as the kernel, ordinary XLA ops. Quantized caches
    dequantize up front — numerically the kernel's contract, but the f32
    cache materializes, so this route only makes sense where the cache is
    small anyway."""
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[..., None]
        v = v.astype(jnp.float32) * v_scale[..., None]
    L = k.shape[1]
    s = jnp.einsum("bhd,bjhd->bhj", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    valid = (jnp.arange(L)[None, :] <= pos[:, None])[:, None, :]
    s = jnp.where(valid, s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhj,bjhd->bhd", p / l, v.astype(jnp.float32))


def decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                     pos: jax.Array, *, scale: Optional[float] = None,
                     k_scale: Optional[jax.Array] = None,
                     v_scale: Optional[jax.Array] = None,
                     route: Optional[str] = None,
                     interpret: Optional[bool] = None) -> jax.Array:
    """Single-token KV-cache attention read — THE auto-routing entry for
    the decode step (models/transformer.py decode_step and everything
    above it: generate_cached/generate_fused, serving.ContinuousBatcher,
    speculative verify).

    q: [B, H, D] (this step's query); k/v: [B, L, H, D] cache slices
    already bounded to the live read length L (callers slice ``[:, :L]``
    per their cache bucket); pos: [B] int32 — rows j <= pos[b] are live.
    k_scale/v_scale: [B, L, H] f32 per-row dequant scales when k/v are
    int8 (see :func:`quantize_kv`). Returns o [B, H, D] f32.

    Routing (``route=None``): the Pallas kernel streams the cache once
    per sample and wins exactly where decode is cache-bytes-bound — long
    reads on the TPU; short reads (L < SHORT_SEQ_DENSE) and off-TPU hosts
    take the dense reference math, where XLA's fusion already keeps the
    small score tensor out of HBM. Both routes share one masked-softmax
    formulation, so route choice never changes greedy tokens
    (tests/test_decode_fused.py asserts this bit-for-bit on CPU via
    ``route="kernel", interpret=True``)."""
    B, L, H, D = k.shape
    scale_v = scale if scale is not None else D ** -0.5
    route = decode_route(L, route)
    from .. import obs
    obs.count("kernels.routes_total", kernel="decode_attention", route=route)
    if route == "dense":
        return _dense_decode_attention(q, k, v, pos, scale_v, k_scale,
                                       v_scale)
    if route != "kernel":
        raise ValueError(f"unknown decode_attention route {route!r}")
    if interpret is None:
        interpret = not _on_tpu()
    posb = pos.astype(jnp.int32)[:, None, None]          # [B, 1, 1]
    q_spec = pl.BlockSpec((1, H, D), lambda b: (b, 0, 0))
    kv_spec = pl.BlockSpec((1, L, H, D), lambda b: (b, 0, 0, 0))
    sc_spec = pl.BlockSpec((1, L, H), lambda b: (b, 0, 0))
    pos_spec = pl.BlockSpec((1, 1, 1), lambda b: (b, 0, 0))
    out_spec = pl.BlockSpec((1, H, D), lambda b: (b, 0, 0))
    out_shape = jax.ShapeDtypeStruct((B, H, D), jnp.float32)
    if k_scale is not None:
        kernel = functools.partial(_decode_attn_q_kernel, scale=scale_v)
        return pl.pallas_call(
            kernel, grid=(B,),
            in_specs=[q_spec, kv_spec, sc_spec, kv_spec, sc_spec, pos_spec],
            out_specs=out_spec, out_shape=out_shape,
            interpret=bool(interpret),
        )(q, k, k_scale, v, v_scale, posb)
    kernel = functools.partial(_decode_attn_kernel, scale=scale_v)
    return pl.pallas_call(
        kernel, grid=(B,),
        in_specs=[q_spec, kv_spec, kv_spec, pos_spec],
        out_specs=out_spec, out_shape=out_shape,
        interpret=bool(interpret),
    )(q, k, v, posb)


# ---------------------------------------------------------------------------
# Paged decode attention — the KV cache as a shared BLOCK POOL instead of one
# max_len-padded row per slot: pools [P, bs, H, D] hold fixed-size pages, a
# per-request block table [B, NB] names which pages hold positions
# j*bs..(j+1)*bs-1, and only LIVE pages move. HBM then holds tokens, not
# padding — the serving plane's mixed-length sessions share one pool and
# freed requests return pages immediately (paddle_tpu/serving/paged.py).
# The kernel streams each sample's live pages through VMEM exactly once
# (scalar-prefetched table indices drive the page DMA), assembles the
# contiguous [L, H, D] view there, and runs the SAME masked-softmax body as
# decode_attention — so the paged read and the dense-row read agree to the
# bit on the same cache contents.
# ---------------------------------------------------------------------------

def _paged_attn_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                       k_all, v_all, *, scale: float, block: int):
    """One (sample, page) program: k_ref/v_ref [1, bs, H, D] is the page the
    scalar-prefetched table names for (b, j); pages accumulate into the
    k_all/v_all [NB*bs, H, D] VMEM scratch, and the LAST page program runs
    the shared masked-softmax body over the assembled contiguous view."""
    b, j = pl.program_id(0), pl.program_id(1)
    k_all[pl.ds(j * block, block)] = k_ref[0].astype(jnp.float32)
    v_all[pl.ds(j * block, block)] = v_ref[0].astype(jnp.float32)

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        q = q_ref[0].astype(jnp.float32) * scale
        _decode_attn_body(q, k_all[...], v_all[...], pos_ref[b], o_ref)


def _paged_attn_q_kernel(tbl_ref, pos_ref, q_ref, k_ref, ks_ref, v_ref,
                         vs_ref, o_ref, k_all, v_all, *, scale: float,
                         block: int):
    """int8 pool variant: pages dequantize in VMEM from per-(row, head)
    scales [1, bs, H] while assembling the f32 view — the f32 cache never
    exists in HBM."""
    b, j = pl.program_id(0), pl.program_id(1)
    k_all[pl.ds(j * block, block)] = (k_ref[0].astype(jnp.float32)
                                      * ks_ref[0][..., None])
    v_all[pl.ds(j * block, block)] = (v_ref[0].astype(jnp.float32)
                                      * vs_ref[0][..., None])

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        q = q_ref[0].astype(jnp.float32) * scale
        _decode_attn_body(q, k_all[...], v_all[...], pos_ref[b], o_ref)


def gather_pages(pool: jax.Array, tables: jax.Array) -> jax.Array:
    """Materialize the dense per-sample view of a page pool: pool
    [P, bs, ...] gathered by tables [B, NB] -> [B, NB*bs, ...]. The dense
    reference route (and tests) read through this; the kernel route never
    materializes it in HBM."""
    B, NB = tables.shape
    g = pool[tables]                       # [B, NB, bs, ...]
    return g.reshape((B, NB * pool.shape[1]) + pool.shape[2:])


def paged_decode_attention(q: jax.Array, k_pool: jax.Array,
                           v_pool: jax.Array, tables: jax.Array,
                           pos: jax.Array, *, scale: Optional[float] = None,
                           k_scale: Optional[jax.Array] = None,
                           v_scale: Optional[jax.Array] = None,
                           route: Optional[str] = None,
                           interpret: Optional[bool] = None) -> jax.Array:
    """Single-token attention read through a block table — the paged twin
    of :func:`decode_attention`.

    q: [B, H, D]; k_pool/v_pool: [P, bs, H, D] page pools (bf16/f32, or
    int8 with k_scale/v_scale [P, bs, H] f32 pools); tables: [B, NB] int32
    page indices covering positions 0..NB*bs-1 (entries past a request's
    live pages point at the reserved null page — rows there sit past
    ``pos`` and are masked exactly like dense padding); pos: [B] int32,
    rows j <= pos[b] are live. Returns o [B, H, D] f32.

    Routing matches decode_attention: the Pallas kernel for long on-TPU
    reads (pages stream through VMEM once, driven by the scalar-prefetched
    table), the dense gather + reference math for short reads / off-TPU.
    Both routes share one masked-softmax formulation over the SAME
    assembled row order, so route choice never changes greedy tokens."""
    B, NB = tables.shape
    P, bs, H, D = k_pool.shape
    L = NB * bs
    scale_v = scale if scale is not None else D ** -0.5
    route = decode_route(L, route)
    from .. import obs
    obs.count("kernels.routes_total", kernel="paged_decode_attention",
              route=route)
    if route == "dense":
        k = gather_pages(k_pool, tables)
        v = gather_pages(v_pool, tables)
        ks = None if k_scale is None else gather_pages(k_scale, tables)
        vs = None if v_scale is None else gather_pages(v_scale, tables)
        return _dense_decode_attention(q, k, v, pos, scale_v, ks, vs)
    if route != "kernel":
        raise ValueError(f"unknown paged_decode_attention route {route!r}")
    if interpret is None:
        interpret = not _on_tpu()
    from jax.experimental.pallas import tpu as pltpu
    q_spec = pl.BlockSpec((1, H, D), lambda b, j, tbl, p: (b, 0, 0))
    page_spec = pl.BlockSpec((1, bs, H, D),
                             lambda b, j, tbl, p: (tbl[b, j], 0, 0, 0))
    sc_spec = pl.BlockSpec((1, bs, H),
                           lambda b, j, tbl, p: (tbl[b, j], 0, 0))
    out_spec = pl.BlockSpec((1, H, D), lambda b, j, tbl, p: (b, 0, 0))
    scratch = [pltpu.VMEM((L, H, D), jnp.float32),
               pltpu.VMEM((L, H, D), jnp.float32)]
    out_shape = jax.ShapeDtypeStruct((B, H, D), jnp.float32)
    tables32 = tables.astype(jnp.int32)
    pos32 = pos.astype(jnp.int32)
    if k_scale is not None:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(B, NB),
            in_specs=[q_spec, page_spec, sc_spec, page_spec, sc_spec],
            out_specs=out_spec, scratch_shapes=scratch)
        kernel = functools.partial(_paged_attn_q_kernel, scale=scale_v,
                                   block=bs)
        return pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=bool(interpret),
        )(tables32, pos32, q, k_pool, k_scale, v_pool, v_scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2, grid=(B, NB),
        in_specs=[q_spec, page_spec, page_spec],
        out_specs=out_spec, scratch_shapes=scratch)
    kernel = functools.partial(_paged_attn_kernel, scale=scale_v, block=bs)
    return pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape,
        interpret=bool(interpret),
    )(tables32, pos32, q, k_pool, v_pool)


# ---------------------------------------------------------------------------
# Fused LSTM sequence kernel — the hl_cuda_lstm.cu analog: the entire T-step
# recurrence runs inside ONE kernel with the recurrent weights and the h/c
# state resident in VMEM, so the per-step state never round-trips HBM the way
# a lax.scan's carry does. The input projection x@W stays outside (one big
# MXU matmul); the kernel consumes the precomputed gates input [B, T, 4H].
# ---------------------------------------------------------------------------

def _lstm_seq_kernel(xw_ref, len_ref, u_ref, b_ref, h0_ref, c0_ref,
                     out_ref, ht_ref, ct_ref, *, T: int, H: int,
                     forget_bias: float):
    """One batch-tile program: xw [T, Bb, 4H] (TIME-MAJOR — dynamic indexing
    is only legal on the leading, untiled dim), lengths [Bb, 1] f32 (mask
    computed in-kernel: no dynamic lane loads), u [H, 4H], b [1, 4H],
    h0/c0 [Bb, H] -> out [T, Bb, H], hT/cT [Bb, H]."""
    u = u_ref[...].astype(jnp.float32)
    bias = b_ref[...].astype(jnp.float32)
    lens = len_ref[...].astype(jnp.float32)          # [Bb, 1]
    h0 = h0_ref[...].astype(jnp.float32)
    c0 = c0_ref[...].astype(jnp.float32)

    def step(t, carry):
        h, c = carry
        xw_t = xw_ref[t].astype(jnp.float32)
        gates = xw_t + jax.lax.dot(h, u,
                                   preferred_element_type=jnp.float32) + bias
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H] + forget_bias)
        g = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)   # [Bb, 1]
        h = m * h_new + (1.0 - m) * h
        c = m * c_new + (1.0 - m) * c
        out_ref[t] = (m * h).astype(out_ref.dtype)
        return h, c

    h, c = jax.lax.fori_loop(0, T, step, (h0, c0))
    ht_ref[...] = h.astype(ht_ref.dtype)
    ct_ref[...] = c.astype(ct_ref.dtype)


def _lstm_seq_train_kernel(xw_ref, len_ref, u_ref, b_ref, h0_ref, c0_ref,
                           out_ref, ht_ref, ct_ref, cseq_ref, *, T: int,
                           H: int, forget_bias: float):
    """Training-mode forward: identical math to _lstm_seq_kernel, plus the
    post-mask cell sequence saved for the hand-written backward (the
    reference's fused hl_lstm likewise saved per-step cell state)."""
    u = u_ref[...].astype(jnp.float32)
    bias = b_ref[...].astype(jnp.float32)
    lens = len_ref[...].astype(jnp.float32)
    h0 = h0_ref[...].astype(jnp.float32)
    c0 = c0_ref[...].astype(jnp.float32)

    def step(t, carry):
        h, c = carry
        xw_t = xw_ref[t].astype(jnp.float32)
        gates = xw_t + jax.lax.dot(h, u,
                                   preferred_element_type=jnp.float32) + bias
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H] + forget_bias)
        g = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)
        h = m * h_new + (1.0 - m) * h
        c = m * c_new + (1.0 - m) * c
        out_ref[t] = (m * h).astype(out_ref.dtype)
        cseq_ref[t] = c.astype(cseq_ref.dtype)
        return h, c

    h, c = jax.lax.fori_loop(0, T, step, (h0, c0))
    ht_ref[...] = h.astype(ht_ref.dtype)
    ct_ref[...] = c.astype(ct_ref.dtype)


def lstm_sequence_fused(xw: jax.Array, lengths: jax.Array, u: jax.Array,
                        b: Optional[jax.Array] = None,
                        h0: Optional[jax.Array] = None,
                        c0: Optional[jax.Array] = None, *,
                        forget_bias: float = 0.0, block_b: int = 8,
                        chunk_t: Optional[int] = None,
                        save_cell: bool = False,
                        interpret: Optional[bool] = None):
    """Masked LSTM over a whole sequence in one Pallas kernel.

    xw: precomputed x@W [B, T, 4H]; lengths: [B] int; u: [H, 4H];
    returns (out [B, T, H], hT [B, H], cT [B, H]), plus the post-mask cell
    sequence c_seq [B, T, H] when ``save_cell`` (the residual the
    hand-written backward kernel consumes — ops/rnn.py wires the custom
    VJP, so training uses this kernel in BOTH directions, matching the
    reference's training-mode fused hl_lstm kernels).

    ``chunk_t`` splits time into chunk-sized kernel launches threading
    (h, c) between them — all inside one traced graph, so the cost is one
    h/c HBM round-trip per boundary, not a dispatch. This is what lets
    ``block_b`` grow past 8 on long sequences: the resident tile is
    [chunk_t, block_b, •] instead of [T, block_b, •], and a 32/64-row
    batch tile feeds the MXU where the old whole-sequence 8-row tile
    starved it (ops/rnn.py _fused_plan picks the pair).
    """
    B, T, G = xw.shape
    if G % 4:
        raise ValueError(f"xw last dim {G} must be 4*H (i/f/g/o gates)")
    H = G // 4
    if chunk_t is not None and chunk_t < T:
        h = h0 if h0 is not None else jnp.zeros((B, H), xw.dtype)
        c = c0 if c0 is not None else jnp.zeros((B, H), xw.dtype)
        outs, cells = [], []
        for s in range(0, T, chunk_t):
            e = min(T, s + chunk_t)
            res = lstm_sequence_fused(
                xw[:, s:e], lengths - s, u, b, h, c,
                forget_bias=forget_bias, block_b=block_b,
                save_cell=save_cell, interpret=interpret)
            if save_cell:
                o, h, c, cs = res
                cells.append(cs)
            else:
                o, h, c = res
            outs.append(o)
        out = jnp.concatenate(outs, axis=1)
        if save_cell:
            return out, h, c, jnp.concatenate(cells, axis=1)
        return out, h, c
    if interpret is None:
        interpret = not _on_tpu()
    if b is None:
        b = jnp.zeros((G,), xw.dtype)
    if h0 is None:
        h0 = jnp.zeros((B, H), xw.dtype)
    if c0 is None:
        c0 = jnp.zeros((B, H), xw.dtype)
    blk = min(block_b, B)
    Bp = -(-B // blk) * blk
    lens = lengths.astype(jnp.float32).reshape(B, 1)
    if Bp > B:
        pad = Bp - B
        xw = jnp.pad(xw, ((0, pad), (0, 0), (0, 0)))
        lens = jnp.pad(lens, ((0, pad), (0, 0)))
        h0 = jnp.pad(h0, ((0, pad), (0, 0)))
        c0 = jnp.pad(c0, ((0, pad), (0, 0)))
    xw_tm = jnp.swapaxes(xw, 0, 1)               # time-major [T, Bp, 4H]
    b2 = b.reshape(1, G)

    in_specs = [
        pl.BlockSpec((T, blk, G), lambda i: (0, i, 0)),
        pl.BlockSpec((blk, 1), lambda i: (i, 0)),
        pl.BlockSpec((H, G), lambda i: (0, 0)),
        pl.BlockSpec((1, G), lambda i: (0, 0)),
        pl.BlockSpec((blk, H), lambda i: (i, 0)),
        pl.BlockSpec((blk, H), lambda i: (i, 0)),
    ]
    out_specs = [
        pl.BlockSpec((T, blk, H), lambda i: (0, i, 0)),
        pl.BlockSpec((blk, H), lambda i: (i, 0)),
        pl.BlockSpec((blk, H), lambda i: (i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
        jax.ShapeDtypeStruct((Bp, H), xw.dtype),
        jax.ShapeDtypeStruct((Bp, H), xw.dtype),
    ]
    if save_cell:
        out_specs.append(pl.BlockSpec((T, blk, H), lambda i: (0, i, 0)))
        out_shape.append(jax.ShapeDtypeStruct((T, Bp, H), xw.dtype))
        kernel = functools.partial(_lstm_seq_train_kernel, T=T, H=H,
                                   forget_bias=forget_bias)
        out, ht, ct, cseq = pl.pallas_call(
            kernel, grid=(Bp // blk,), in_specs=in_specs,
            out_specs=out_specs, out_shape=out_shape,
            interpret=bool(interpret))(xw_tm, lens, u, b2, h0, c0)
        return (jnp.swapaxes(out, 0, 1)[:B], ht[:B], ct[:B],
                jnp.swapaxes(cseq, 0, 1)[:B])

    kernel = functools.partial(_lstm_seq_kernel, T=T, H=H,
                               forget_bias=forget_bias)
    out, ht, ct = pl.pallas_call(
        kernel,
        grid=(Bp // blk,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=bool(interpret),
    )(xw_tm, lens, u, b2, h0, c0)
    return jnp.swapaxes(out, 0, 1)[:B], ht[:B], ct[:B]


def _lstm_seq_bwd_kernel(xw_ref, len_ref, u_ref, b_ref, h0_ref, c0_ref,
                         out_ref, cseq_ref, gout_ref, ght_ref, gct_ref,
                         dxw_ref, dh0_ref, dc0_ref, du_ref, *, T: int,
                         H: int, forget_bias: float):
    """Hand-written whole-sequence LSTM backward — the
    hl_lstm_parallel_backward_data/_weight analog: the reverse-time gate
    recurrence runs entirely in VMEM, recomputing gate activations from the
    saved (h, c) sequences instead of storing [T, B, 4H] activations.

    Per reverse step: recompute gates from xw_t + h_{t-1}·u + b (h_{t-1} is
    the saved masked output — identical to the true carry on every live
    step, and irrelevant on dead steps where the mask zeroes all grads),
    then the standard LSTM adjoints. dW/dx/db are large batched matmuls
    left to XLA outside (ops/rnn.py); dU accumulates in VMEM here because
    it needs the per-step h_{t-1}·dgates products.
    """
    u = u_ref[...].astype(jnp.float32)
    bias = b_ref[...].astype(jnp.float32)
    lens = len_ref[...].astype(jnp.float32)
    h0 = h0_ref[...].astype(jnp.float32)
    c0 = c0_ref[...].astype(jnp.float32)

    def step(s, carry):
        dh, dc, du = carry
        t = T - 1 - s
        tm1 = jnp.maximum(t - 1, 0)
        live_prev = (t > 0).astype(jnp.float32)
        h_prev = (live_prev * out_ref[tm1].astype(jnp.float32)
                  + (1.0 - live_prev) * h0)
        c_prev = (live_prev * cseq_ref[tm1].astype(jnp.float32)
                  + (1.0 - live_prev) * c0)
        xw_t = xw_ref[t].astype(jnp.float32)
        gates = xw_t + jax.lax.dot(h_prev, u,
                                   preferred_element_type=jnp.float32) + bias
        i = jax.nn.sigmoid(gates[:, :H])
        f = jax.nn.sigmoid(gates[:, H:2 * H] + forget_bias)
        g = jnp.tanh(gates[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(gates[:, 3 * H:])
        c_cur = f * c_prev + i * g
        tc = jnp.tanh(c_cur)

        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)   # [Bb, 1]
        dh_t = dh + m * gout_ref[t].astype(jnp.float32)
        dhp = m * dh_t
        dct = m * dc + dhp * o * (1.0 - tc * tc)
        do_ = dhp * tc
        dgi = (dct * g) * i * (1.0 - i)
        dgf = (dct * c_prev) * f * (1.0 - f)
        dgg = (dct * i) * (1.0 - g * g)
        dgo = do_ * o * (1.0 - o)
        dgates = jnp.concatenate([dgi, dgf, dgg, dgo], axis=1)   # [Bb, 4H]
        dxw_ref[t] = dgates.astype(dxw_ref.dtype)
        du = du + jax.lax.dot_general(
            h_prev, dgates, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [H, 4H]
        dh_prev = (1.0 - m) * dh_t + jax.lax.dot_general(
            dgates, u, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dc_prev = (1.0 - m) * dc + dct * f
        return dh_prev, dc_prev, du

    dh0_i = ght_ref[...].astype(jnp.float32)
    dc0_i = gct_ref[...].astype(jnp.float32)
    du0 = jnp.zeros((H, 4 * H), jnp.float32)
    dh, dc, du = jax.lax.fori_loop(0, T, step, (dh0_i, dc0_i, du0))
    dh0_ref[...] = dh.astype(dh0_ref.dtype)
    dc0_ref[...] = dc.astype(dc0_ref.dtype)

    # the du output block is shared by every grid program; the TPU grid is
    # sequential, so accumulate across batch tiles in place
    @pl.when(pl.program_id(0) == 0)
    def _init():
        du_ref[...] = jnp.zeros_like(du_ref)

    du_ref[...] += du.astype(du_ref.dtype)


def lstm_sequence_fused_bwd(xw, lengths, u, b, h0, c0, out_seq, c_seq,
                            g_out, g_ht, g_ct, *, forget_bias: float = 0.0,
                            block_b: int = 8,
                            interpret: Optional[bool] = None):
    """Backward of :func:`lstm_sequence_fused` (save_cell residuals).

    Returns (dxw [B,T,4H], dh0 [B,H], dc0 [B,H], du [H,4H] f32).
    """
    B, T, G = xw.shape
    H = G // 4
    if interpret is None:
        interpret = not _on_tpu()
    blk = min(block_b, B)
    Bp = -(-B // blk) * blk
    lens = lengths.astype(jnp.float32).reshape(B, 1)
    if Bp > B:
        pad = Bp - B
        pad3 = ((0, pad), (0, 0), (0, 0))
        pad2 = ((0, pad), (0, 0))
        xw = jnp.pad(xw, pad3)
        out_seq = jnp.pad(out_seq, pad3)
        c_seq = jnp.pad(c_seq, pad3)
        g_out = jnp.pad(g_out, pad3)
        lens = jnp.pad(lens, pad2)
        h0 = jnp.pad(h0, pad2)
        c0 = jnp.pad(c0, pad2)
        g_ht = jnp.pad(g_ht, pad2)
        g_ct = jnp.pad(g_ct, pad2)
    tm = lambda a: jnp.swapaxes(a, 0, 1)
    b2 = b.reshape(1, G)

    kernel = functools.partial(_lstm_seq_bwd_kernel, T=T, H=H,
                               forget_bias=forget_bias)
    seq_spec = lambda width: pl.BlockSpec((T, blk, width), lambda i: (0, i, 0))
    vec_spec = pl.BlockSpec((blk, H), lambda i: (i, 0))
    dxw, dh0, dc0, du = pl.pallas_call(
        kernel,
        grid=(Bp // blk,),
        in_specs=[
            seq_spec(G),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((H, G), lambda i: (0, 0)),
            pl.BlockSpec((1, G), lambda i: (0, 0)),
            vec_spec, vec_spec,
            seq_spec(H), seq_spec(H), seq_spec(H),
            vec_spec, vec_spec,
        ],
        out_specs=[
            seq_spec(G),
            vec_spec, vec_spec,
            pl.BlockSpec((H, G), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, G), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((H, G), jnp.float32),
        ],
        interpret=bool(interpret),
    )(tm(xw), lens, u, b2, h0, c0, tm(out_seq), tm(c_seq), tm(g_out),
      g_ht, g_ct)
    return jnp.swapaxes(dxw, 0, 1)[:B], dh0[:B], dc0[:B], du


def _gru_seq_kernel(xw_ref, len_ref, u_ref, h0_ref, out_ref, ht_ref,
                    *, T: int, H: int):
    """Fused whole-sequence GRU (hl_gpu_gru.cuh analog) — one batch-tile
    program, time-major xw [T, Bb, 3H] with the BIAS PRE-ADDED (Mosaic
    rejects sliced-bias broadcasts; the bias is a per-gate constant, so it
    folds into the input projection), u [H, 3H] packed [u_z | u_r | u_c],
    gate order z, r, candidate (the reference's layout)."""
    u = u_ref[...].astype(jnp.float32)
    uz, ur, uc = u[:, :H], u[:, H:2 * H], u[:, 2 * H:]
    lens = len_ref[...].astype(jnp.float32)
    h0 = h0_ref[...].astype(jnp.float32)

    def step(t, h):
        xw_t = xw_ref[t].astype(jnp.float32)
        xz, xr, xc = xw_t[:, :H], xw_t[:, H:2 * H], xw_t[:, 2 * H:]
        z = jax.nn.sigmoid(
            xz + jax.lax.dot(h, uz, preferred_element_type=jnp.float32))
        r = jax.nn.sigmoid(
            xr + jax.lax.dot(h, ur, preferred_element_type=jnp.float32))
        c = jnp.tanh(
            xc + jax.lax.dot(r * h, uc,
                             preferred_element_type=jnp.float32))
        h_new = (1.0 - z) * h + z * c
        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)
        h = m * h_new + (1.0 - m) * h
        out_ref[t] = (m * h).astype(out_ref.dtype)
        return h

    h = jax.lax.fori_loop(0, T, step, h0)
    ht_ref[...] = h.astype(ht_ref.dtype)


def _gru_seq_bwd_kernel(xw_ref, len_ref, u_ref, h0_ref, out_ref, gout_ref,
                        ght_ref, dxw_ref, dh0_ref, du_ref, *, T: int, H: int):
    """Hand-written whole-sequence GRU backward (hl_gpu_gru.cuh backward
    analog). Everything is recomputable from xw (bias pre-added) and the
    saved masked output sequence, so no extra residuals are stored; the
    reverse recurrence and dU accumulation stay in VMEM."""
    u = u_ref[...].astype(jnp.float32)
    uz, ur, uc = u[:, :H], u[:, H:2 * H], u[:, 2 * H:]
    lens = len_ref[...].astype(jnp.float32)
    h0 = h0_ref[...].astype(jnp.float32)

    def step(s, carry):
        dh, du = carry
        t = T - 1 - s
        tm1 = jnp.maximum(t - 1, 0)
        live_prev = (t > 0).astype(jnp.float32)
        h_prev = (live_prev * out_ref[tm1].astype(jnp.float32)
                  + (1.0 - live_prev) * h0)
        xw_t = xw_ref[t].astype(jnp.float32)
        xz, xr, xc = xw_t[:, :H], xw_t[:, H:2 * H], xw_t[:, 2 * H:]
        z = jax.nn.sigmoid(
            xz + jax.lax.dot(h_prev, uz, preferred_element_type=jnp.float32))
        r = jax.nn.sigmoid(
            xr + jax.lax.dot(h_prev, ur, preferred_element_type=jnp.float32))
        rh = r * h_prev
        c = jnp.tanh(
            xc + jax.lax.dot(rh, uc, preferred_element_type=jnp.float32))

        m = (t.astype(jnp.float32) < lens).astype(jnp.float32)
        dh_t = dh + m * gout_ref[t].astype(jnp.float32)
        dhp = m * dh_t                              # grad wrt h'_t
        # h' = (1-z) h_prev + z c
        dgz = (dhp * (c - h_prev)) * z * (1.0 - z)
        dgc = (dhp * z) * (1.0 - c * c)
        drh = jax.lax.dot_general(dgc, uc, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dgr = (drh * h_prev) * r * (1.0 - r)
        dh_prev = ((1.0 - m) * dh_t + dhp * (1.0 - z) + drh * r
                   + jax.lax.dot_general(dgz, uz, (((1,), (1,)), ((), ())),
                                         preferred_element_type=jnp.float32)
                   + jax.lax.dot_general(dgr, ur, (((1,), (1,)), ((), ())),
                                         preferred_element_type=jnp.float32))
        dxw_ref[t] = jnp.concatenate([dgz, dgr, dgc],
                                     axis=1).astype(dxw_ref.dtype)
        ha = lambda lhs, rhs: jax.lax.dot_general(
            lhs, rhs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        du = du + jnp.concatenate([ha(h_prev, dgz), ha(h_prev, dgr),
                                   ha(rh, dgc)], axis=1)
        return dh_prev, du

    dh0_i = ght_ref[...].astype(jnp.float32)
    du0 = jnp.zeros((H, 3 * H), jnp.float32)
    dh, du = jax.lax.fori_loop(0, T, step, (dh0_i, du0))
    dh0_ref[...] = dh.astype(dh0_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        du_ref[...] = jnp.zeros_like(du_ref)

    du_ref[...] += du.astype(du_ref.dtype)


def gru_sequence_fused_bwd(xw, lengths, u, h0, out_seq, g_out, g_ht, *,
                           block_b: int = 8,
                           interpret: Optional[bool] = None):
    """Backward of :func:`gru_sequence_fused` (xw carries the pre-added
    bias, so its grad is also the bias grad summed outside).

    Returns (dxw [B,T,3H], dh0 [B,H], du [H,3H] f32).
    """
    B, T, G = xw.shape
    H = G // 3
    if interpret is None:
        interpret = not _on_tpu()
    blk = min(block_b, B)
    Bp = -(-B // blk) * blk
    lens = lengths.astype(jnp.float32).reshape(B, 1)
    if Bp > B:
        pad = Bp - B
        pad3 = ((0, pad), (0, 0), (0, 0))
        pad2 = ((0, pad), (0, 0))
        xw = jnp.pad(xw, pad3)
        out_seq = jnp.pad(out_seq, pad3)
        g_out = jnp.pad(g_out, pad3)
        lens = jnp.pad(lens, pad2)
        h0 = jnp.pad(h0, pad2)
        g_ht = jnp.pad(g_ht, pad2)
    tm = lambda a: jnp.swapaxes(a, 0, 1)

    kernel = functools.partial(_gru_seq_bwd_kernel, T=T, H=H)
    seq_spec = lambda width: pl.BlockSpec((T, blk, width), lambda i: (0, i, 0))
    vec_spec = pl.BlockSpec((blk, H), lambda i: (i, 0))
    dxw, dh0, du = pl.pallas_call(
        kernel,
        grid=(Bp // blk,),
        in_specs=[
            seq_spec(G),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((H, G), lambda i: (0, 0)),
            vec_spec,
            seq_spec(H), seq_spec(H),
            vec_spec,
        ],
        out_specs=[
            seq_spec(G),
            vec_spec,
            pl.BlockSpec((H, G), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, G), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((H, G), jnp.float32),
        ],
        interpret=bool(interpret),
    )(tm(xw), lens, u, h0, tm(out_seq), tm(g_out), g_ht)
    return jnp.swapaxes(dxw, 0, 1)[:B], dh0[:B], du


def gru_sequence_fused(xw: jax.Array, lengths: jax.Array, u: jax.Array,
                       b: Optional[jax.Array] = None,
                       h0: Optional[jax.Array] = None, *,
                       block_b: int = 8, chunk_t: Optional[int] = None,
                       interpret: Optional[bool] = None):
    """Masked GRU over a whole sequence in one Pallas kernel; see
    lstm_sequence_fused for the design notes (including ``chunk_t`` time
    chunking, which buys the wide MXU-feeding batch tiles). xw: x@W
    [B, T, 3H]; returns (out [B, T, H], hT [B, H])."""
    B, T, G = xw.shape
    if G % 3:
        raise ValueError(f"xw last dim {G} must be 3*H (z/r/candidate gates)")
    H = G // 3
    if chunk_t is not None and chunk_t < T:
        if b is not None:
            xw = xw + b
            b = None
        h = h0 if h0 is not None else jnp.zeros((B, H), xw.dtype)
        outs = []
        for s in range(0, T, chunk_t):
            e = min(T, s + chunk_t)
            o, h = gru_sequence_fused(xw[:, s:e], lengths - s, u, None, h,
                                      block_b=block_b, interpret=interpret)
            outs.append(o)
        return jnp.concatenate(outs, axis=1), h
    if interpret is None:
        interpret = not _on_tpu()
    if b is not None:
        xw = xw + b                       # bias folds into the projection
    if h0 is None:
        h0 = jnp.zeros((B, H), xw.dtype)
    blk = min(block_b, B)
    Bp = -(-B // blk) * blk
    lens = lengths.astype(jnp.float32).reshape(B, 1)
    if Bp > B:
        pad = Bp - B
        xw = jnp.pad(xw, ((0, pad), (0, 0), (0, 0)))
        lens = jnp.pad(lens, ((0, pad), (0, 0)))
        h0 = jnp.pad(h0, ((0, pad), (0, 0)))
    xw_tm = jnp.swapaxes(xw, 0, 1)

    kernel = functools.partial(_gru_seq_kernel, T=T, H=H)
    out, ht = pl.pallas_call(
        kernel,
        grid=(Bp // blk,),
        in_specs=[
            pl.BlockSpec((T, blk, G), lambda i: (0, i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((H, G), lambda i: (0, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((T, blk, H), lambda i: (0, i, 0)),
            pl.BlockSpec((blk, H), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, Bp, H), xw.dtype),
            jax.ShapeDtypeStruct((Bp, H), xw.dtype),
        ],
        interpret=bool(interpret),
    )(xw_tm, lens, u, h0)
    return jnp.swapaxes(out, 0, 1)[:B], ht[:B]


# ---------------------------------------------------------------------------
# Roofline cost models — Pallas custom calls report ZERO FLOPs/bytes to XLA's
# cost analysis, so each kernel registers the analytic HBM bytes of one
# dispatch with the obs cost ledger (obs/roofline.py register_kernel_cost).
# Every consumer — the live fluid.device_bytes_total accounting, the
# kernels.bytes_total counters at dispatch sites, and the bench rows'
# hbm_bw_util columns (benchmarks/serving_decode.py) — resolves through
# roofline.kernel_cost, so the modeled number has exactly one owner and the
# bench rows and live gauges can never disagree on methodology.
# ---------------------------------------------------------------------------

def _decode_attention_bytes(*, batch, read, n_heads, d_head, layers=1,
                            kv_dtype=None, itemsize=2, steps=1):
    """HBM bytes of ``steps`` decode_attention dispatches: k+v live cache
    rows stream once per step (int8 rows read 1 byte/element plus one f32
    scale per (row, head) — the quantized-KV numerics contract,
    docs/design/kernels.md)."""
    row = n_heads * (d_head + 4 if kv_dtype == "int8"
                     else d_head * itemsize)
    return 2.0 * batch * read * row * layers * steps


def _paged_decode_attention_bytes(*, batch, pages, page_block, n_heads,
                                  d_head, layers=1, kv_dtype=None,
                                  itemsize=2, steps=1):
    """HBM bytes of ``steps`` paged reads: each sample streams its
    ``pages`` live pages (``page_block`` rows each) once per step."""
    return _decode_attention_bytes(batch=batch, read=pages * page_block,
                                   n_heads=n_heads, d_head=d_head,
                                   layers=layers, kv_dtype=kv_dtype,
                                   itemsize=itemsize, steps=steps)


def _paged_prefill_attention_bytes(*, batch, pages, page_block, n_heads,
                                   d_head, layers=1, kv_dtype=None,
                                   itemsize=2):
    """HBM bytes of one prefix-HIT admission dispatch
    (TransformerLM.prefill_paged): each sample's read view gathers its
    ``pages`` table-named pages once per layer — the shared-prefix read
    that replaces re-prefilling those positions. The suffix k/v WRITES
    ride the executable's own XLA byte analysis; only the gathered cache
    read needs a hand model (same shape as the paged decode read at
    steps=1)."""
    return _paged_decode_attention_bytes(batch=batch, pages=pages,
                                         page_block=page_block,
                                         n_heads=n_heads, d_head=d_head,
                                         layers=layers, kv_dtype=kv_dtype,
                                         itemsize=itemsize, steps=1)


def _lstm_sequence_fused_bytes(*, batch, seq_len, hidden, itemsize=4,
                               gates=4):
    """HBM bytes of one fused-RNN forward launch: the [B, T, G*H] gate
    input streams in once, [B, T, H] outputs stream out, the recurrent
    [H, G*H] weights load once (VMEM-resident across steps — the whole
    point of the kernel)."""
    return float(itemsize) * (batch * seq_len * hidden * gates      # xw in
                              + batch * seq_len * hidden            # out
                              + hidden * hidden * gates)            # U


def _register_cost_models():
    from ..obs import roofline
    roofline.register_kernel_cost("decode_attention",
                                  _decode_attention_bytes)
    roofline.register_kernel_cost("paged_decode_attention",
                                  _paged_decode_attention_bytes)
    roofline.register_kernel_cost("paged_prefill_attention",
                                  _paged_prefill_attention_bytes)
    roofline.register_kernel_cost("lstm_sequence_fused",
                                  _lstm_sequence_fused_bytes)
    roofline.register_kernel_cost(
        "gru_sequence_fused",
        functools.partial(_lstm_sequence_fused_bytes, gates=3))


_register_cost_models()
