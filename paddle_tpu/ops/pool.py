"""Pooling ops.

Replaces paddle/function pooling paths and gen-2 pool2d/pool3d (+cudnn, with-index)
operators (operators/pool_op.cc, pool_with_index_op.cc) and the ROI/spatial-pyramid
layers (gserver/layers/ROIPoolLayer.cpp, SpatialPyramidPoolLayer.cpp) with
``lax.reduce_window`` — XLA's native windowed reduction. NHWC layout.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

IntOr2 = Union[int, Sequence[int]]


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _pads(padding, k):
    if isinstance(padding, str):
        return padding.upper()
    p = _pair(padding)
    return [(0, 0), (p[0], p[0]), (p[1], p[1]), (0, 0)]


def max_pool2d(x: jax.Array, kernel: IntOr2, stride: IntOr2 = None,
               padding: Union[str, IntOr2] = 0) -> jax.Array:
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, (1, kh, kw, 1), (1, sh, sw, 1),
                             _pads(padding, kernel))


def avg_pool2d(x: jax.Array, kernel: IntOr2, stride: IntOr2 = None,
               padding: Union[str, IntOr2] = 0,
               count_include_pad: bool = True) -> jax.Array:
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    pads = _pads(padding, kernel)
    summed = lax.reduce_window(x, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1), pads)
    if count_include_pad or isinstance(padding, str):
        return summed / (kh * kw)
    ones = jnp.ones_like(x)
    counts = lax.reduce_window(ones, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1), pads)
    return summed / counts


def global_avg_pool2d(x: jax.Array) -> jax.Array:
    return jnp.mean(x, axis=(1, 2))


def global_max_pool2d(x: jax.Array) -> jax.Array:
    return jnp.max(x, axis=(1, 2))


def max_pool2d_with_index(x: jax.Array, kernel: IntOr2, stride: IntOr2 = None,
                          padding: IntOr2 = 0) -> Tuple[jax.Array, jax.Array]:
    """ref: operators/pool_with_index_op.cc — returns (pooled, flat argmax index
    within each window's input plane), used by unpooling."""
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    B, H, W, C = x.shape
    flat_idx = jnp.arange(H * W, dtype=jnp.float32).reshape(1, H, W, 1)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)

    def reducer(a, b):
        av, ai = a
        bv, bi = b
        take_b = bv > av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    init = (jnp.array(-jnp.inf, x.dtype), jnp.array(-1.0))
    vals, idxs = lax.reduce_window((x, flat_idx), init, reducer,
                                   (1, kh, kw, 1), (1, sh, sw, 1), _pads(padding, kernel))
    return vals, idxs.astype(jnp.int32)


def max_pool3d(x: jax.Array, kernel, stride=None, padding=0) -> jax.Array:
    k = (kernel,) * 3 if isinstance(kernel, int) else tuple(kernel)
    s = k if stride is None else ((stride,) * 3 if isinstance(stride, int) else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    pads = [(0, 0)] + [(pi, pi) for pi in p] + [(0, 0)]
    return lax.reduce_window(x, -jnp.inf, lax.max, (1,) + k + (1,), (1,) + s + (1,), pads)


def avg_pool3d(x: jax.Array, kernel, stride=None, padding=0) -> jax.Array:
    k = (kernel,) * 3 if isinstance(kernel, int) else tuple(kernel)
    s = k if stride is None else ((stride,) * 3 if isinstance(stride, int) else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    pads = [(0, 0)] + [(pi, pi) for pi in p] + [(0, 0)]
    summed = lax.reduce_window(x, 0.0, lax.add, (1,) + k + (1,), (1,) + s + (1,), pads)
    return summed / (k[0] * k[1] * k[2])


def spatial_pyramid_pool(x: jax.Array, pyramid_height: int,
                         pool_type: str = "max") -> jax.Array:
    """ref: gserver/layers/SpatialPyramidPoolLayer.cpp, operators/spp_op.cc.

    Pools the feature map at pyramid levels 1x1, 2x2, ... 2^(h-1) bins and concats.
    Output length is fixed: sum over levels of bins^2 * C, independent of H/W —
    bin boundaries are computed per-bin (floor/ceil), SPP-paper style."""
    B, H, W, C = x.shape
    outs = []
    for level in range(pyramid_height):
        bins = 2 ** level
        for i in range(bins):
            y0, y1 = (i * H) // bins, -(-((i + 1) * H) // bins)
            for j in range(bins):
                x0, x1 = (j * W) // bins, -(-((j + 1) * W) // bins)
                region = x[:, y0:y1, x0:x1, :]
                if pool_type == "max":
                    outs.append(jnp.max(region, axis=(1, 2)))
                else:
                    outs.append(jnp.mean(region, axis=(1, 2)))
    return jnp.concatenate(outs, axis=-1)


def roi_pool(feat: jax.Array, rois: jax.Array, out_size: Tuple[int, int],
             spatial_scale: float = 1.0) -> jax.Array:
    """ROI max pooling (ref: gserver/layers/ROIPoolLayer.cpp, operators/roi_pool_op.cc).

    feat: [H, W, C] single image feature; rois: [N, 4] (x1, y1, x2, y2) in input scale.
    Static-shape implementation: for each output bin, build a mask over the feature map
    and take a masked max — O(N * oh * ow) masked reductions, fine for detection heads.
    """
    H, W, C = feat.shape
    oh, ow = out_size
    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0) / oh
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0) / ow

        def one_bin(i, j):
            y_lo, y_hi = y1 + i * rh, y1 + (i + 1) * rh
            x_lo, x_hi = x1 + j * rw, x1 + (j + 1) * rw
            my = (ys >= jnp.floor(y_lo)) & (ys < jnp.ceil(y_hi))
            mx = (xs >= jnp.floor(x_lo)) & (xs < jnp.ceil(x_hi))
            m = (my[:, None] & mx[None, :])[:, :, None]
            return jnp.max(jnp.where(m, feat, -jnp.inf), axis=(0, 1))

        rows = jnp.stack([jnp.stack([one_bin(i, j) for j in range(ow)]) for i in range(oh)])
        return rows  # [oh, ow, C]

    return jax.vmap(one_roi)(rois.astype(jnp.float32))
