"""Random ops — dropout and random fills.

ref: operators/dropout_op.cc, gaussian_random_op.cc, uniform_random_op.cc. Explicit
PRNG keys (JAX convention) replace the reference's global generators; under jit the
threefry bits generate on-device.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dropout(x: jax.Array, rate: float, rng: jax.Array, train: bool = True,
            scale_in_train: bool = True) -> jax.Array:
    """ref dropout semantics: in eval the output is x (upscale-in-train) or
    x*(1-rate) (downgrade-in-infer) depending on implementation flag."""
    if not train or rate <= 0.0:
        return x if scale_in_train else x * (1.0 - rate)
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    if scale_in_train:
        return jnp.where(mask, x / keep, 0.0)
    return jnp.where(mask, x, 0.0)


def gaussian_random(rng, shape, mean=0.0, std=1.0, dtype=jnp.float32):
    return mean + std * jax.random.normal(rng, shape, dtype)


def uniform_random(rng, shape, low=-1.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(rng, shape, dtype, low, high)
