"""Recurrent ops: LSTM/GRU cells + masked scans.

Replaces the reference's fused recurrent kernels (paddle/cuda/src/hl_cuda_lstm.cu,
hl_gpu_gru.cuh, operators/math/lstm_compute.cc, gru_compute.cc) and the dynamic-RNN
engine (gserver/gradientmachines/RecurrentGradientMachine.cpp, operators/recurrent_op.cc,
dynamic_recurrent_op.cc). TPU-first design:

* The input projection x @ W for ALL timesteps is one big [B*T, 4H] matmul (MXU-
  friendly) done before the scan; only the recurrent h @ U matmul lives inside
  ``lax.scan`` — the same restructuring SequenceToBatch did for step-parallelism,
  expressed at the compiler level.
* Variable lengths: every step is masked (state frozen once t >= length), replacing
  shrink-live-batch (lod_rank_table + shrink_rnn_memory_op) with branch-free masking.
* Gate order: i, f, c(candidate/g), o — matching the reference's hl_lstm layout
  (input/forget/cell/output).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.lod import sequence_mask


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_cell(xw: jax.Array, state: LSTMState, u: jax.Array, b: Optional[jax.Array],
              forget_bias: float = 0.0) -> LSTMState:
    """One LSTM step. xw: precomputed x@W [B, 4H]; u: [H, 4H]."""
    h, c = state
    gates = xw + jnp.matmul(h, u)
    if b is not None:
        gates = gates + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return LSTMState(h_new, c_new)


def gru_cell(xw: jax.Array, h: jax.Array, u: jax.Array,
             b: Optional[jax.Array]) -> jax.Array:
    """One GRU step (ref gate order: update z, reset r, candidate).

    xw: x@W [B, 3H]; u: [H, 3H] packed [u_zr | u_c]."""
    H = h.shape[-1]
    xz, xr, xc = jnp.split(xw, 3, axis=-1)
    uz, ur, uc = jnp.split(u, 3, axis=-1)
    bz = br = bc = 0.0
    if b is not None:
        bz, br, bc = jnp.split(b, 3, axis=-1)
    z = jax.nn.sigmoid(xz + jnp.matmul(h, uz) + bz)
    r = jax.nn.sigmoid(xr + jnp.matmul(h, ur) + br)
    c = jnp.tanh(xc + jnp.matmul(r * h, uc) + bc)
    return (1.0 - z) * h + z * c


def lstm(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array, u: jax.Array,
         b: Optional[jax.Array] = None, h0: Optional[jax.Array] = None,
         c0: Optional[jax.Array] = None, reverse: bool = False,
         forget_bias: float = 0.0) -> Tuple[jax.Array, LSTMState]:
    """Full-sequence LSTM. x: [B, T, D]; w: [D, 4H]; u: [H, 4H].

    Returns (outputs [B, T, H], final LSTMState). Masked: for t >= length the state
    carries through unchanged and the output is zero (LoD semantics — downstream
    sequence pooling then ignores padding for free)."""
    B, T, D = x.shape
    H = u.shape[0]
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)  # one MXU pass
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)

    def step(carry, inp):
        state = LSTMState(*carry)
        xw_t, m_t = inp
        new = lstm_cell(xw_t, state, u, b, forget_bias)
        m = m_t[:, None]
        h_n = m * new.h + (1.0 - m) * state.h
        c_n = m * new.c + (1.0 - m) * state.c
        return (h_n, c_n), m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))  # [T, B, ...]
    (h, c), ys = lax.scan(step, (h, c), xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), LSTMState(h, c)


def gru(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array, u: jax.Array,
        b: Optional[jax.Array] = None, h0: Optional[jax.Array] = None,
        reverse: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence GRU. x: [B, T, D]; w: [D, 3H]; u: [H, 3H]."""
    B, T, D = x.shape
    H = u.shape[0]
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xw_t, m_t = inp
        h_new = gru_cell(xw_t, h_prev, u, b)
        m = m_t[:, None]
        h_n = m * h_new + (1.0 - m) * h_prev
        return h_n, m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))
    h, ys = lax.scan(step, h, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), h


def bidirectional(rnn_fn: Callable, x, lengths, fwd_params: dict, bwd_params: dict,
                  merge: str = "concat"):
    """Bidirectional wrapper (ref: networks.py bidirectional_lstm:553ff).

    For the reverse direction the mask-aware scan runs with reverse=True, which on
    padded-right batches is equivalent to the reference's sequence-reverse layers
    because masked steps carry state through unchanged."""
    out_f, _ = rnn_fn(x, lengths, reverse=False, **fwd_params)
    out_b, _ = rnn_fn(x, lengths, reverse=True, **bwd_params)
    if merge == "concat":
        return jnp.concatenate([out_f, out_b], axis=-1)
    if merge == "sum":
        return out_f + out_b
    raise ValueError(f"unknown merge '{merge}'")


def simple_rnn(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array,
               u: jax.Array, b: Optional[jax.Array] = None,
               act: Callable = jnp.tanh, h0: Optional[jax.Array] = None,
               reverse: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Vanilla RNN (ref: gserver/layers/RecurrentLayer.cpp)."""
    B, T, D = x.shape
    H = u.shape[0]
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xw_t, m_t = inp
        h_new = act(xw_t + jnp.matmul(h_prev, u) + (b if b is not None else 0.0))
        m = m_t[:, None]
        h_n = m * h_new + (1.0 - m) * h_prev
        return h_n, m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))
    h, ys = lax.scan(step, h, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), h
