"""Recurrent ops: LSTM/GRU cells + masked scans.

Replaces the reference's fused recurrent kernels (paddle/cuda/src/hl_cuda_lstm.cu,
hl_gpu_gru.cuh, operators/math/lstm_compute.cc, gru_compute.cc) and the dynamic-RNN
engine (gserver/gradientmachines/RecurrentGradientMachine.cpp, operators/recurrent_op.cc,
dynamic_recurrent_op.cc). TPU-first design:

* The input projection x @ W for ALL timesteps is one big [B*T, 4H] matmul (MXU-
  friendly) done before the scan; only the recurrent h @ U matmul lives inside
  ``lax.scan`` — the same restructuring SequenceToBatch did for step-parallelism,
  expressed at the compiler level.
* Variable lengths: every step is masked (state frozen once t >= length), replacing
  shrink-live-batch (lod_rank_table + shrink_rnn_memory_op) with branch-free masking.
* Gate order: i, f, c(candidate/g), o — matching the reference's hl_lstm layout
  (input/forget/cell/output).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import functools

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.lod import sequence_mask


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_cell(xw: jax.Array, state: LSTMState, u: jax.Array, b: Optional[jax.Array],
              forget_bias: float = 0.0) -> LSTMState:
    """One LSTM step. xw: precomputed x@W [B, 4H]; u: [H, 4H]."""
    h, c = state
    gates = xw + jnp.matmul(h, u)
    if b is not None:
        gates = gates + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return LSTMState(h_new, c_new)


def gru_cell(xw: jax.Array, h: jax.Array, u: jax.Array,
             b: Optional[jax.Array]) -> jax.Array:
    """One GRU step (ref gate order: update z, reset r, candidate).

    xw: x@W [B, 3H]; u: [H, 3H] packed [u_zr | u_c]."""
    H = h.shape[-1]
    xz, xr, xc = jnp.split(xw, 3, axis=-1)
    uz, ur, uc = jnp.split(u, 3, axis=-1)
    bz = br = bc = 0.0
    if b is not None:
        bz, br, bc = jnp.split(b, 3, axis=-1)
    z = jax.nn.sigmoid(xz + jnp.matmul(h, uz) + bz)
    r = jax.nn.sigmoid(xr + jnp.matmul(h, ur) + br)
    c = jnp.tanh(xc + jnp.matmul(r * h, uc) + bc)
    return (1.0 - z) * h + z * c


def lstm(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array, u: jax.Array,
         b: Optional[jax.Array] = None, h0: Optional[jax.Array] = None,
         c0: Optional[jax.Array] = None, reverse: bool = False,
         forget_bias: float = 0.0,
         fused: Optional[bool] = None) -> Tuple[jax.Array, LSTMState]:
    """Full-sequence LSTM. x: [B, T, D]; w: [D, 4H]; u: [H, 4H].

    Returns (outputs [B, T, H], final LSTMState). Masked: for t >= length the state
    carries through unchanged and the output is zero (LoD semantics — downstream
    sequence pooling then ignores padding for free).

    ``fused=True`` routes the forward pass through the Pallas whole-sequence
    kernel (hl_cuda_lstm.cu analog: u and h/c resident in VMEM for all T
    steps); both paths compute identical math. Use it on forward-only paths
    (inference bundles set it automatically at export,
    fluid/io.py export_inference_model) — under autodiff the backward
    replays the scan, so training should keep the default.
    """
    if fused is None:
        fused = False
    if fused and not reverse:
        from . import pallas_kernels as _pk
        B, T, _ = x.shape
        H = u.shape[0]
        blk = _fused_block_b(T, H)
        if not _pk._on_tpu() or blk is None:
            # off-TPU, or the sequence is too long for the whole-sequence
            # tile to fit VMEM even at block_b=1 — the scan handles any shape
            return _lstm_scan(x, lengths, w, u, b, h0, c0, reverse,
                              forget_bias)
        lens = (lengths if lengths is not None
                else jnp.full((B,), T, jnp.int32))
        b_ = b if b is not None else jnp.zeros((4 * H,), x.dtype)
        h0_ = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
        c0_ = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)
        out, ht, ct = _lstm_fused(x, lens, w, u, b_, h0_, c0_, forget_bias,
                                  blk)
        return out, LSTMState(ht, ct)
    return _lstm_scan(x, lengths, w, u, b, h0, c0, reverse, forget_bias)


def _fused_block_b(T: int, H: int, gates: int = 4,
                   budget_bytes: int = 10_000_000):
    """Largest batch tile whose whole-sequence VMEM working set (xw + out
    blocks, double-buffered, plus resident u) fits; None -> use the scan.
    ``gates``: 4 for LSTM, 3 for GRU (sizes the [H, gates*H] u and the
    [T, blk, gates*H] xw tile)."""
    u_bytes = H * gates * H * 4
    for blk in (8, 4, 2, 1):
        tile = T * blk * (gates * H + H) * 4 * 2  # xw + out, double-buffered
        if u_bytes + tile <= budget_bytes:
            return blk
    return None


def _lstm_scan(x, lengths, w, u, b, h0, c0, reverse, forget_bias):
    B, T, D = x.shape
    H = u.shape[0]
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)  # one MXU pass
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)

    def step(carry, inp):
        state = LSTMState(*carry)
        xw_t, m_t = inp
        new = lstm_cell(xw_t, state, u, b, forget_bias)
        m = m_t[:, None]
        h_n = m * new.h + (1.0 - m) * state.h
        c_n = m * new.c + (1.0 - m) * state.c
        return (h_n, c_n), m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))  # [T, B, ...]
    (h, c), ys = lax.scan(step, (h, c), xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), LSTMState(h, c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _lstm_fused(x, lens, w, u, b, h0, c0, forget_bias, block_b):
    """Forward through the Pallas fused kernel; backward recomputes through
    the (bit-identical) scan implementation — the hand-kernel-forward /
    autodiff-backward split of the reference's fused hl_lstm."""
    from .pallas_kernels import lstm_sequence_fused
    B, T, D = x.shape
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    return lstm_sequence_fused(xw, lens, u, b, h0=h0, c0=c0,
                               forget_bias=forget_bias, block_b=block_b)


def _lstm_fused_fwd(x, lens, w, u, b, h0, c0, forget_bias, block_b):
    out = _lstm_fused(x, lens, w, u, b, h0, c0, forget_bias, block_b)
    return out, (x, lens, w, u, b, h0, c0)


def _lstm_fused_bwd(forget_bias, block_b, res, g):
    x, lens, w, u, b, h0, c0 = res

    def replay(x, w, u, b, h0, c0):
        out, state = _lstm_scan(x, lens, w, u, b, h0, c0, False, forget_bias)
        return out, state.h, state.c

    _, vjp = jax.vjp(replay, x, w, u, b, h0, c0)
    dx, dw, du, db, dh0, dc0 = vjp(g)
    zero_lens = np.zeros(lens.shape, jax.dtypes.float0)
    return dx, zero_lens, dw, du, db, dh0, dc0


_lstm_fused.defvjp(_lstm_fused_fwd, _lstm_fused_bwd)


def gru(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array, u: jax.Array,
        b: Optional[jax.Array] = None, h0: Optional[jax.Array] = None,
        reverse: bool = False,
        fused: Optional[bool] = None) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence GRU. x: [B, T, D]; w: [D, 3H]; u: [H, 3H].

    ``fused=True`` runs the forward through the Pallas whole-sequence kernel
    (hl_gpu_gru.cuh analog) — same contract as lstm(fused=True): forward-only
    paths; gradients replay the scan."""
    B, T, D = x.shape
    H = u.shape[0]
    if fused and not reverse:
        from . import pallas_kernels as _pk
        blk = _fused_block_b(T, H, gates=3)
        if _pk._on_tpu() and blk is not None:
            lens = (lengths if lengths is not None
                    else jnp.full((B,), T, jnp.int32))
            b_ = b if b is not None else jnp.zeros((3 * H,), x.dtype)
            h0_ = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
            return _gru_fused(x, lens, w, u, b_, h0_, blk)
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xw_t, m_t = inp
        h_new = gru_cell(xw_t, h_prev, u, b)
        m = m_t[:, None]
        h_n = m * h_new + (1.0 - m) * h_prev
        return h_n, m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))
    h, ys = lax.scan(step, h, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), h


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def _gru_fused(x, lens, w, u, b, h0, block_b):
    from .pallas_kernels import gru_sequence_fused
    B, T, D = x.shape
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    return gru_sequence_fused(xw, lens, u, b, h0=h0, block_b=block_b)


def _gru_fused_fwd(x, lens, w, u, b, h0, block_b):
    return _gru_fused(x, lens, w, u, b, h0, block_b), (x, lens, w, u, b, h0)


def _gru_fused_bwd(block_b, res, g):
    x, lens, w, u, b, h0 = res

    def replay(x, w, u, b, h0):
        return gru(x, lens, w, u, b, h0, fused=False)

    _, vjp = jax.vjp(replay, x, w, u, b, h0)
    dx, dw, du, db, dh0 = vjp(g)
    return dx, np.zeros(lens.shape, jax.dtypes.float0), dw, du, db, dh0


_gru_fused.defvjp(_gru_fused_fwd, _gru_fused_bwd)


def bidirectional(rnn_fn: Callable, x, lengths, fwd_params: dict, bwd_params: dict,
                  merge: str = "concat"):
    """Bidirectional wrapper (ref: networks.py bidirectional_lstm:553ff).

    For the reverse direction the mask-aware scan runs with reverse=True, which on
    padded-right batches is equivalent to the reference's sequence-reverse layers
    because masked steps carry state through unchanged."""
    out_f, _ = rnn_fn(x, lengths, reverse=False, **fwd_params)
    out_b, _ = rnn_fn(x, lengths, reverse=True, **bwd_params)
    if merge == "concat":
        return jnp.concatenate([out_f, out_b], axis=-1)
    if merge == "sum":
        return out_f + out_b
    raise ValueError(f"unknown merge '{merge}'")


def simple_rnn(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array,
               u: jax.Array, b: Optional[jax.Array] = None,
               act: Callable = jnp.tanh, h0: Optional[jax.Array] = None,
               reverse: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Vanilla RNN (ref: gserver/layers/RecurrentLayer.cpp)."""
    B, T, D = x.shape
    H = u.shape[0]
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xw_t, m_t = inp
        h_new = act(xw_t + jnp.matmul(h_prev, u) + (b if b is not None else 0.0))
        m = m_t[:, None]
        h_n = m * h_new + (1.0 - m) * h_prev
        return h_n, m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))
    h, ys = lax.scan(step, h, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), h
