"""Recurrent ops: LSTM/GRU cells + masked scans.

Replaces the reference's fused recurrent kernels (paddle/cuda/src/hl_cuda_lstm.cu,
hl_gpu_gru.cuh, operators/math/lstm_compute.cc, gru_compute.cc) and the dynamic-RNN
engine (gserver/gradientmachines/RecurrentGradientMachine.cpp, operators/recurrent_op.cc,
dynamic_recurrent_op.cc). TPU-first design:

* The input projection x @ W for ALL timesteps is one big [B*T, 4H] matmul (MXU-
  friendly) done before the scan; only the recurrent h @ U matmul lives inside
  ``lax.scan`` — the same restructuring SequenceToBatch did for step-parallelism,
  expressed at the compiler level.
* Variable lengths: every step is masked (state frozen once t >= length), replacing
  shrink-live-batch (lod_rank_table + shrink_rnn_memory_op) with branch-free masking.
* Gate order: i, f, c(candidate/g), o — matching the reference's hl_lstm layout
  (input/forget/cell/output).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Tuple

import functools

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.lod import sequence_mask


class LSTMState(NamedTuple):
    h: jax.Array
    c: jax.Array


def lstm_cell(xw: jax.Array, state: LSTMState, u: jax.Array, b: Optional[jax.Array],
              forget_bias: float = 0.0) -> LSTMState:
    """One LSTM step. xw: precomputed x@W [B, 4H]; u: [H, 4H]."""
    h, c = state
    gates = xw + jnp.matmul(h, u)
    if b is not None:
        gates = gates + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return LSTMState(h_new, c_new)


def gru_cell(xw: jax.Array, h: jax.Array, u: jax.Array,
             b: Optional[jax.Array]) -> jax.Array:
    """One GRU step (ref gate order: update z, reset r, candidate).

    xw: x@W [B, 3H]; u: [H, 3H] packed [u_zr | u_c]."""
    H = h.shape[-1]
    xz, xr, xc = jnp.split(xw, 3, axis=-1)
    uz, ur, uc = jnp.split(u, 3, axis=-1)
    bz = br = bc = 0.0
    if b is not None:
        bz, br, bc = jnp.split(b, 3, axis=-1)
    z = jax.nn.sigmoid(xz + jnp.matmul(h, uz) + bz)
    r = jax.nn.sigmoid(xr + jnp.matmul(h, ur) + br)
    c = jnp.tanh(xc + jnp.matmul(r * h, uc) + bc)
    return (1.0 - z) * h + z * c


def lstm(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array, u: jax.Array,
         b: Optional[jax.Array] = None, h0: Optional[jax.Array] = None,
         c0: Optional[jax.Array] = None, reverse: bool = False,
         forget_bias: float = 0.0,
         fused: Optional[bool] = None) -> Tuple[jax.Array, LSTMState]:
    """Full-sequence LSTM. x: [B, T, D]; w: [D, 4H]; u: [H, 4H].

    Returns (outputs [B, T, H], final LSTMState). Masked: for t >= length the state
    carries through unchanged and the output is zero (LoD semantics — downstream
    sequence pooling then ignores padding for free).

    ``fused=True`` routes through the Pallas whole-sequence kernels in BOTH
    directions (hl_cuda_lstm.cu analog: u and h/c resident in VMEM for all
    T steps; the backward is the hand-written reverse-recurrence kernel,
    hl_lstm_parallel_backward_data/_weight analog). Both paths compute
    identical math, so fused training == scan training numerically (see
    tests/test_pallas.py).

    ``fused=None`` (default) auto-selects: the kernel whenever a legal
    (batch-tile, time-chunk) plan fits VMEM on the TPU (see
    :func:`_fused_plan`), the scan otherwise. The original narrow-tile
    kernel lost MXU-bound large batches (B=64 train 2.2x slower — VMEM
    capped the whole-sequence batch tile at 8 rows, starving the 128-row
    MXU; docs/design/fused_rnn_bench.md); time-chunked launches lift that
    cap to 32/64-row tiles, which is what routes the textcls (h256,
    len 30-100, B>=64) and NMT-encoder shape families onto the kernel.
    benchmarks/fused_rnn.py re-measures the crossover on-chip.
    """
    B, T, _ = x.shape
    H = u.shape[0]
    if fused is None:
        fused = True                 # auto: plan + backend decide below
    if fused:
        from . import pallas_kernels as _pk
        from .. import obs
        plan = _fused_plan(T, H, seq_h_units=6, batch=B,
                           kernel="lstm_sequence_fused")
        obs.count("kernels.routes_total", kernel="lstm_sequence_fused",
                  route=("fused" if _pk._on_tpu() and plan is not None
                         else "scan"))
        if _pk._on_tpu() and plan is not None:
            # modeled launch bytes through the ONE registered model
            # (pallas_kernels._lstm_sequence_fused_bytes): under an
            # executor/instrumented-jit trace the collector re-emits them
            # PER DISPATCH; eagerly this counts kernels.bytes_total now
            obs.roofline.note_kernel_bytes(
                "lstm_sequence_fused",
                obs.roofline.kernel_cost(
                    "lstm_sequence_fused", batch=B, seq_len=T,
                    hidden=H, itemsize=jnp.dtype(x.dtype).itemsize))
            blk, chunk = plan
            lens = (lengths if lengths is not None
                    else jnp.full((B,), T, jnp.int32))
            b_ = b if b is not None else jnp.zeros((4 * H,), x.dtype)
            h0_ = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
            c0_ = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)
            xk = _reverse_within_length(x, lens) if reverse else x
            out, ht, ct = _lstm_fused(xk, lens, w, u, b_, h0_, c0_,
                                      forget_bias, blk, chunk)
            if reverse:
                out = _reverse_within_length(out, lens)
            return out, LSTMState(ht, ct)
        # off-TPU, or no VMEM-legal plan — the scan handles any shape
    return _lstm_scan(x, lengths, w, u, b, h0, c0, reverse, forget_bias)


#: minimum resident time-chunk for a wide batch tile: below this the
#: chunk-boundary h/c round-trips start to rival the per-step work
_CHUNK_MIN_WIDE = 16


def plan_is_legal(T: int, H: int, gates: int, seq_h_units: int,
                  batch: int, block_b: int, chunk_t: int,
                  budget_bytes: int = 15_500_000,
                  double_buffer_always: bool = False) -> bool:
    """Can (block_b, chunk_t) launch the fused kernel for this family?

    The ONE owner of launch legality — :func:`_fused_plan`'s heuristic
    preference and the autotune plane's candidate enumeration /
    cached-plan validation (paddle_tpu.tune) both resolve through it, so
    a tuned cache can never name a plan the heuristic's VMEM cost model
    would reject. Constraints: Mosaic's batch-tile rule (a multiple of 8,
    or one single-program grid covering the whole batch), and the
    resident [chunk, blk, seq_h_units*H] tile + u (+ du accumulator)
    fitting the scoped-VMEM budget — double-buffered whenever the grid
    has more than one program (see :func:`_fused_plan`)."""
    if block_b < 1 or chunk_t < 1 or batch < 1:
        return False
    blk = min(block_b, batch)
    grid_is_1 = blk >= batch            # one program covers the batch
    if blk % 8 and not grid_is_1:
        return False                    # Mosaic batch-tile rule
    u_bytes = H * gates * H * 4
    avail = budget_bytes - 2 * u_bytes
    if avail <= 0:
        return False
    per_step = blk * seq_h_units * H * 4
    if double_buffer_always or not grid_is_1:
        per_step *= 2
    return min(chunk_t, T) * per_step <= avail


def _tuned_plan(kernel: Optional[str], T: int, H: int, gates: int,
                seq_h_units: int, batch: Optional[int],
                budget_bytes: int,
                double_buffer_always: bool) -> Optional[Tuple[int, int]]:
    """Consult the autotune cache for this launch's family; None on any
    miss (no cache, stale hash, illegal plan) — the heuristic then owns
    the decision, so a cache changes speed, never numerics."""
    if kernel is None or batch is None:
        return None
    from .. import tune
    plan = tune.fused_plan(kernel, T=T, H=H, gates=gates,
                           seq_h_units=seq_h_units, batch=batch,
                           budget_bytes=budget_bytes,
                           double_buffer_always=double_buffer_always)
    if plan is None:
        return None
    blk, chunk = plan
    return blk, min(chunk, T)


def _fused_plan(T: int, H: int, gates: int = 4,
                seq_h_units: Optional[int] = None,
                batch: Optional[int] = None,
                budget_bytes: int = 15_500_000,
                double_buffer_always: bool = False,
                kernel: Optional[str] = None
                ) -> Optional[Tuple[int, int]]:
    """(block_b, chunk_t) for the fused whole-sequence kernels, or None
    for the scan. ``gates``: 4 for LSTM, 3 for GRU (sizes the [H, gates*H]
    u and the [chunk, blk, gates*H] xw tile); ``seq_h_units``: total width
    of the per-step sequence buffers in multiples of H (default xw + out =
    gates + 1; the train forward adds the saved cell sequence, the
    backward roughly doubles it).

    ``kernel`` names the launch site ("lstm_sequence_fused", ...): when
    given, a MEASURED plan from the autotune cache (paddle_tpu.tune,
    ``paddle_tpu tune``) is consulted first and, when one exists for this
    exact (kernel, shape family, device_kind) and passes
    :func:`plan_is_legal`, it replaces the heuristic preference below —
    both plans run the same kernel math, so the swap changes launch
    geometry (speed) only, never outputs.

    Preference order: the WIDEST batch tile whose resident time-chunk
    still fits VMEM — the recurrent matmul is [blk, H] @ [H, gates*H] per
    step, so blk is the MXU row dimension and an 8-row tile starves the
    128-row systolic array (the measured 2.2x large-batch loss of the old
    whole-sequence-resident kernel). chunk_t < T costs one h/c HBM
    round-trip per boundary inside the same traced graph — cheap next to
    feeding the MXU 4-8x more rows.

    Mosaic tiling: the batch tile is the second-to-last block dim, so it
    must be a multiple of 8 — or equal the whole (padded) batch, i.e. a
    single grid program, which is how sub-8 batches run. Cost model
    calibrated against the chip's 16 MB scoped VMEM (measured on v5e):
    with more than one grid program Pallas double-buffers every
    batch-varying block, so the tile costs 2x; a single-program grid is
    single-buffered (which is why tiny-batch probes fit shapes that OOM
    at full batch)."""
    if seq_h_units is None:
        seq_h_units = gates + 1
    tuned = _tuned_plan(kernel, T, H, gates, seq_h_units, batch,
                        budget_bytes, double_buffer_always)
    if tuned is not None:
        return tuned
    u_bytes = H * gates * H * 4          # u resident + du accumulator
    avail = budget_bytes - 2 * u_bytes
    if avail <= 0:
        return None

    def chunk_for(blk, grid_is_1):
        per_step = blk * seq_h_units * H * 4
        if double_buffer_always or not grid_is_1:
            per_step *= 2                # double-buffered batch tiles
        return avail // per_step

    if batch is not None and batch < 8:
        chunk = chunk_for(batch, True)
        return (batch, min(T, chunk)) if chunk >= min(T, 8) else None
    for blk in (64, 32, 16):
        if batch is not None and blk > batch:
            continue
        chunk = chunk_for(blk, batch is not None and blk == batch)
        if chunk >= min(T, _CHUNK_MIN_WIDE):
            return blk, min(T, chunk)
    chunk = chunk_for(8, batch == 8)
    if chunk >= min(T, 8):
        return 8, min(T, chunk)
    return None


def _fused_bwd_plan(T: int, H: int, gates: int, seq_h_units: int,
                    batch: int,
                    budget_bytes: int = 15_500_000,
                    kernel: Optional[str] = None
                    ) -> Optional[Tuple[int, int]]:
    """(block_b, chunk_t) for the hand-written backward kernels — the SAME
    planner as :func:`_fused_plan` (one place owns the VMEM cost model and
    tile preference), always double-buffer-costed. The reverse recurrence
    splits cleanly at chunk boundaries: the saved (out, c) sequences
    provide each chunk's initial state, so the wrapper runs a few kernel
    launches instead of one. ``kernel`` (e.g. "lstm_sequence_fused_bwd")
    keys the autotune consult separately from the forward plan."""
    return _fused_plan(T, H, gates, seq_h_units, batch, budget_bytes,
                       double_buffer_always=True, kernel=kernel)


def _reverse_within_length(x: jax.Array, lengths: jax.Array) -> jax.Array:
    """Flip each sample's FIRST ``length`` steps along time; positions at
    or past length become zero. x: [B, T, ...].

    This is how ``reverse=True`` rides the forward-only fused kernels: a
    masked reverse scan over a right-padded batch is exactly a forward
    scan over the within-length-flipped input — state updates visit the
    original steps length-1..0 and frozen (t >= length) steps stay
    frozen — with the output flipped back on the way out (outputs at
    padding are zero on both sides, so the round trip is lossless).
    Ordinary gather/where, so autodiff flows through it around the fused
    kernel's custom VJP."""
    T = x.shape[1]
    idx = lengths.astype(jnp.int32)[:, None] - 1 - jnp.arange(T)[None, :]
    ok = idx >= 0                                     # [B, T]
    idx = jnp.clip(idx, 0, T - 1)
    tail = (1,) * (x.ndim - 2)
    out = jnp.take_along_axis(x, idx.reshape(idx.shape + tail), axis=1)
    return jnp.where(ok.reshape(ok.shape + tail), out,
                     jnp.zeros((), x.dtype))


def _lstm_scan(x, lengths, w, u, b, h0, c0, reverse, forget_bias):
    B, T, D = x.shape
    H = u.shape[0]
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)  # one MXU pass
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
    c = c0 if c0 is not None else jnp.zeros((B, H), x.dtype)

    def step(carry, inp):
        state = LSTMState(*carry)
        xw_t, m_t = inp
        new = lstm_cell(xw_t, state, u, b, forget_bias)
        m = m_t[:, None]
        h_n = m * new.h + (1.0 - m) * state.h
        c_n = m * new.c + (1.0 - m) * state.c
        return (h_n, c_n), m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))  # [T, B, ...]
    (h, c), ys = lax.scan(step, (h, c), xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), LSTMState(h, c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9))
def _lstm_fused(x, lens, w, u, b, h0, c0, forget_bias, block_b, chunk_t):
    """Forward through the Pallas fused kernel; under autodiff the VJP pairs
    it with the hand-written reverse-recurrence kernel
    (pallas_kernels.lstm_sequence_fused_bwd) — fused in BOTH directions,
    the training-mode discipline of the reference's hl_lstm kernels
    (hl_cuda_lstm.cu hl_lstm_parallel_backward_data/_weight)."""
    from .pallas_kernels import lstm_sequence_fused
    B, T, D = x.shape
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    return lstm_sequence_fused(xw, lens, u, b, h0=h0, c0=c0,
                               forget_bias=forget_bias, block_b=block_b,
                               chunk_t=chunk_t)


def _lstm_fused_fwd(x, lens, w, u, b, h0, c0, forget_bias, block_b, chunk_t):
    from .pallas_kernels import lstm_sequence_fused
    B, T, D = x.shape
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    out, ht, ct, c_seq = lstm_sequence_fused(
        xw, lens, u, b, h0=h0, c0=c0, forget_bias=forget_bias,
        block_b=block_b, chunk_t=chunk_t, save_cell=True)
    return (out, ht, ct), (x, lens, w, u, b, h0, c0, xw, out, c_seq)


def _lstm_fused_bwd(forget_bias, block_b, chunk_t, res, g):
    x, lens, w, u, b, h0, c0, xw, out, c_seq = res
    zero_lens = np.zeros(lens.shape, jax.dtypes.float0)
    B, T, D = x.shape
    H = u.shape[0]
    plan = _fused_bwd_plan(T, H, 4, 11, B,   # 2*(xw+dxw) + 3 H-wide seqs
                           kernel="lstm_sequence_fused_bwd")
    if plan is None:
        # VMEM won't hold even an 8-step backward tile: replay the
        # (bit-identical) scan under autodiff instead
        def replay(x, w, u, b, h0, c0):
            out, state = _lstm_scan(x, lens, w, u, b, h0, c0, False,
                                    forget_bias)
            return out, state.h, state.c

        _, vjp = jax.vjp(replay, x, w, u, b, h0, c0)
        dx, dw, du, db, dh0, dc0 = vjp(g)
        return dx, zero_lens, dw, du, db, dh0, dc0

    from .pallas_kernels import lstm_sequence_fused_bwd
    g_out, g_ht, g_ct = g
    blk, chunk = plan
    dh, dc = g_ht, g_ct
    du = jnp.zeros((H, 4 * H), jnp.float32)
    parts = []
    starts = list(range(0, T, chunk))
    for s in reversed(starts):
        e = min(T, s + chunk)
        h0_k = h0 if s == 0 else out[:, s - 1]
        c0_k = c0 if s == 0 else c_seq[:, s - 1]
        dxw_k, dh, dc, du_k = lstm_sequence_fused_bwd(
            xw[:, s:e], lens - s, u, b, h0_k, c0_k, out[:, s:e],
            c_seq[:, s:e], g_out[:, s:e], dh, dc,
            forget_bias=forget_bias, block_b=blk)
        du = du + du_k
        parts.append(dxw_k)
    dxw = parts[0] if len(parts) == 1 else jnp.concatenate(parts[::-1],
                                                           axis=1)
    dh0, dc0 = dh, dc
    G = 4 * H
    dxw2 = dxw.reshape(B * T, G).astype(jnp.float32)
    dx = jnp.matmul(dxw2, w.T.astype(jnp.float32)).reshape(x.shape)\
        .astype(x.dtype)
    dw = jnp.matmul(x.reshape(B * T, D).T.astype(jnp.float32), dxw2)\
        .astype(w.dtype)
    db = dxw2.sum(0).astype(b.dtype)
    return (dx, zero_lens, dw, du.astype(u.dtype), db, dh0.astype(h0.dtype),
            dc0.astype(c0.dtype))


_lstm_fused.defvjp(_lstm_fused_fwd, _lstm_fused_bwd)


def gru(x: jax.Array, lengths: Optional[jax.Array], w: jax.Array, u: jax.Array,
        b: Optional[jax.Array] = None, h0: Optional[jax.Array] = None,
        reverse: bool = False,
        fused: Optional[bool] = None) -> Tuple[jax.Array, jax.Array]:
    """Full-sequence GRU. x: [B, T, D]; w: [D, 3H]; u: [H, 3H].

    ``fused=True`` runs both directions through the Pallas whole-sequence
    kernels (hl_gpu_gru.cuh analog) — same contract as lstm(fused=True):
    identical math to the scan, hand-written backward kernel;
    ``fused=None`` auto-selects the kernel whenever a VMEM-legal
    (batch-tile, time-chunk) plan exists on the TPU — including
    ``reverse=True`` (the bidirectional NMT encoder), which rides the
    forward kernel via the within-length flip (see lstm())."""
    B, T, D = x.shape
    H = u.shape[0]
    if fused is None:
        fused = True
    if fused:
        from . import pallas_kernels as _pk
        from .. import obs
        plan = _fused_plan(T, H, gates=3, batch=B,
                           kernel="gru_sequence_fused")
        obs.count("kernels.routes_total", kernel="gru_sequence_fused",
                  route=("fused" if _pk._on_tpu() and plan is not None
                         else "scan"))
        if _pk._on_tpu() and plan is not None:
            obs.roofline.note_kernel_bytes(
                "gru_sequence_fused",
                obs.roofline.kernel_cost(
                    "gru_sequence_fused", batch=B, seq_len=T,
                    hidden=H, itemsize=jnp.dtype(x.dtype).itemsize))
            blk, chunk = plan
            lens = (lengths if lengths is not None
                    else jnp.full((B,), T, jnp.int32))
            b_ = b if b is not None else jnp.zeros((3 * H,), x.dtype)
            h0_ = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)
            xk = _reverse_within_length(x, lens) if reverse else x
            out, ht = _gru_fused(xk, lens, w, u, b_, h0_, blk, chunk)
            if reverse:
                out = _reverse_within_length(out, lens)
            return out, ht
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xw_t, m_t = inp
        h_new = gru_cell(xw_t, h_prev, u, b)
        m = m_t[:, None]
        h_n = m * h_new + (1.0 - m) * h_prev
        return h_n, m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))
    h, ys = lax.scan(step, h, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), h


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def _gru_fused(x, lens, w, u, b, h0, block_b, chunk_t):
    from .pallas_kernels import gru_sequence_fused
    B, T, D = x.shape
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    return gru_sequence_fused(xw, lens, u, b, h0=h0, block_b=block_b,
                              chunk_t=chunk_t)


def _gru_fused_fwd(x, lens, w, u, b, h0, block_b, chunk_t):
    from .pallas_kernels import gru_sequence_fused
    B, T, D = x.shape
    xw = jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1)
    if b is not None:
        xw = xw + b                        # kernel expects bias pre-added
    out, ht = gru_sequence_fused(xw, lens, u, None, h0=h0, block_b=block_b,
                                 chunk_t=chunk_t)
    return (out, ht), (x, lens, w, u, b, h0, xw, out)


def _gru_fused_bwd(block_b, chunk_t, res, g):
    x, lens, w, u, b, h0, xw, out = res
    zero_lens = np.zeros(lens.shape, jax.dtypes.float0)
    B, T, D = x.shape
    H = u.shape[0]
    plan = _fused_bwd_plan(T, H, 3, 8, B,    # 2*(xw+dxw) + 2 H-wide seqs
                           kernel="gru_sequence_fused_bwd")
    if plan is None:
        def replay(x, w, u, b, h0):
            return gru(x, lens, w, u, b, h0, fused=False)

        _, vjp = jax.vjp(replay, x, w, u, b, h0)
        dx, dw, du, db, dh0 = vjp(g)
        return dx, zero_lens, dw, du, db, dh0

    from .pallas_kernels import gru_sequence_fused_bwd
    g_out, g_ht = g
    blk, chunk = plan
    dh = g_ht
    du = jnp.zeros((H, 3 * H), jnp.float32)
    parts = []
    for s in reversed(range(0, T, chunk)):
        e = min(T, s + chunk)
        h0_k = h0 if s == 0 else out[:, s - 1]
        dxw_k, dh, du_k = gru_sequence_fused_bwd(
            xw[:, s:e], lens - s, u, h0_k, out[:, s:e], g_out[:, s:e], dh,
            block_b=blk)
        du = du + du_k
        parts.append(dxw_k)
    dxw = parts[0] if len(parts) == 1 else jnp.concatenate(parts[::-1],
                                                           axis=1)
    dh0 = dh
    G = 3 * H
    dxw2 = dxw.reshape(B * T, G).astype(jnp.float32)
    dx = jnp.matmul(dxw2, w.T.astype(jnp.float32)).reshape(x.shape)\
        .astype(x.dtype)
    dw = jnp.matmul(x.reshape(B * T, D).T.astype(jnp.float32), dxw2)\
        .astype(w.dtype)
    db = None if b is None else dxw2.sum(0).astype(b.dtype)
    return dx, zero_lens, dw, du.astype(u.dtype), db, dh0.astype(h0.dtype)


_gru_fused.defvjp(_gru_fused_fwd, _gru_fused_bwd)


def bidirectional(rnn_fn: Callable, x, lengths, fwd_params: dict, bwd_params: dict,
                  merge: str = "concat"):
    """Bidirectional wrapper (ref: networks.py bidirectional_lstm:553ff).

    For the reverse direction the mask-aware scan runs with reverse=True, which on
    padded-right batches is equivalent to the reference's sequence-reverse layers
    because masked steps carry state through unchanged."""
    out_f, _ = rnn_fn(x, lengths, reverse=False, **fwd_params)
    out_b, _ = rnn_fn(x, lengths, reverse=True, **bwd_params)
    if merge == "concat":
        return jnp.concatenate([out_f, out_b], axis=-1)
    if merge == "sum":
        return out_f + out_b
    raise ValueError(f"unknown merge '{merge}'")


def simple_rnn(x: jax.Array, lengths: Optional[jax.Array],
               w: Optional[jax.Array], u: jax.Array,
               b: Optional[jax.Array] = None,
               act: Callable = jnp.tanh, h0: Optional[jax.Array] = None,
               reverse: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Vanilla RNN (ref: gserver/layers/RecurrentLayer.cpp). ``w=None`` is
    the reference's recurrent_layer contract — x is already projected to
    the hidden width and only the recurrent transform U applies."""
    B, T, D = x.shape
    H = u.shape[0]
    xw = (x if w is None
          else jnp.matmul(x.reshape(B * T, D), w).reshape(B, T, -1))
    mask = (sequence_mask(lengths, T, x.dtype) if lengths is not None
            else jnp.ones((B, T), x.dtype))
    h = h0 if h0 is not None else jnp.zeros((B, H), x.dtype)

    def step(h_prev, inp):
        xw_t, m_t = inp
        h_new = act(xw_t + jnp.matmul(h_prev, u) + (b if b is not None else 0.0))
        m = m_t[:, None]
        h_n = m * h_new + (1.0 - m) * h_prev
        return h_n, m * h_n

    xs = (jnp.swapaxes(xw, 0, 1), jnp.swapaxes(mask, 0, 1))
    h, ys = lax.scan(step, h, xs, reverse=reverse)
    return jnp.swapaxes(ys, 0, 1), h


def lstm_peephole_step(xw: jax.Array, c_prev: jax.Array, w_peep: jax.Array,
                       b: Optional[jax.Array] = None,
                       forget_bias: float = 0.0) -> Tuple[jax.Array, jax.Array]:
    """One LSTM step with PRE-PROJECTED gates and peephole connections —
    the reference's LstmStepLayer (gserver/layers/LstmStepLayer.cpp,
    trainer_config_helpers/layers.py:3544 lstm_step_layer): the user's
    mixed_layer computes Wx_t + Wh_{t-1}; this step only adds the
    c_{t-1}/c_t peephole terms, bias, and the cell recurrence.

        i = sigmoid(g_i + w_ci * c_prev + b_i)
        f = sigmoid(g_f + w_cf * c_prev + b_f [+ forget_bias])
        c = f * c_prev + i * tanh(g_c + b_c)
        o = sigmoid(g_o + w_co * c + b_o)      # peeps at the NEW cell
        h = o * tanh(c)

    xw: [B, 4H] packed (i, f, c, o); w_peep: [3, H] packed (ci, cf, co).
    Returns (h, c).
    """
    H = c_prev.shape[-1]
    gi, gf, gc, go = (xw[..., :H], xw[..., H:2 * H], xw[..., 2 * H:3 * H],
                      xw[..., 3 * H:])
    if b is not None:
        bi, bf, bc, bo = (b[..., :H], b[..., H:2 * H], b[..., 2 * H:3 * H],
                          b[..., 3 * H:])
        gi, gf, gc, go = gi + bi, gf + bf, gc + bc, go + bo
    i = jax.nn.sigmoid(gi + c_prev * w_peep[0])
    f = jax.nn.sigmoid(gf + c_prev * w_peep[1] + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    o = jax.nn.sigmoid(go + c * w_peep[2])
    return o * jnp.tanh(c), c
