"""Sequence ops over padded LoD batches.

Replaces the reference's sequence-aware layer/op family: SequencePoolLayer
(gserver/layers/SequencePoolLayer.cpp: max/average/sum/last/first over sequences),
sequence_expand (operators/seq_expand_op.cc), sequence_concat/slice
(SequenceConcatLayer.cpp, SequenceSliceLayer.cpp), sequence_conv
(operators/sequence_conv_op.cc + ContextProjection function/ContextProjectionOp.cpp),
sequence_reverse, and the first/last-instance layers. All take (data [B, T, ...],
lengths [B]) in place of LoD offsets.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.lod import sequence_mask


def _mask(x, lengths, fill=0.0):
    m = sequence_mask(lengths, x.shape[1], jnp.bool_)
    m = m.reshape(m.shape + (1,) * (x.ndim - 2))
    return jnp.where(m, x, fill), m


def sequence_pool(x: jax.Array, lengths: jax.Array, pool_type: str = "average") -> jax.Array:
    """[B, T, D] -> [B, D]. pool_type: average|sum|max|min|sqrt|last|first
    (ref: SequencePoolLayer.cpp, operators/sequence_pool_op.cc)."""
    n = jnp.maximum(lengths.astype(x.dtype), 1)
    shape_n = n.reshape((-1,) + (1,) * (x.ndim - 2))
    if pool_type in ("average", "avg"):
        xm, _ = _mask(x, lengths)
        return jnp.sum(xm, axis=1) / shape_n
    if pool_type == "sum":
        xm, _ = _mask(x, lengths)
        return jnp.sum(xm, axis=1)
    if pool_type == "sqrt":
        xm, _ = _mask(x, lengths)
        return jnp.sum(xm, axis=1) / jnp.sqrt(shape_n)
    empty = (lengths == 0).reshape((-1,) + (1,) * (x.ndim - 2))
    if pool_type == "max":
        xm, _ = _mask(x, lengths, fill=-jnp.inf)
        # length-0 rows (nested-seq padding) pool to 0, not -inf — an inf here
        # turns into NaN the moment a mask multiplies it
        return jnp.where(empty, 0.0, jnp.max(xm, axis=1))
    if pool_type == "min":
        xm, _ = _mask(x, lengths, fill=jnp.inf)
        return jnp.where(empty, 0.0, jnp.min(xm, axis=1))
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1
        )[:, 0]
    if pool_type == "first":
        return x[:, 0]
    raise ValueError(f"unknown pool_type '{pool_type}'")


def sequence_last_step(x, lengths):
    return sequence_pool(x, lengths, "last")


def sequence_first_step(x, lengths):
    return sequence_pool(x, lengths, "first")


def sequence_expand(x: jax.Array, ref_lengths: jax.Array, max_len: int) -> jax.Array:
    """Broadcast one vector per sequence across its timesteps:
    [B, D] -> [B, T, D] masked to ref lengths (ref: seq_expand_op.cc / ExpandLayer)."""
    out = jnp.broadcast_to(x[:, None, :], (x.shape[0], max_len, x.shape[-1]))
    m = sequence_mask(ref_lengths, max_len, x.dtype)
    return out * m[..., None]


def sequence_reverse(x: jax.Array, lengths: jax.Array) -> jax.Array:
    """Reverse each sequence's valid prefix in place, keep padding at the tail
    (ref: gserver SequenceReverseLayer / operators/sequence_reverse semantics)."""
    B, T = x.shape[0], x.shape[1]
    pos = jnp.arange(T)
    # index j of reversed: maps to length-1-j for j < len else j (identity on padding)
    idx = jnp.where(pos[None, :] < lengths[:, None],
                    jnp.maximum(lengths[:, None] - 1 - pos[None, :], 0),
                    pos[None, :])
    return jnp.take_along_axis(x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)


def sequence_slice(x: jax.Array, lengths: jax.Array, offset: jax.Array,
                   length: jax.Array, max_out: int) -> jax.Array:
    """Per-sequence subsequence extraction (ref: SequenceSliceLayer.cpp).

    offset/length: [B] per-sequence start and new length; output padded to max_out."""
    B, T = x.shape[0], x.shape[1]
    pos = jnp.arange(max_out)
    src = offset[:, None] + pos[None, :]
    src = jnp.clip(src, 0, T - 1)
    out = jnp.take_along_axis(x, src.reshape(src.shape + (1,) * (x.ndim - 2)).astype(jnp.int32), axis=1)
    m = (pos[None, :] < length[:, None])
    return jnp.where(m.reshape(m.shape + (1,) * (x.ndim - 2)), out, 0.0)


def sequence_concat(a: jax.Array, la: jax.Array, b: jax.Array, lb: jax.Array,
                    max_out: Optional[int] = None):
    """Concatenate sequences pairwise in time (ref: SequenceConcatLayer.cpp).

    Returns (data [B, max_out, D], lengths la+lb)."""
    B, Ta = a.shape[0], a.shape[1]
    Tb = b.shape[1]
    T = max_out if max_out is not None else Ta + Tb
    lengths = la + lb
    pos = jnp.arange(T)
    in_a = pos[None, :] < la[:, None]
    idx_a = jnp.clip(pos[None, :], 0, Ta - 1)
    idx_b = jnp.clip(pos[None, :] - la[:, None], 0, Tb - 1)
    ga = jnp.take_along_axis(a, idx_a.reshape(idx_a.shape + (1,) * (a.ndim - 2)).astype(jnp.int32), axis=1)
    gb = jnp.take_along_axis(b, idx_b.reshape(idx_b.shape + (1,) * (b.ndim - 2)).astype(jnp.int32), axis=1)
    sel = in_a.reshape(in_a.shape + (1,) * (a.ndim - 2))
    out = jnp.where(sel, ga, gb)
    valid = pos[None, :] < lengths[:, None]
    out = jnp.where(valid.reshape(valid.shape + (1,) * (a.ndim - 2)), out, 0.0)
    return out, lengths


def context_projection(x: jax.Array, lengths: jax.Array, context_start: int,
                       context_length: int, w: Optional[jax.Array] = None) -> jax.Array:
    """Sliding context-window concat (ref: function/ContextProjectionOp.cpp,
    gserver ContextProjection; the core of sequence_conv).

    [B, T, D] -> [B, T, context_length*D]; out-of-range steps zero-padded (or taken
    from trainable boundary weights w [pad_rows, D] like the reference's
    trainable_padding)."""
    B, T, D = x.shape
    valid0 = sequence_mask(lengths, T, x.dtype)
    cols = []
    for c in range(context_start, context_start + context_length):
        if c == 0:
            cols.append(x * valid0[..., None])
            continue
        shifted = jnp.roll(x, -c, axis=1)
        pos = jnp.arange(T)
        valid = (pos[None, :] + c >= 0) & (pos[None, :] + c < lengths[:, None])
        shifted = jnp.where(valid[..., None], shifted, 0.0)
        if w is not None:
            # trainable boundary rows: row index within the padding block
            if c < 0:
                pad_row = jnp.clip(pos[None, :] + c + (-context_start), 0, w.shape[0] - 1)
                use_pad = (pos[None, :] + c < 0)
            else:
                over = pos[None, :] + c - lengths[:, None]
                pad_row = jnp.clip((-context_start) + over, 0, w.shape[0] - 1)
                use_pad = (pos[None, :] + c >= lengths[:, None]) & (pos[None, :] < lengths[:, None])
            padv = w[pad_row]
            shifted = jnp.where(use_pad[..., None], padv, shifted)
        # mask the DESTINATION position too: padding timesteps stay zero even for
        # negative offsets / trainable pad rows (padded-batch invariant)
        cols.append(shifted * valid0[..., None])
    return jnp.concatenate(cols, axis=-1)


def sequence_conv(x: jax.Array, lengths: jax.Array, filt: jax.Array,
                  context_start: int = -1, context_length: int = 3,
                  b: Optional[jax.Array] = None) -> jax.Array:
    """Sequence convolution = context projection + matmul
    (ref: operators/sequence_conv_op.cc). filt: [context_length*D, H]."""
    ctx = context_projection(x, lengths, context_start, context_length)
    out = jnp.einsum("btd,dh->bth", ctx, filt)
    if b is not None:
        out = out + b
    m = sequence_mask(lengths, x.shape[1], out.dtype)
    return out * m[..., None]


# =============================================================================
# Nested-sequence (2-level LoD) ops — sub-sequence pooling/expansion and the
# nested scan group (gserver SubNestedSequence / sequence_nest_rnn configs,
# config_parser.py:319-387; Argument.h:84-90 subSequenceStartPositions).
# Pattern: drop to inner_flat() for the single-level op, lift back via outer().
# =============================================================================

from ..core.lod import NestedSeqBatch  # noqa: E402


def nested_seq_pool(nb: NestedSeqBatch, pool_type: str = "average"):
    """Pool each sub-sequence -> SeqBatch [B, S, ...] over sub-sequence
    summaries (the inner step of a nested recurrent_group that feeds the
    outer group)."""
    flat = nb.inner_flat()
    pooled = sequence_pool(flat.data, flat.lengths, pool_type)
    return nb.outer(pooled)


def nested_last_step(nb: NestedSeqBatch):
    flat = nb.inner_flat()
    return nb.outer(sequence_last_step(flat.data, flat.lengths))


def nested_first_step(nb: NestedSeqBatch):
    flat = nb.inner_flat()
    return nb.outer(sequence_first_step(flat.data, flat.lengths))


def sub_seq_expand(outer_vals: jax.Array, nb: NestedSeqBatch) -> jax.Array:
    """Broadcast one value per sub-sequence [B, S, D] to every inner step
    [B, S, T, D], zeroed on invalid steps (SequenceExpand at the sub-seq
    level — e.g. handing an outer memory to every word of a sentence)."""
    tiled = jnp.broadcast_to(outer_vals[:, :, None],
                             outer_vals.shape[:2] + (nb.max_sublen,)
                             + outer_vals.shape[2:])
    m = nb.inner_mask(tiled.dtype)
    return tiled * m.reshape(m.shape + (1,) * (tiled.ndim - 3))


def nested_rnn(rnn_fn, nb: NestedSeqBatch, *args, **kwargs):
    """Run a single-level masked RNN (ops.rnn.lstm / gru / simple_rnn)
    independently over every sub-sequence: state does NOT flow across
    sub-sequence boundaries — exactly the nested recurrent_group semantics
    the reference tests in sequence_nest_rnn*.py (each inner group restarts
    from its boot memory).

    Returns (outputs as [B, S, T, H], last-state lifted to [B, S, H] SeqBatch).
    """
    flat = nb.inner_flat()
    out, last = rnn_fn(flat.data, flat.lengths, *args, **kwargs)
    B, S = nb.batch_size, nb.max_subseqs
    out_n = out.reshape((B, S) + out.shape[1:])
    h = last.h if hasattr(last, "h") else last
    return out_n, nb.outer(h)


def kmax_seq_score(scores: jax.Array, lengths: jax.Array,
                   k: int) -> jax.Array:
    """Indices of the k highest-scoring positions per sequence, padding
    masked out (KmaxSeqScoreLayer, gserver/layers/KmaxSeqScoreLayer.cpp /
    trainer_config_helpers/layers.py:6927). scores: [B, T] (or [B, T, 1]);
    returns int32 [B, k], positions beyond a sequence's true length never
    selected (they score -inf; for length < k the tail indices repeat the
    mask's argmin — callers gate on lengths as the reference's beam code
    did)."""
    if scores.ndim == 3:
        scores = scores[..., 0]
    T = scores.shape[1]
    mask = sequence_mask(lengths, T, scores.dtype)
    masked = jnp.where(mask > 0, scores, -jnp.inf)
    _, idx = jax.lax.top_k(masked, k)
    return idx.astype(jnp.int32)


def sub_nested_seq(x: jax.Array, sub_lengths: jax.Array,
                   indices: jax.Array):
    """Select sub-sequences of a nested sequence by per-sample indices
    (SubNestedSequenceLayer, layers.py:6781 — the beam-training trim).

    x: [B, S, T, ...]; sub_lengths: [B, S]; indices: [B, K] int. Returns
    (x_out [B, K, T, ...], sub_lengths_out [B, K]). Indices are clamped to
    the valid sub-sequence range, matching the defensive clipping of the
    reference's CPU gather."""
    S = x.shape[1]
    idx = jnp.clip(indices.astype(jnp.int32), 0, S - 1)
    gather = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    sub_out = jnp.take_along_axis(sub_lengths, idx, axis=1)
    return gather, sub_out
