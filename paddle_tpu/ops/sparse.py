"""Sparse-row gradients (SelectedRows) and embedding update path.

The reference's sparse story: ``SelectedRows`` (framework/selected_rows.h) carries
{rows, value} for gradients touching few rows of a big table;
``SparseRowCpuMatrix``/``SparseAutoGrowRowCpuMatrix`` (math/SparseRowMatrix.h) back
sparse SGD, and the remote path ships only touched rows
(trainer/RemoteParameterUpdater.h:265 SparseRemoteParameterUpdater,
pserver getParameterSparse).

TPU-native design (SURVEY §7): embedding tables live sharded on HBM; the "sparse
gradient" is (ids, grad_rows) pairs and the optimizer applies a row-gathered update
with scatter-add HLO — no pserver. For tables larger than HBM,
:mod:`paddle_tpu.runtime.host_embedding` keeps the master table in host memory
(native HostOptimizer storage) and streams only each batch's touched rows to the
device, with an exactness-preserving overlapped prefetcher.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import jax
from jax.experimental import sparse as jsparse
import jax.numpy as jnp


@jax.tree_util.register_pytree_node_class
@dataclass
class SelectedRows:
    """Sparse gradient: values [K, D] at row indices rows [K] of a [N, D] table."""

    rows: jax.Array
    values: jax.Array
    height: int  # static: number of rows of the dense table

    def tree_flatten(self):
        return (self.rows, self.values), self.height

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    def to_dense(self) -> jax.Array:
        out = jnp.zeros((self.height, self.values.shape[-1]), self.values.dtype)
        return out.at[self.rows].add(self.values)


def embedding_grad_rows(ids: jax.Array, out_grad: jax.Array, height: int
                        ) -> SelectedRows:
    """Build the SelectedRows gradient of an embedding lookup: one row per lookup
    (duplicate ids intentionally kept — scatter-add merges them, matching
    SelectedRows semantics of repeated rows)."""
    flat_ids = ids.reshape(-1)
    flat_g = out_grad.reshape(-1, out_grad.shape[-1])
    return SelectedRows(flat_ids, flat_g, height)


def sgd_sparse_update(table: jax.Array, grad: SelectedRows, lr) -> jax.Array:
    """Row-sparse SGD (ref: operators/sgd_op.cc SelectedRows branch)."""
    return table.at[grad.rows].add(-lr * grad.values)


def adagrad_sparse_update(table: jax.Array, moment: jax.Array, grad: SelectedRows,
                          lr, eps: float = 1e-6) -> Tuple[jax.Array, jax.Array]:
    """Row-sparse Adagrad (ref: operators/adagrad_op.cc sparse kernel): merge
    duplicate rows first, then update each touched row once.

    Duplicate-row merge goes through a dense scatter-add (static shapes rule out a
    dynamic unique()); the per-row gather/sets after it are idempotent across
    duplicates, so each touched row is updated exactly once with the merged grad."""
    merged = grad.to_dense()                     # [N, D]; sums duplicate rows
    g_rows = merged[grad.rows]                   # [K, D] merged grad per touched row
    new_m_rows = moment[grad.rows] + jnp.square(g_rows)
    moment = moment.at[grad.rows].set(new_m_rows)
    step = -lr * g_rows / (jnp.sqrt(new_m_rows) + eps)
    table = table.at[grad.rows].set(table[grad.rows] + step)
    return table, moment


def lookup_table(table: jax.Array, ids: jax.Array,
                 padding_idx: int = None) -> jax.Array:
    """Embedding lookup (ref: operators/lookup_table_op.cc). Forward for both the
    dense-autodiff path and the manual sparse path."""
    out = jnp.take(table, ids, axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return out


# ---------------------------------------------------------------------------
# General sparse matrices (CSR/CSC/COO) — the paddle/math sparse layer beyond
# row-sparse gradients: CpuSparseMatrix/SparseMatrix (math/CpuSparseMatrix.h,
# SparseMatrix.h) carried CSR/CSC value + non-value formats for sparse
# input features and sparse matmuls. TPU-native: jax.experimental.sparse
# BCOO (batched COO, the XLA-friendly format) with CSR-style constructors;
# matmuls lower to gather+segment ops the compiler fuses.
# ---------------------------------------------------------------------------

def csr_matrix(values, col_ids, row_ptr, shape) -> "jsparse.BCOO":
    """Build a sparse matrix from CSR arrays (CpuSparseMatrix CSR format;
    non-value format = pass values of all ones)."""
    import numpy as np
    values = jnp.asarray(values)
    col_ids = np.asarray(col_ids)
    row_ptr = np.asarray(row_ptr)
    rows = np.repeat(np.arange(len(row_ptr) - 1), np.diff(row_ptr))
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(col_ids, jnp.int32)], axis=1)
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def csc_matrix(values, row_ids, col_ptr, shape) -> "jsparse.BCOO":
    """CSC constructor (CpuSparseMatrix CSC format)."""
    import numpy as np
    values = jnp.asarray(values)
    row_ids = np.asarray(row_ids)
    col_ptr = np.asarray(col_ptr)
    cols = np.repeat(np.arange(len(col_ptr) - 1), np.diff(col_ptr))
    idx = jnp.stack([jnp.asarray(row_ids, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def coo_matrix(values, rows, cols, shape) -> "jsparse.BCOO":
    values = jnp.asarray(values)
    idx = jnp.stack([jnp.asarray(rows, jnp.int32),
                     jnp.asarray(cols, jnp.int32)], axis=1)
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def sparse_dense_matmul(sp: "jsparse.BCOO", dense: jax.Array) -> jax.Array:
    """sp @ dense (Matrix::mul with a sparse lhs — the sparse-input fc path
    of CpuSparseMatrix). Differentiable w.r.t. both operands."""
    return sp @ dense


def dense_sparse_matmul(dense: jax.Array, sp: "jsparse.BCOO") -> jax.Array:
    """dense @ sp (sparse rhs)."""
    return dense @ sp


def sparse_to_dense(sp: "jsparse.BCOO") -> jax.Array:
    return sp.todense()


def dense_to_bcoo(x: jax.Array, nse: int = None) -> "jsparse.BCOO":
    """Sparsify a dense matrix (test/construction helper)."""
    return jsparse.BCOO.fromdense(x, nse=nse)
