from .lr_scheduler import (constant_lr, exponential_decay, inverse_time_decay,
                           linear_warmup, natural_exp_decay, piecewise_decay,
                           poly_decay, discexp_lr)
from .hooks import HookSet, ParameterHook, PruningHook, StaticHook
from .optimizers import (SGD, Adadelta, Adagrad, Adam, Adamax, DecayedAdagrad,
                         Ftrl, Momentum, Optimizer, ProximalGD, RMSProp,
                         ParameterAverager)
from .clip import clip_by_global_norm, clip_by_norm, clip_by_value

__all__ = [
    "HookSet", "ParameterHook", "PruningHook", "StaticHook",
    "Optimizer", "SGD", "Momentum", "Adagrad", "DecayedAdagrad", "Adadelta",
    "RMSProp", "Adam", "Adamax", "ProximalGD", "Ftrl", "ParameterAverager",
    "constant_lr", "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "poly_decay", "piecewise_decay", "linear_warmup", "discexp_lr",
    "clip_by_value", "clip_by_norm", "clip_by_global_norm",
]
