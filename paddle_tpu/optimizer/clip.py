"""Gradient clipping — ref: parameter/FirstOrderOptimizer.h:346 (OptimizerWithGradientClipping),
operators/clip_op.cc, fluid GradientClipByGlobalNorm."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clip_by_value(grads, min_val: float, max_val: float):
    return jax.tree_util.tree_map(lambda g: jnp.clip(g, min_val, max_val), grads)


def clip_by_norm(grads, max_norm: float):
    from ..ops.math import clip_by_norm as _clip_one
    return jax.tree_util.tree_map(lambda g: _clip_one(g, max_norm), grads)


def global_norm(grads):
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g)) for g in leaves))


def clip_by_global_norm(grads, max_norm: float):
    n = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(n, 1e-12))
    return jax.tree_util.tree_map(lambda g: g * scale, grads)
