"""Parameter-update hooks — the ParameterUpdaterHook.cpp re-provision.

The reference attaches hooks per parameter via ParameterAttr(update_hooks=):
* static parameters (is_static: excluded from updates — frozen embeddings,
  pretrained feature towers);
* StaticPruningHook: a magnitude mask fixed at init (keep the largest
  (1 - sparsity_ratio) fraction) applied after every update, so pruned
  entries stay zero through training.

TPU-native: hooks are pure functions composed into the optimizer's jitted
update (no host round trips); attachment is by parameter-path regex, matching
how parallel.ShardingRules target parameters.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp


def path_str(path) -> str:
    """KeyPath -> 'l1/w' style string (same form ShardingRules matches)."""
    parts = []
    for e in path:
        k = getattr(e, "key", None)
        if k is None:
            k = getattr(e, "idx", e)
        parts.append(str(k))
    return "/".join(parts)


class ParameterHook:
    """Base hook: optional per-parameter state + post-update transform."""

    def init_state(self, p: jax.Array) -> Dict[str, jax.Array]:
        return {}

    def apply(self, p_new: jax.Array, p_old: jax.Array,
              hook_state: Dict[str, jax.Array]) -> jax.Array:
        return p_new


class StaticHook(ParameterHook):
    """Frozen parameter (ParameterConfig.is_static): the update is discarded.

    Slot state still advances benignly; the parameter value never moves."""

    def apply(self, p_new, p_old, hook_state):
        return p_old


class PruningHook(ParameterHook):
    """StaticPruningHook: magnitude mask computed ONCE from the initial
    values; masked entries are forced to zero after every update."""

    def __init__(self, sparsity_ratio: float = 0.75):
        if not 0.0 <= sparsity_ratio < 1.0:
            raise ValueError("sparsity_ratio in [0, 1)")
        self.sparsity_ratio = sparsity_ratio

    def init_state(self, p):
        k = int(p.size * self.sparsity_ratio)
        if k == 0:
            mask = jnp.ones_like(p)
        else:
            # exact-k by index: magnitude ties at the threshold (e.g. a
            # zero-heavy init) must not over-prune — a threshold compare
            # would mask an all-zero parameter entirely and freeze it
            order = jnp.argsort(jnp.abs(p).ravel())   # ascending
            mask = jnp.ones((p.size,), p.dtype).at[order[:k]].set(0)
            mask = mask.reshape(p.shape)
        return {"mask": mask}

    def apply(self, p_new, p_old, hook_state):
        return p_new * hook_state["mask"]


class HookSet:
    """(pattern, hook) rules; first match wins — attach with
    ``Optimizer(..., hooks=HookSet([(r"embed/w$", StaticHook())]))``."""

    def __init__(self, rules: List[Tuple[str, ParameterHook]]):
        self.rules = [(re.compile(pat), h) for pat, h in rules]

    def match(self, path) -> Optional[ParameterHook]:
        s = path_str(path)
        for pat, h in self.rules:
            if pat.search(s):
                return h
        return None
