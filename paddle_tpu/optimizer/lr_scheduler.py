"""Learning-rate schedules.

Analog of the reference's LR schedulers (paddle/parameter/LearningRateScheduler.cpp —
registered types: constant, poly, caffe_poly, exp, discexp, linear, manual, pass_manual)
and fluid's learning_rate_decay functions. Each schedule is a pure fn step -> lr scale,
usable inside jit (step is a traced scalar).
"""

from __future__ import annotations

import jax.numpy as jnp


def constant_lr(lr: float):
    def sched(step):
        return jnp.asarray(lr, jnp.float32)
    return sched


def exponential_decay(lr: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False):
    def sched(step):
        p = step / decay_steps
        if staircase:
            p = jnp.floor(p)
        return lr * jnp.power(decay_rate, p)
    return sched


def natural_exp_decay(lr: float, decay_steps: int, decay_rate: float,
                      staircase: bool = False):
    def sched(step):
        p = step / decay_steps
        if staircase:
            p = jnp.floor(p)
        return lr * jnp.exp(-decay_rate * p)
    return sched


def inverse_time_decay(lr: float, decay_steps: int, decay_rate: float,
                       staircase: bool = False):
    def sched(step):
        p = step / decay_steps
        if staircase:
            p = jnp.floor(p)
        return lr / (1.0 + decay_rate * p)
    return sched


def poly_decay(lr: float, decay_steps: int, end_lr: float = 1e-4, power: float = 1.0,
               cycle: bool = False):
    def sched(step):
        if cycle:
            decay = decay_steps * jnp.maximum(1.0, jnp.ceil(step / decay_steps))
        else:
            decay = decay_steps
        s = jnp.minimum(step.astype(jnp.float32) if hasattr(step, "astype") else float(step), decay)
        return (lr - end_lr) * jnp.power(1.0 - s / decay, power) + end_lr
    return sched


def piecewise_decay(boundaries, values):
    def sched(step):
        lr = jnp.asarray(values[0], jnp.float32)
        for b, v in zip(boundaries, values[1:]):
            lr = jnp.where(step >= b, v, lr)
        return lr
    return sched


def discexp_lr(lr: float, decay_rate: float, decay_steps: int):
    """gen-1 'discexp': lr * decay_rate^floor(step/decay_steps)
    (ref: LearningRateScheduler.cpp discexp)."""
    return exponential_decay(lr, decay_steps, decay_rate, staircase=True)


def linear_warmup(base_sched, warmup_steps: int, start_frac: float = 0.0):
    def sched(step):
        warm = start_frac + (1.0 - start_frac) * (step / max(warmup_steps, 1))
        return jnp.where(step < warmup_steps, warm, 1.0) * base_sched(step)
    return sched
