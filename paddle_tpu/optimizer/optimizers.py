"""First-order optimizers.

Re-provides the reference's optimizer zoo:
* gen-1 ``ParameterOptimizer`` hierarchy (paddle/parameter/FirstOrderOptimizer.h —
  SGD:24, SparseMomentum:63, AdaGrad:111, AdaDelta:141, RMSProp:167,
  DecayedAdaGrad:210, Adam:255, AdaMax:290) and ``AverageOptimizer``
  (AverageOptimizer.cpp, parameter averaging);
* gen-2 optimizer operators (operators/{sgd,momentum,adam,adamax,adagrad,adadelta,
  decayed_adagrad,rmsprop,proximal_gd,proximal_adagrad,ftrl}_op.cc) and the standalone
  C-ABI optimizer lib (paddle/optimizer/*.cc) used by the Go pserver.

Design: functional update — ``init(params) -> state``, ``update(grads, state, params,
step) -> (new_params, new_state)``. The whole update is one fused XLA computation (the
reference needed hand-written TrainingAlgorithmOp.cu kernels for this). L1/L2
regularization (parameter/Regularizer.cpp) and clipping compose as pre-update hooks.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import clip as clip_mod

Params = Any
State = Dict[str, Any]
tmap = jax.tree_util.tree_map


def _is_stat_path(path) -> bool:
    """True if a pytree path goes through a "stats" dict key (nn.Module.stat)."""
    for entry in path:
        if getattr(entry, "key", None) == "stats":
            return True
    return False


def _sched(lr):
    if callable(lr):
        return lr
    return lambda step: jnp.asarray(lr, jnp.float32)


class Optimizer:
    """Base: handles lr schedule, weight decay (L2), L1, and clipping."""

    def __init__(self, learning_rate=0.01, weight_decay: float = 0.0,
                 l1_decay: float = 0.0, grad_clip: Optional[Tuple[str, float]] = None,
                 hooks=None):
        self.lr = _sched(learning_rate)
        self.weight_decay = weight_decay
        self.l1_decay = l1_decay
        self.grad_clip = grad_clip
        self.hooks = hooks          # optimizer.hooks.HookSet or None

    # -- subclass API ---------------------------------------------------
    def init_slot(self, p: jax.Array) -> Dict[str, jax.Array]:
        return {}

    def apply_one(self, p, g, slot, lr, step) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    # -- public ---------------------------------------------------------
    def init(self, params: Params) -> State:
        slots = tmap(lambda p: self.init_slot(p), params)
        state = {"step": jnp.zeros((), jnp.int32), "slots": slots}
        if self.hooks is not None:
            flat_p, treedef = jax.tree_util.tree_flatten_with_path(params)
            hook_states = []
            for path, p in flat_p:
                h = self.hooks.match(path)
                hook_states.append(h.init_state(p) if h is not None else {})
            state["hooks"] = jax.tree_util.tree_unflatten(treedef, hook_states)
        return state

    def _preprocess(self, grads, params):
        if self.weight_decay:
            grads = tmap(lambda g, p: g + self.weight_decay * p, grads, params)
        if self.l1_decay:
            grads = tmap(lambda g, p: g + self.l1_decay * jnp.sign(p), grads, params)
        if self.grad_clip is not None:
            kind, val = self.grad_clip
            if kind == "value":
                grads = clip_mod.clip_by_value(grads, -val, val)
            elif kind == "norm":
                grads = clip_mod.clip_by_norm(grads, val)
            elif kind == "global_norm":
                grads = clip_mod.clip_by_global_norm(grads, val)
            else:
                raise ValueError(f"unknown clip kind {kind}")
        return grads

    def update(self, grads: Params, state: State, params: Params) -> Tuple[Params, State]:
        """Apply one update. Leaves under a ``"stats"`` key (non-trainable running
        state, see nn.Module.stat) pass through untouched — no decay, no slots."""
        step = state["step"] + 1
        lr = self.lr(step.astype(jnp.float32))
        grads = self._preprocess(grads, params)

        flat_p, treedef = jax.tree_util.tree_flatten_with_path(params)
        flat_g = jax.tree_util.tree_flatten(grads)[0]
        flat_s = treedef.flatten_up_to(state["slots"])
        flat_h = (treedef.flatten_up_to(state["hooks"])
                  if self.hooks is not None and "hooks" in state else None)
        new_p, new_s = [], []
        for i, ((path, p), g, s) in enumerate(zip(flat_p, flat_g, flat_s)):
            if _is_stat_path(path):
                new_p.append(p)
                new_s.append(s)
                continue
            np_, ns_ = self.apply_one(p, g, s, lr, step)
            if flat_h is not None:
                h = self.hooks.match(path)
                if h is not None:
                    np_ = h.apply(np_, p, flat_h[i])
            new_p.append(np_)
            new_s.append(ns_)
        out_state = {"step": step,
                     "slots": jax.tree_util.tree_unflatten(treedef, new_s)}
        if "hooks" in state:
            out_state["hooks"] = state["hooks"]
        return jax.tree_util.tree_unflatten(treedef, new_p), out_state


class SGD(Optimizer):
    """Plain SGD (ref: FirstOrderOptimizer.h:24 SgdOptimizer; operators/sgd_op.cc)."""

    def apply_one(self, p, g, slot, lr, step):
        return p - lr * g, slot


class Momentum(Optimizer):
    """Momentum/Nesterov (ref: operators/momentum_op.cc; gen-1 momentum is folded into
    SgdOptimizer via ParameterConfig.momentum)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.mu = momentum
        self.nesterov = use_nesterov

    def init_slot(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        v = self.mu * slot["velocity"] + g
        if self.nesterov:
            p = p - lr * (g + self.mu * v)
        else:
            p = p - lr * v
        return p, {"velocity": v}


class Adagrad(Optimizer):
    """ref: FirstOrderOptimizer.h:111 AdagradParameterOptimizer;
    operators/adagrad_op.cc."""

    def __init__(self, learning_rate=0.01, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.eps = epsilon

    def init_slot(self, p):
        return {"moment": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        m = slot["moment"] + jnp.square(g)
        p = p - lr * g / (jnp.sqrt(m) + self.eps)
        return p, {"moment": m}


class DecayedAdagrad(Optimizer):
    """ref: FirstOrderOptimizer.h:210 DecayedAdagradParameterOptimizer;
    operators/decayed_adagrad_op.cc."""

    def __init__(self, learning_rate=0.01, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.eps = decay, epsilon

    def init_slot(self, p):
        return {"moment": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        m = self.decay * slot["moment"] + (1.0 - self.decay) * jnp.square(g)
        p = p - lr * g / (jnp.sqrt(m) + self.eps)
        return p, {"moment": m}


class Adadelta(Optimizer):
    """ref: FirstOrderOptimizer.h:141 AdaDeltaParameterOptimizer;
    operators/adadelta_op.cc."""

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.eps = rho, epsilon

    def init_slot(self, p):
        return {"avg_sq_grad": jnp.zeros_like(p), "avg_sq_update": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        asg = self.rho * slot["avg_sq_grad"] + (1.0 - self.rho) * jnp.square(g)
        upd = jnp.sqrt(slot["avg_sq_update"] + self.eps) / jnp.sqrt(asg + self.eps) * g
        asu = self.rho * slot["avg_sq_update"] + (1.0 - self.rho) * jnp.square(upd)
        return p - lr * upd, {"avg_sq_grad": asg, "avg_sq_update": asu}


class RMSProp(Optimizer):
    """ref: FirstOrderOptimizer.h:167 RMSPropParameterOptimizer;
    operators/rmsprop_op.cc (with momentum slot)."""

    def __init__(self, learning_rate=0.01, rho=0.95, epsilon=1e-6, momentum=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.eps, self.mu = rho, epsilon, momentum

    def init_slot(self, p):
        return {"mean_square": jnp.zeros_like(p), "moment": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        ms = self.rho * slot["mean_square"] + (1.0 - self.rho) * jnp.square(g)
        mom = self.mu * slot["moment"] + lr * g / jnp.sqrt(ms + self.eps)
        return p - mom, {"mean_square": ms, "moment": mom}


class Adam(Optimizer):
    """ref: FirstOrderOptimizer.h:255 AdamParameterOptimizer; operators/adam_op.cc."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def init_slot(self, p):
        return {"m": jnp.zeros_like(p), "v": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        t = step.astype(jnp.float32)
        m = self.b1 * slot["m"] + (1.0 - self.b1) * g
        v = self.b2 * slot["v"] + (1.0 - self.b2) * jnp.square(g)
        mhat = m / (1.0 - jnp.power(self.b1, t))
        vhat = v / (1.0 - jnp.power(self.b2, t))
        return p - lr * mhat / (jnp.sqrt(vhat) + self.eps), {"m": m, "v": v}


class Adamax(Optimizer):
    """ref: FirstOrderOptimizer.h:290 AdamaxParameterOptimizer;
    operators/adamax_op.cc."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def init_slot(self, p):
        return {"m": jnp.zeros_like(p), "u": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        t = step.astype(jnp.float32)
        m = self.b1 * slot["m"] + (1.0 - self.b1) * g
        u = jnp.maximum(self.b2 * slot["u"], jnp.abs(g))
        p = p - lr / (1.0 - jnp.power(self.b1, t)) * m / (u + self.eps)
        return p, {"m": m, "u": u}


class ProximalGD(Optimizer):
    """ref: operators/proximal_gd_op.cc — L1/L2 proximal step."""

    def __init__(self, learning_rate=0.01, l1: float = 0.0, l2: float = 0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2 = l1, l2

    def apply_one(self, p, g, slot, lr, step):
        prox = p - lr * g
        if self.l1 > 0:
            prox = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * self.l1, 0.0)
        return prox / (1.0 + lr * self.l2), slot


class Ftrl(Optimizer):
    """ref: operators/ftrl_op.cc."""

    def __init__(self, learning_rate=0.01, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def init_slot(self, p):
        return {"squared": jnp.zeros_like(p), "linear": jnp.zeros_like(p)}

    def apply_one(self, p, g, slot, lr, step):
        n, z = slot["squared"], slot["linear"]
        n_new = n + jnp.square(g)
        sigma = (jnp.power(n_new, -self.lr_power) - jnp.power(jnp.maximum(n, 1e-38), -self.lr_power)) / lr
        z_new = z + g - sigma * p
        denom = (jnp.power(n_new, -self.lr_power)) / lr + 2.0 * self.l2
        p_new = jnp.where(
            jnp.abs(z_new) > self.l1,
            -(z_new - jnp.sign(z_new) * self.l1) / denom,
            0.0)
        return p_new, {"squared": n_new, "linear": z_new}


class ParameterAverager:
    """Parameter averaging for eval (ref: parameter/AverageOptimizer.cpp,
    ``average_window`` in OptimizationConfig).

    ``average_window`` in (0, 1) selects an exponential moving average with that
    decay (approximating the reference's sliding window over ~1/(1-w) batches);
    0 means a plain cumulative mean over all accumulated steps. ``average()``
    returns the raw params until ``min_count`` accumulations have happened."""

    def __init__(self, average_window: float = 0.0, min_count: int = 0):
        self.window = average_window
        self.min_count = min_count

    def init(self, params):
        return {"sum": tmap(jnp.zeros_like, params), "count": jnp.zeros((), jnp.float32)}

    def accumulate(self, state, params):
        if self.window > 0.0:
            w = self.window
            return {"sum": tmap(lambda s, p: w * s + (1.0 - w) * p, state["sum"], params),
                    "count": state["count"] + 1.0}
        return {"sum": tmap(lambda s, p: s + p, state["sum"], params),
                "count": state["count"] + 1.0}

    def average(self, state, params):
        c = jnp.maximum(state["count"], 1.0)
        if self.window > 0.0:
            # bias-correct the EMA like Adam's m-hat
            avg = tmap(lambda s: s / (1.0 - jnp.power(self.window, c)), state["sum"])
        else:
            avg = tmap(lambda s: s / c, state["sum"])
        use_avg = state["count"] >= self.min_count
        return tmap(lambda a, p: jnp.where(use_avg, a, p), avg, params)
