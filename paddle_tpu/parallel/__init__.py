"""Distributed / parallel execution — SPMD over a jax.sharding.Mesh.

This package replaces ALL of the reference's parallelism machinery with the
TPU-native SPMD design (SURVEY.md §2.5):

* ``MultiGradientMachine`` ring data-parallel (gserver/gradientmachines/
  MultiGradientMachine.h:44-97)         -> :mod:`data_parallel` (batch sharded over the
  ``data`` mesh axis; XLA inserts ``psum`` over ICI).
* pserver sharded params + RemoteParameterUpdater (pserver/ParameterServer2.h,
  trainer/RemoteParameterUpdater.h)     -> collective DP; optimizer state sharded with
  ZeRO-style ``reduce_scatter`` when requested.
* ``ParallelNeuralNetwork`` per-layer device placement (--parallel_nn)
                                        -> :mod:`tensor_parallel` sharding annotations +
  :mod:`pipeline` stage partitioning over a ``pipe`` mesh axis.
* NCCL ops (operators/nccl_op.cc:19-148) -> :mod:`collectives` named XLA collectives.
* (modern capability extension, no 2017 analog) :mod:`ring_attention` — sequence-dim
  sharding with blockwise attention over a ``seq`` mesh axis via ``ppermute``.
* sparse/embedding parallel (SparseRowMatrix + remote sparse updates, §2.5)
                                        -> :mod:`tensor_parallel` ShardedEmbedding, and its
  modern extension :mod:`moe` — expert parallelism (top-k token-choice MoE,
  experts + tokens sharded over an ``expert`` axis, all_to_all dispatch).
"""

from .compat import pcast, shard_map
from .mesh import (MeshSpec, current_mesh, make_mesh, local_mesh,
                   mesh_axis_size, use_mesh)
from .sharding import (replicate, shard, shard_batch, shard_params,
                       with_sharding_constraint, ShardingRules, SpecLayout)
from .collectives import (all_reduce, all_gather, reduce_scatter, broadcast,
                          all_to_all, permute_ring, axis_index)
from .data_parallel import DataParallel, Zero1DataParallel, Zero1State
from .tensor_parallel import ColumnParallelLinear, RowParallelLinear, ShardedEmbedding
from .ring_attention import (ring_attention, blockwise_attention,
                             ring_self_attention, ulysses_attention)
from .pipeline import PipelineStage, pipeline_1f1b, pipeline_spmd
from .moe import ExpertParallelMoE, init_moe_params, moe_ffn_dense
from . import multihost

__all__ = [
    "MeshSpec", "make_mesh", "local_mesh", "mesh_axis_size",
    "current_mesh", "use_mesh",
    "shard_map", "pcast",
    "replicate", "shard", "shard_batch", "shard_params",
    "with_sharding_constraint", "ShardingRules", "SpecLayout",
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "all_to_all",
    "permute_ring", "axis_index",
    "DataParallel",
    "Zero1DataParallel",
    "Zero1State",
    "ColumnParallelLinear", "RowParallelLinear", "ShardedEmbedding",
    "ring_attention", "blockwise_attention", "ring_self_attention",
    "ulysses_attention",
    "PipelineStage", "pipeline_spmd", "pipeline_1f1b", "multihost",
    "ExpertParallelMoE", "init_moe_params", "moe_ffn_dense",
]
