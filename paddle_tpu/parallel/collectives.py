"""Named XLA collectives — the framework's communication primitive set.

Replaces the reference's three communication backends with one: XLA collectives
over ICI/DCN (SURVEY.md §5 'Distributed communication backend'):

* NCCL operator family — ncclAllReduce/ncclReduce/ncclBcast
  (operators/nccl_op.cc:66,93,119)        -> all_reduce / reduce-to-root / broadcast
* MultiGradientMachine software ring allreduce
  (MultiGradientMachine.h:61-83)           -> all_reduce (XLA picks the ring/tree)
* pserver grad scatter + param gather
  (pserver/ParameterClient2.cpp)           -> reduce_scatter + all_gather

These are thin wrappers over ``jax.lax`` primitives so framework code reads in
terms of collective names; inside ``shard_map`` the axis_name binds to a mesh axis
and XLA emits the ICI collective.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(x, axis_name: str, op: str = "sum"):
    """Sum/mean/max over a mesh axis (ncclAllReduce analog, nccl_op.cc:66)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduction {op}")


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """Concatenate shards from every device along ``axis``."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    """Sum then scatter shards — the ZeRO grad-shard primitive."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast(x, axis_name: str, root: int = 0):
    """Every device gets root's value (ncclBcast analog, nccl_op.cc:119)."""
    idx = lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)
    mask = (idx == root).astype(x.dtype)
    # zero out non-root shards then sum: O(allreduce) but shape-stable.
    return lax.psum(x * mask, axis_name) if n > 1 else x


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int):
    """Transpose shard ownership — the Ulysses/sequence<->head exchange primitive."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def permute_ring(x, axis_name: str, shift: int = 1):
    """Pass each shard to the next device on the axis ring (collective-permute).

    The explicit building block of ring attention and pipelined collectives —
    the TPU-native version of the hand-written device ring in
    MultiGradientMachine.h:61-83.
    """
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str):
    """This device's coordinate on a mesh axis (trainer_id analog, utils/Flags.h)."""
    return lax.axis_index(axis_name)
