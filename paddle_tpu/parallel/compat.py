"""jax API compatibility for the parallel plane.

The framework is written against the current spellings (``jax.shard_map``
with ``check_vma``, ``lax.pcast`` for varying-axes typing). The tier-1
environment carries an older jax where ``shard_map`` still lives in
``jax.experimental.shard_map`` (kwarg ``check_rep``) and ``pcast`` does not
exist. One resolution point here keeps every call site on the modern
spelling — and keeps the whole parallel suite runnable on both jax
generations instead of AttributeError-ing on import of the hot path.
"""

from __future__ import annotations

import jax
from jax import lax

__all__ = ["shard_map", "pcast"]


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        """``jax.shard_map`` spelling on top of the experimental module.

        ``check_vma`` maps onto the old ``check_rep`` knob; when the caller
        leaves it unset we default it OFF — the code base is written for
        the varying-mesh-axes type system, and the legacy replication
        checker rejects valid programs of that style (ppermute rings,
        pallas_call bodies) that VMA accepts.
        """
        kw.setdefault("check_rep", bool(check_vma) if check_vma is not None
                      else False)
        return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)


if hasattr(lax, "pcast"):
    pcast = lax.pcast
else:
    def pcast(x, axis_name, *, to="varying"):
        """Identity fallback: ``pcast`` only adjusts the replication-
        tracking *type* of a value (unvarying -> varying over an axis);
        with the legacy checker disabled the value itself is already
        correct."""
        del axis_name, to
        return x
