"""Data-parallel training — the MultiGradientMachine replacement.

Reference semantics being preserved (gserver/gradientmachines/MultiGradientMachine.h):
* batch split across devices (``TrainerThread`` per GPU, .h:44-60)
* gradient ring allreduce + broadcast of updated params (.h:61-83)
* final parameters identical to single-device training on the whole batch
  (tested by the test_CompareSparse.cpp-style equivalence test).

TPU-native: ONE jitted SPMD train step. The batch carries a ``data``-axis sharding,
loss is a mean over the global batch, and XLA inserts the grad ``psum`` over ICI
automatically from the sharding propagation — no explicit communication code.
Optionally optimizer state is sharded over ``data`` (ZeRO-1) via reduce_scatter
semantics, recovering what the pserver did (each server owns a param shard's
optimizer state, ParameterServer2.h:383 doOperation).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import make_mesh
from .sharding import ShardingRules, replicate, shard_batch, shard_params
from . import compat


class DataParallel:
    """Wrap (loss_fn, optimizer) into a sharded, jitted train step.

    loss_fn(params, *batch) -> scalar loss (mean over ITS batch rows).
    """

    def __init__(self, loss_fn: Callable, optimizer, mesh: Optional[Mesh] = None,
                 axis: str = "data", param_rules: Optional[ShardingRules] = None,
                 donate: bool = True, aux_fn: Optional[Callable] = None):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.mesh = mesh if mesh is not None else make_mesh(data=-1)
        self.axis = axis
        self.rules = param_rules
        self.aux_fn = aux_fn

        def _step(params, opt_state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            # aux (eval outputs) computed INSIDE the same jitted step so XLA
            # shares the forward pass — no second per-batch dispatch
            aux = aux_fn(params, *batch) if aux_fn is not None else None
            new_params, new_state = self.opt.update(grads, opt_state, params)
            if aux_fn is not None:
                return new_params, new_state, loss, aux
            return new_params, new_state, loss

        donate_args = (0, 1) if donate else ()
        # cost-instrumented jit (as Trainer._step): an obs session sees the
        # SPMD step's FLOPs/bytes in the roofline ledger per dispatch
        from ..obs import roofline
        self._step = roofline.instrument(
            jax.jit(_step, donate_argnums=donate_args), "data_parallel.step")

    # -- placement ---------------------------------------------------------
    def init(self, params, opt_state=None):
        """Place params (+ optimizer state) on the mesh. Called again on a
        checkpoint restore, this is what re-places host arrays onto the
        CURRENT mesh — the rules are a pure function of path+shape, so a
        job resumed on a different mesh shape just re-resolves."""
        params = shard_params(params, self.mesh, self.rules)
        if opt_state is None:
            opt_state = self.opt.init(params)
        if hasattr(self.rules, "resolve"):
            # SpecLayout: slot paths embed their parameter's path, so the
            # same resolution shards optimizer moments like their params
            opt_state = self.rules.apply(self.mesh, opt_state)
        else:
            opt_state = jax.device_put(opt_state, replicate(self.mesh))
        return params, opt_state

    def shard_batch(self, batch):
        return shard_batch(batch, self.mesh, self.axis)

    # -- the hot loop ------------------------------------------------------
    def step(self, params, opt_state, *batch) -> Tuple[Any, Any, jax.Array]:
        """One global-batch SGD step; batch leaves should already be sharded
        (use :meth:`shard_batch`) or will be sharded by XLA on first use."""
        with self.mesh:
            return self._step(params, opt_state, *batch)


class Zero1State(NamedTuple):
    """ZeRO-1 training state: the f32 master copy of all trainable parameters
    lives as ONE flat vector sharded over the data axis; optimizer slots share
    that sharding; non-trainable ``stats`` leaves stay replicated."""
    flat: jax.Array          # [N_padded] f32, sharded P(axis)
    opt_state: Any           # {"step": scalar, "slots": {"flat": ...}} P(axis)
    stats: Tuple[Any, ...]   # replicated non-trainable leaves, original order


class Zero1DataParallel:
    """TRUE ZeRO-1 data parallelism (partitioned optimizer states).

    Semantics recovered from the reference's parameter server, where each
    pserver owns a shard of every parameter block and runs the optimizer on
    its shard only (ParameterServer2.h:383 doOperation; ParameterClient2
    splits parameters into blocks hashed across pservers):

    * each device owns 1/n of one flat f32 master parameter vector and the
      optimizer slots FOR THAT SHARD ONLY (n× slot-memory saving),
    * per step inside one jitted shard_map: all_gather(param shards) →
      local fwd/bwd → **reduce_scatter**(grads) → shard-local optimizer
      update → next step's all_gather broadcasts the new params,
    * final parameters match plain DP / single-device training exactly
      (equivalence-tested like test_CompareSparse.cpp).

    loss_fn(params, *batch) -> scalar loss (mean over ITS batch rows).
    """

    def __init__(self, loss_fn: Callable, optimizer, mesh: Optional[Mesh] = None,
                 axis: str = "data"):
        if getattr(optimizer, "grad_clip", None) is not None and \
                optimizer.grad_clip[0] in ("norm", "global_norm"):
            raise ValueError(
                "norm-based grad clip inside the shard-local optimizer would "
                "clip by the LOCAL shard's norm (not per-leaf / global); "
                "clip in loss_fn or use grad_clip=('value', ...)")
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.mesh = mesh if mesh is not None else make_mesh(data=-1)
        self.axis = axis
        self.n = self.mesh.shape[axis]
        self._stepfns = {}        # batch treedef -> compiled shard_map step

    # -- flat <-> pytree ----------------------------------------------------
    def _build_template(self, params):
        from ..optimizer.optimizers import _is_stat_path
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        self._treedef = treedef
        self._is_stat = [_is_stat_path(path) for path, _ in flat]
        train = [leaf for (path, leaf), st in zip(flat, self._is_stat) if not st]
        self._shapes = [l.shape for l in train]
        self._dtypes = [l.dtype for l in train]
        self._sizes = [int(np.prod(l.shape)) if l.shape else 1 for l in train]
        total = sum(self._sizes)
        self._padded = -(-total // self.n) * self.n
        self._offsets = np.cumsum([0] + self._sizes).tolist()

    def _flatten(self, leaves):
        """Trainable leaves -> [N_padded] f32."""
        parts = [jnp.ravel(l).astype(jnp.float32) for l in leaves]
        flat = jnp.concatenate(parts) if parts else jnp.zeros((0,), jnp.float32)
        pad = self._padded - flat.shape[0]
        return jnp.pad(flat, (0, pad)) if pad else flat

    def _unflatten(self, flat, stats):
        """[N_padded] f32 + replicated stat leaves -> params pytree."""
        train = [flat[o:o + s].reshape(shape).astype(dt)
                 for o, s, shape, dt in zip(self._offsets, self._sizes,
                                            self._shapes, self._dtypes)]
        it_t, it_s = iter(train), iter(stats)
        leaves = [next(it_s) if st else next(it_t) for st in self._is_stat]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def _train_leaves(self, tree):
        flat = jax.tree_util.tree_leaves(tree)
        return [l for l, st in zip(flat, self._is_stat) if not st]

    def _stat_leaves(self, tree):
        flat = jax.tree_util.tree_leaves(tree)
        return tuple(l for l, st in zip(flat, self._is_stat) if st)

    # -- placement ----------------------------------------------------------
    def init(self, params) -> Zero1State:
        self._build_template(params)
        flat = self._flatten(self._train_leaves(params))
        flat = jax.device_put(flat, NamedSharding(self.mesh, P(self.axis)))
        opt_state = self.opt.init({"flat": flat})   # slots inherit the sharding
        opt_state = jax.tree_util.tree_map(
            lambda x: x if getattr(x, "ndim", 0) >= 1 else
            jax.device_put(x, replicate(self.mesh)), opt_state)
        stats = jax.device_put(self._stat_leaves(params), replicate(self.mesh))
        return Zero1State(flat, opt_state, stats)

    def params(self, state: Zero1State):
        """Materialise the full parameter pytree (for eval / checkpointing)."""
        return self._unflatten(jax.device_get(state.flat), state.stats)

    def shard_batch(self, batch):
        return shard_batch(batch, self.mesh, self.axis)

    # -- the hot loop --------------------------------------------------------
    def _make_step(self, state: Zero1State, batch):
        axis, n = self.axis, self.n
        flat_spec = P(axis)
        state_spec = jax.tree_util.tree_map(
            lambda x: P(axis) if getattr(x, "ndim", 0) >= 1 else P(),
            state.opt_state)
        stats_spec = jax.tree_util.tree_map(lambda x: P(), state.stats)
        batch_specs = tuple(
            jax.tree_util.tree_map(
                lambda l: P(axis, *([None] * (jnp.ndim(l) - 1)))
                if jnp.ndim(l) >= 1 else P(), b)
            for b in batch)

        def local_step(flat_shard, opt_state, stats, *batch):
            from . import collectives as cc
            full = cc.all_gather(flat_shard, axis)
            params = self._unflatten(full, stats)
            loss, grads = jax.value_and_grad(self.loss_fn)(params, *batch)
            gflat = self._flatten(self._train_leaves(grads))
            # mean over the data axis, scattered so each device only keeps
            # (and updates) its own 1/n shard
            g_shard = cc.reduce_scatter(gflat, axis) / n
            new_p, new_state = self.opt.update({"flat": g_shard}, opt_state,
                                               {"flat": flat_shard})
            return new_p["flat"], new_state, jax.lax.pmean(loss, axis)

        fn = compat.shard_map(
            local_step, mesh=self.mesh,
            in_specs=(flat_spec, state_spec, stats_spec) + batch_specs,
            out_specs=(flat_spec, state_spec, P()),
            check_vma=False)
        return jax.jit(fn, donate_argnums=(0, 1))

    def step(self, state: Zero1State, *batch):
        """One global-batch ZeRO-1 step -> (new_state, loss)."""
        # key on leaf ranks too: in_specs bake each leaf's rank, so same-tree
        # batches with different ranks must not share a compiled step
        key = (str(jax.tree_util.tree_structure(batch)),
               tuple(jnp.ndim(l) for l in jax.tree_util.tree_leaves(batch)))
        if key not in self._stepfns:
            self._stepfns[key] = self._make_step(state, batch)
        with self.mesh:
            flat, opt_state, loss = self._stepfns[key](
                state.flat, state.opt_state, state.stats, *batch)
        return Zero1State(flat, opt_state, state.stats), loss
