"""Data-parallel training — the MultiGradientMachine replacement.

Reference semantics being preserved (gserver/gradientmachines/MultiGradientMachine.h):
* batch split across devices (``TrainerThread`` per GPU, .h:44-60)
* gradient ring allreduce + broadcast of updated params (.h:61-83)
* final parameters identical to single-device training on the whole batch
  (tested by the test_CompareSparse.cpp-style equivalence test).

TPU-native: ONE jitted SPMD train step. The batch carries a ``data``-axis sharding,
loss is a mean over the global batch, and XLA inserts the grad ``psum`` over ICI
automatically from the sharding propagation — no explicit communication code.
Optionally optimizer state is sharded over ``data`` (ZeRO-1) via reduce_scatter
semantics, recovering what the pserver did (each server owns a param shard's
optimizer state, ParameterServer2.h:383 doOperation).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import make_mesh
from .sharding import ShardingRules, replicate, shard_batch, shard_params


class DataParallel:
    """Wrap (loss_fn, optimizer) into a sharded, jitted train step.

    loss_fn(params, *batch) -> scalar loss (mean over ITS batch rows).
    """

    def __init__(self, loss_fn: Callable, optimizer, mesh: Optional[Mesh] = None,
                 axis: str = "data", param_rules: Optional[ShardingRules] = None,
                 zero1: bool = False, donate: bool = True):
        self.loss_fn = loss_fn
        self.opt = optimizer
        self.mesh = mesh if mesh is not None else make_mesh(data=-1)
        self.axis = axis
        self.rules = param_rules
        self.zero1 = zero1

        def _step(params, opt_state, *batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, *batch)
            new_params, new_state = self.opt.update(grads, opt_state, params)
            return new_params, new_state, loss

        donate_args = (0, 1) if donate else ()
        self._step = jax.jit(_step, donate_argnums=donate_args)

    # -- placement ---------------------------------------------------------
    def init(self, params, opt_state=None):
        """Place params (+ optimizer state) on the mesh."""
        params = shard_params(params, self.mesh, self.rules)
        if opt_state is None:
            opt_state = self.opt.init(params)
        if self.zero1:
            opt_state = self._shard_opt_state(opt_state)
        else:
            opt_state = jax.device_put(opt_state, replicate(self.mesh))
        return params, opt_state

    def _shard_opt_state(self, opt_state):
        """ZeRO-1: slot buffers sharded over the data axis on dim 0 when divisible."""
        n = self.mesh.shape[self.axis]

        def put(x):
            if getattr(x, "ndim", 0) >= 1 and x.shape[0] % n == 0 and x.shape[0] >= n:
                spec = P(self.axis, *([None] * (x.ndim - 1)))
            else:
                spec = P()
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        return jax.tree_util.tree_map(put, opt_state)

    def shard_batch(self, batch):
        return shard_batch(batch, self.mesh, self.axis)

    # -- the hot loop ------------------------------------------------------
    def step(self, params, opt_state, *batch) -> Tuple[Any, Any, jax.Array]:
        """One global-batch SGD step; batch leaves should already be sharded
        (use :meth:`shard_batch`) or will be sharded by XLA on first use."""
        with self.mesh:
            return self._step(params, opt_state, *batch)
