"""Device-mesh construction.

The reference enumerates GPUs and spreads ``TrainerThread``s over them
(gserver/gradientmachines/MultiGradientMachine.h:44-97) and reaches other hosts
through pserver RPC. TPU-native: one logical ``jax.sharding.Mesh`` spans every chip
in the job (ICI within a slice, DCN across slices); parallelism strategies are just
named mesh axes.

Canonical axis names used across the framework:
  ``data``  — batch sharding (DP)           ``model`` — tensor/model parallel (TP)
  ``pipe``  — pipeline stages (PP)          ``seq``   — sequence/context parallel (SP)
  ``expert``— expert parallel (EP, reserved)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


# Axis ordering: innermost (fastest-varying over devices) LAST so that the most
# communication-heavy axis (model/seq) lands on nearest-neighbour ICI links.
CANONICAL_ORDER = ("pipe", "data", "expert", "seq", "model")


@dataclass
class MeshSpec:
    """Declarative mesh request: axis name -> size. Size -1 means 'the rest'."""

    axes: Dict[str, int] = field(default_factory=dict)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        axes = {k: v for k, v in self.axes.items() if v != 1 or k == "data"}
        if not axes:
            axes = {"data": -1}
        wild = [k for k, v in axes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis, got {wild}")
        known = int(np.prod([v for v in axes.values() if v != -1]))
        if wild:
            if n_devices % known:
                raise ValueError(f"{n_devices} devices not divisible by {known}")
            axes[wild[0]] = n_devices // known
        total = int(np.prod(list(axes.values())))
        if total > n_devices or n_devices % total:
            raise ValueError(f"mesh {axes} needs {total} devices, have {n_devices}")
        return axes


def make_mesh(spec: Optional[MeshSpec] = None, devices: Optional[Sequence] = None,
              **axes: int) -> Mesh:
    """Build a Mesh from a spec or kwargs: ``make_mesh(data=4, model=2)``.

    Axes are laid out in CANONICAL_ORDER so the model axis maps to adjacent
    devices (nearest-neighbour ICI) and pipe to the outermost dimension.
    """
    if spec is None:
        spec = MeshSpec(dict(axes))
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    resolved = spec.resolve(len(devices))
    names = tuple(sorted(resolved, key=lambda a: CANONICAL_ORDER.index(a)
                         if a in CANONICAL_ORDER else len(CANONICAL_ORDER)))
    shape = tuple(resolved[a] for a in names)
    n = int(np.prod(shape))
    arr = np.array(devices[:n]).reshape(shape)   # a sub-mesh is allowed
    return Mesh(arr, names)


def local_mesh(**axes: int) -> Mesh:
    """Mesh over this process's addressable devices (single-host path)."""
    return make_mesh(devices=jax.local_devices(), **axes)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)
