"""Device-mesh construction.

The reference enumerates GPUs and spreads ``TrainerThread``s over them
(gserver/gradientmachines/MultiGradientMachine.h:44-97) and reaches other hosts
through pserver RPC. TPU-native: one logical ``jax.sharding.Mesh`` spans every chip
in the job (ICI within a slice, DCN across slices); parallelism strategies are just
named mesh axes.

Canonical axis names used across the framework:
  ``data``  — batch sharding (DP)           ``model`` — tensor/model parallel (TP)
  ``pipe``  — pipeline stages (PP)          ``seq``   — sequence/context parallel (SP)
  ``expert``— expert parallel (EP)          ``fsdp``  — parameter sharding (ZeRO-3
  ``tp``    — tensor parallel (the           style: storage split, XLA gathers
  ``SpecLayout`` spelling; ``model``         for compute)
  remains the legacy alias)
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


# Axis ordering: innermost (fastest-varying over devices) LAST so that the most
# communication-heavy axis (model/tp/seq) lands on nearest-neighbour ICI links.
CANONICAL_ORDER = ("pipe", "data", "fsdp", "expert", "seq", "model", "tp")


@dataclass
class MeshSpec:
    """Declarative mesh request: axis name -> size. Size -1 means 'the rest'."""

    axes: Dict[str, int] = field(default_factory=dict)

    def resolve(self, n_devices: int) -> Dict[str, int]:
        axes = {k: v for k, v in self.axes.items() if v != 1 or k == "data"}
        if not axes:
            axes = {"data": -1}
        wild = [k for k, v in axes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one -1 axis, got {wild}")
        known = int(np.prod([v for v in axes.values() if v != -1]))
        if wild:
            if n_devices % known:
                raise ValueError(f"{n_devices} devices not divisible by {known}")
            axes[wild[0]] = n_devices // known
        total = int(np.prod(list(axes.values())))
        if total > n_devices or n_devices % total:
            raise ValueError(f"mesh {axes} needs {total} devices, have {n_devices}")
        return axes


def make_mesh(spec: Optional[MeshSpec] = None, devices: Optional[Sequence] = None,
              **axes: int) -> Mesh:
    """Build a Mesh from a spec or kwargs: ``make_mesh(data=4, model=2)``.

    Axes are laid out in CANONICAL_ORDER so the model axis maps to adjacent
    devices (nearest-neighbour ICI) and pipe to the outermost dimension.
    """
    if spec is None:
        spec = MeshSpec(dict(axes))
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    resolved = spec.resolve(len(devices))
    names = tuple(sorted(resolved, key=lambda a: CANONICAL_ORDER.index(a)
                         if a in CANONICAL_ORDER else len(CANONICAL_ORDER)))
    shape = tuple(resolved[a] for a in names)
    n = int(np.prod(shape))
    arr = np.array(devices[:n]).reshape(shape)   # a sub-mesh is allowed
    return Mesh(arr, names)


def local_mesh(**axes: int) -> Mesh:
    """Mesh over this process's addressable devices (single-host path)."""
    return make_mesh(devices=jax.local_devices(), **axes)


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


# -- the ambient mesh ----------------------------------------------------------
# One job, one logical mesh: components that place data (the fluid Executor,
# checkpoint restore, benches) pick up the enclosing ``use_mesh`` instead of
# each growing a mesh parameter on every call path. A ContextVar (not a
# module-global list) keeps the scope per-thread/per-task — jax's own mesh
# context is thread-local too, and a prefetch or RPC thread constructing an
# Executor must not inherit (or corrupt) another thread's ambient mesh.

import contextvars as _contextvars

_MESH_STACK: "_contextvars.ContextVar[Tuple[Mesh, ...]]" = \
    _contextvars.ContextVar("paddle_tpu_mesh_stack", default=())


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Make ``mesh`` the ambient mesh (``current_mesh``) for the scope.

    Also enters jax's own mesh context so named-axis APIs resolve. An
    ``Executor()`` constructed inside the scope adopts the mesh::

        with pp.use_mesh(pp.make_mesh(data=2, fsdp=2, tp=2)):
            exe = fluid.Executor(layout=pp.SpecLayout())
    """
    token = _MESH_STACK.set(_MESH_STACK.get() + (mesh,))
    try:
        with mesh:
            yield mesh
    finally:
        _MESH_STACK.reset(token)


def current_mesh() -> Optional[Mesh]:
    """The innermost :func:`use_mesh` mesh of this thread/task, or None."""
    stack = _MESH_STACK.get()
    return stack[-1] if stack else None
