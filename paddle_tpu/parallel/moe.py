"""Expert parallelism: token-choice top-k mixture-of-experts FFN sharded
over the ``expert`` mesh axis.

The 2017 reference's closest machinery is sparse/embedding sharding
(SparseRowMatrix + remote sparse updates, SURVEY §2.5); expert parallelism
is the modern extension of the same idea — parameters too big for one chip,
touched sparsely per token — built TPU-first (GShard/Mesh-TF shape):

* tokens AND experts shard over one mesh axis (``expert``): each device
  holds ``T/n`` tokens and ``E/n`` experts' weights;
* each shard routes its tokens with top-k gating into a fixed-capacity
  dispatch tensor ``[E, C, D]`` (static shapes — XLA-friendly; over-capacity
  tokens drop, the GShard contract);
* one ``all_to_all`` turns shard-major dispatch into expert-major compute
  ``[E_local, n*C, D]``, the expert FFN runs as big batched einsums on the
  MXU, and the reverse ``all_to_all`` brings results home where the combine
  weights (gate probs) produce the output;
* the auxiliary load-balance loss (mean gate fraction x mean assignment
  fraction x E) is returned next to the output.

Capacity semantics are per (source shard, expert): ``capacity`` tokens per
expert from EACH shard. With capacity >= T_local no token ever drops and
the sharded output equals the dense single-device reference exactly
(tests/test_moe.py).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from . import compat
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> dict:
    kg, k1, k2 = jax.random.split(key, 3)
    s_in = 1.0 / np.sqrt(d_model)
    s_ff = 1.0 / np.sqrt(d_ff)
    return {
        "gate_w": (jax.random.normal(kg, (d_model, n_experts), dtype) * s_in),
        "w1": jax.random.normal(k1, (n_experts, d_model, d_ff), dtype) * s_in,
        "w2": jax.random.normal(k2, (n_experts, d_ff, d_model), dtype) * s_ff,
    }


def _route(x, gate_w, n_experts: int, k: int, capacity: int):
    """Top-k routing for one shard's tokens.

    Returns (dispatch [T, E, C] 0/1, combine [T, E, C] prob-weighted,
    aux_loss scalar). GShard discipline: choices assign greedily per k
    (the 2nd choice only sees capacity left by the 1st), positions come
    from a cumsum over tokens, over-capacity tokens drop.
    """
    if k > n_experts:
        raise ValueError(f"top-{k} routing needs k <= n_experts "
                         f"({n_experts}): an exhausted gate row would "
                         "re-dispatch to expert 0")
    T = x.shape[0]
    logits = x @ gate_w                               # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # aux load-balance loss (GShard eq.(4)): E * mean_e(gate frac * assign frac)
    top1 = jnp.argmax(probs, axis=-1)
    assign_frac = jnp.mean(jax.nn.one_hot(top1, n_experts), axis=0)
    gate_frac = jnp.mean(probs, axis=0)
    aux = n_experts * jnp.sum(assign_frac * gate_frac)

    remaining = probs
    used = jnp.zeros((n_experts,), jnp.int32)         # slots taken per expert
    dispatch = jnp.zeros((T, n_experts, capacity), x.dtype)
    combine = jnp.zeros((T, n_experts, capacity), x.dtype)
    for _ in range(k):
        choice = jnp.argmax(remaining, axis=-1)       # [T]
        prob = jnp.take_along_axis(probs, choice[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(choice, n_experts, dtype=jnp.int32)
        # slot index within the chosen expert: earlier tokens first, offset
        # by slots previous choices already consumed
        pos = jnp.cumsum(onehot, axis=0) - onehot + used[None, :]   # [T, E]
        slot = jnp.sum(pos * onehot, axis=-1)                        # [T]
        keep = slot < capacity
        oh_slot = jax.nn.one_hot(slot, capacity, dtype=x.dtype)
        d_k = (onehot.astype(x.dtype)[:, :, None] * oh_slot[:, None, :]
               * keep[:, None, None].astype(x.dtype))
        dispatch = dispatch + d_k
        combine = combine + d_k * prob[:, None, None]
        used = used + jnp.sum(onehot * keep[:, None].astype(jnp.int32),
                              axis=0)
        remaining = remaining * (1.0 - onehot.astype(probs.dtype))
    return dispatch, combine, aux


def _expert_ffn(tokens, w1, w2):
    """tokens [E, N, D] through each expert's 2-layer relu FFN."""
    h = jax.nn.relu(jnp.einsum("end,edf->enf", tokens, w1))
    return jnp.einsum("enf,efd->end", h, w2)


def moe_ffn_dense(params, x, *, k: int = 1,
                  capacity: Optional[int] = None) -> Tuple[jax.Array, jax.Array]:
    """Single-device reference: x [T, D] -> (y [T, D], aux loss)."""
    E = params["gate_w"].shape[-1]
    T = x.shape[0]
    capacity = capacity if capacity is not None else T
    dispatch, combine, aux = _route(x, params["gate_w"], E, k, capacity)
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x)     # [E, C, D]
    expert_out = _expert_ffn(expert_in, params["w1"], params["w2"])
    y = jnp.einsum("tec,ecd->td", combine, expert_out)
    return y, aux


class ExpertParallelMoE:
    """Expert-sharded MoE FFN over mesh axis ``expert``.

    ``shard_params`` places w1/w2 expert-sharded and the gate replicated;
    ``__call__`` jits one shard_map step: tokens x [T, D] sharded over the
    expert axis rows, output identically sharded.
    """

    def __init__(self, mesh: Mesh, *, k: int = 1,
                 capacity: Optional[int] = None, axis: str = "expert"):
        self.mesh = mesh
        self.axis = axis
        self.n = mesh.shape[axis]
        self.k = k
        self.capacity = capacity
        self._compiled = {}       # (E, T_local, capacity) -> jitted shard_map

    def shard_params(self, params: dict) -> dict:
        es = NamedSharding(self.mesh, P(self.axis))
        rep = NamedSharding(self.mesh, P())
        return {"gate_w": jax.device_put(params["gate_w"], rep),
                "w1": jax.device_put(params["w1"], es),
                "w2": jax.device_put(params["w2"], es)}

    def shard_tokens(self, x) -> jax.Array:
        return jax.device_put(
            x, NamedSharding(self.mesh, P(self.axis, None)))

    def __call__(self, params, x) -> Tuple[jax.Array, jax.Array]:
        E = params["gate_w"].shape[-1]
        T_local = x.shape[0] // self.n
        capacity = self.capacity if self.capacity is not None else T_local
        key = (E, T_local, capacity)
        if key not in self._compiled:
            self._compiled[key] = self._build(E, capacity)
        return self._compiled[key](params["gate_w"], params["w1"],
                                   params["w2"], x)

    def _build(self, E: int, capacity: int):
        n, axis, k = self.n, self.axis, self.k

        def local(gate_w, w1, w2, xs):
            dispatch, combine, aux = _route(xs, gate_w, E, k, capacity)
            ein = jnp.einsum("tec,td->ecd", dispatch, xs)   # [E, C, D]
            # shard-major -> expert-major: [n, E_l, C, D] a2a over the ring
            el = E // n
            ein = ein.reshape(n, el, capacity, -1)
            recv = jax.lax.all_to_all(ein, axis, split_axis=0, concat_axis=0)
            # recv [n, E_l, C, D]: dim0 = source shard; fold into the token dim
            tokens = jnp.swapaxes(recv, 0, 1).reshape(el, n * capacity, -1)
            out = _expert_ffn(tokens, w1, w2)               # [E_l, n*C, D]
            back = jnp.swapaxes(out.reshape(el, n, capacity, -1), 0, 1)
            back = jax.lax.all_to_all(back, axis, split_axis=0, concat_axis=0)
            # back [n, E_l, C, D] with dim0 = expert-home shard == expert id
            # major order: reshape to [E, C, D] for the combine
            back = back.reshape(E, capacity, -1)
            y = jnp.einsum("tec,ecd->td", combine, back)
            # aux is a per-shard mean over its tokens; average across shards
            return y, jax.lax.pmean(aux, axis)

        fn = compat.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(), P(self.axis), P(self.axis), P(self.axis, None)),
            out_specs=(P(self.axis, None), P()))
        return jax.jit(fn)
