"""Multi-host (pod / multi-slice) initialization helpers.

The reference scales across machines with pserver endpoints + etcd membership
(trainer flags trainer_id/num_gradient_servers, utils/Flags.h:19-43; cluster
launchers paddle/scripts/cluster_train*). TPU-native: every host runs the SAME
SPMD program; membership/coordination is jax.distributed's coordinator (GCE
metadata on real pods), the mesh spans all hosts' devices (ICI within a slice,
DCN across), and the data plane is the master service
(runtime/master_service.py) sharding input chunks across hosts.
"""

from __future__ import annotations

import os
import signal
import sys
from typing import Callable, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import MeshSpec, make_mesh

# Exit code a worker uses for clean job-teardown (peer failure): distinct
# from crash codes so the launcher/operator can tell "I was torn down" from
# "I failed". Mirrors the reference's trainer-as-stateless-task-consumer
# contract (doc/design/cluster_train/README.md): workers hold no durable
# state, so teardown is checkpoint-then-exit and recovery is a fresh launch.
TEARDOWN_EXIT_CODE = 17

_teardown_hooks: List[Callable[[], None]] = []


def on_job_teardown(fn: Callable[[], None]) -> None:
    """Register a callback run when the launcher tears the job down after a
    peer failure (SIGTERM). Typical use: write a final checkpoint marker so
    the restart (docs/design/distributed.md runbook) resumes at the last
    good pass instead of from scratch."""
    _teardown_hooks.append(fn)


def _teardown_handler(signum, frame):  # noqa: ARG001 - signal signature
    print("paddle_tpu.multihost: job teardown (peer failure or operator "
          "stop) — running teardown hooks, then exiting. Restart from the "
          "latest checkpoint: docs/design/distributed.md.", file=sys.stderr)
    for fn in _teardown_hooks:
        try:
            fn()
        except Exception as e:  # noqa: BLE001 - teardown must not cascade
            print(f"paddle_tpu.multihost: teardown hook failed: {e}",
                  file=sys.stderr)
    sys.stderr.flush()
    # _exit, not SystemExit: the main thread may be inside a blocked
    # collective; raising would be swallowed or deadlock in native code.
    os._exit(TEARDOWN_EXIT_CODE)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> dict:
    """Join the multi-host job (jax.distributed.initialize wrapper).

    On real TPU pods all three args auto-detect from the environment; flags
    mirror the reference's --trainer_id/--num_gradient_servers, and the
    cluster launcher (cli.py cluster_train) exports them as
    PADDLE_TPU_{COORDINATOR,NUM_PROCESSES,PROCESS_ID}. Returns a summary
    dict. Safe to call single-host (no-op when nothing configured).
    """
    env = os.environ
    coordinator_address = coordinator_address or env.get(
        "PADDLE_TPU_COORDINATOR")
    if num_processes is None and "PADDLE_TPU_NUM_PROCESSES" in env:
        num_processes = int(env["PADDLE_TPU_NUM_PROCESSES"])
    if process_id is None and "PADDLE_TPU_PROCESS_ID" in env:
        process_id = int(env["PADDLE_TPU_PROCESS_ID"])
    if coordinator_address or num_processes or env.get(
            "JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        # the launcher (cli.py cluster_train) tears a failed job down with
        # SIGTERM-then-SIGKILL; give every worker the clean-exit path
        signal.signal(signal.SIGTERM, _teardown_handler)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def global_mesh(**axes: int) -> Mesh:
    """Mesh over ALL devices in the job (every process constructs the same
    mesh; jax.devices() is globally consistent)."""
    return make_mesh(**axes)


def process_batch_slice(global_batch_size: int) -> slice:
    """This host's row range of the global batch — the per-process feed for
    jax.make_array_from_process_local_data-style input pipelines."""
    n = jax.process_count()
    per = global_batch_size // n
    i = jax.process_index()
    return slice(i * per, (i + 1) * per)


def make_global_array(local_rows: np.ndarray, mesh: Mesh, axis: str = "data"):
    """Assemble a global device array from each process's local batch rows
    (multi-host feed path; single-host it is a plain device_put)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P(axis, *([None] * (local_rows.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)


def make_global_batch(mesh: Mesh, batch, axis: str = "data"):
    """Assemble a per-process host-local batch into global device arrays.

    Each process passes ITS slice of the global batch (rows
    ``process_batch_slice(global_bs)``); returns jax Arrays sharded
    ``P(axis)`` over the global mesh — the multi-host analog of
    DataParallel.shard_batch (replaces the reference's per-trainer
    DataProvider feed, trainer flags trainer_id/num_gradient_servers).
    """
    return jax.tree_util.tree_map(
        lambda x: make_global_array(np.asarray(x), mesh, axis), batch)


def replicate_from_host(mesh: Mesh, tree):
    """Place identical host data (e.g. initial params) replicated over a
    multi-process mesh — every process must pass the same values (SPMD)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P()), x, x.shape)

    return jax.tree_util.tree_map(put, tree)
