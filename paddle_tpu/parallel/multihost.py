"""Multi-host (pod / multi-slice) initialization helpers.

The reference scales across machines with pserver endpoints + etcd membership
(trainer flags trainer_id/num_gradient_servers, utils/Flags.h:19-43; cluster
launchers paddle/scripts/cluster_train*). TPU-native: every host runs the SAME
SPMD program; membership/coordination is jax.distributed's coordinator (GCE
metadata on real pods), the mesh spans all hosts' devices (ICI within a slice,
DCN across), and the data plane is the master service
(runtime/master_service.py) sharding input chunks across hosts.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import MeshSpec, make_mesh


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> dict:
    """Join the multi-host job (jax.distributed.initialize wrapper).

    On real TPU pods all three args auto-detect from the environment; flags
    mirror the reference's --trainer_id/--num_gradient_servers, and the
    cluster launcher (cli.py cluster_train) exports them as
    PADDLE_TPU_{COORDINATOR,NUM_PROCESSES,PROCESS_ID}. Returns a summary
    dict. Safe to call single-host (no-op when nothing configured).
    """
    env = os.environ
    coordinator_address = coordinator_address or env.get(
        "PADDLE_TPU_COORDINATOR")
    if num_processes is None and "PADDLE_TPU_NUM_PROCESSES" in env:
        num_processes = int(env["PADDLE_TPU_NUM_PROCESSES"])
    if process_id is None and "PADDLE_TPU_PROCESS_ID" in env:
        process_id = int(env["PADDLE_TPU_PROCESS_ID"])
    if coordinator_address or num_processes or env.get(
            "JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def global_mesh(**axes: int) -> Mesh:
    """Mesh over ALL devices in the job (every process constructs the same
    mesh; jax.devices() is globally consistent)."""
    return make_mesh(**axes)


def process_batch_slice(global_batch_size: int) -> slice:
    """This host's row range of the global batch — the per-process feed for
    jax.make_array_from_process_local_data-style input pipelines."""
    n = jax.process_count()
    per = global_batch_size // n
    i = jax.process_index()
    return slice(i * per, (i + 1) * per)


def make_global_array(local_rows: np.ndarray, mesh: Mesh, axis: str = "data"):
    """Assemble a global device array from each process's local batch rows
    (multi-host feed path; single-host it is a plain device_put)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    sharding = NamedSharding(mesh, P(axis, *([None] * (local_rows.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(sharding, local_rows)


def make_global_batch(mesh: Mesh, batch, axis: str = "data"):
    """Assemble a per-process host-local batch into global device arrays.

    Each process passes ITS slice of the global batch (rows
    ``process_batch_slice(global_bs)``); returns jax Arrays sharded
    ``P(axis)`` over the global mesh — the multi-host analog of
    DataParallel.shard_batch (replaces the reference's per-trainer
    DataProvider feed, trainer flags trainer_id/num_gradient_servers).
    """
    return jax.tree_util.tree_map(
        lambda x: make_global_array(np.asarray(x), mesh, axis), batch)


def replicate_from_host(mesh: Mesh, tree):
    """Place identical host data (e.g. initial params) replicated over a
    multi-process mesh — every process must pass the same values (SPMD)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        x = np.asarray(x)
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P()), x, x.shape)

    return jax.tree_util.tree_map(put, tree)
