"""Pipeline parallelism over a ``pipe`` mesh axis (GPipe-style SPMD).

The reference pipelines by placing whole layers on devices and streaming
batches through per-device threads (ParallelNeuralNetwork.h:23-34, TaskType
fwd/bwd queues). TPU-native: all stages run the SAME jitted SPMD program; stage
parameters are stacked on a leading axis sharded over ``pipe``, microbatch
activations hop stage->stage via ``ppermute`` over ICI, and the schedule is a
``lax.fori_loop`` of (n_microbatches + n_stages - 1) ticks. Autodiff flows
through ppermute, so the same program trains (XLA overlaps the transfers —
recovering the reference's thread-pipelined overlap, SURVEY §2.5 row
'Pipeline-ish overlap').

Constraint inherited from SPMD: every stage must share one activation shape
(equal-width trunk), the usual homogeneous-transformer-stack case.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.module import Module
from . import compat


class PipelineStage(Module):
    """Repeats one stage Module across pipeline stages with stacked params.

    ``init`` produces params with a leading [n_stages] axis on every leaf;
    shard that axis over ``pipe`` and run via :func:`pipeline_spmd`.
    """

    def __init__(self, make_stage: Callable[[], Module], n_stages: int):
        super().__init__()
        self.n_stages = n_stages
        self.stage = make_stage()

    def init(self, rng):
        keys = jax.random.split(rng, self.n_stages)
        per_stage = [self.stage.init(k) for k in keys]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)

    def __call__(self, params, x, **kw):
        """Reference (non-pipelined) execution: fold over stages sequentially."""
        def body(x, stage_params):
            return self.stage(stage_params, x, **kw), None
        out, _ = lax.scan(body, x, params)
        return out


def pipeline_spmd(stage_fn: Callable, mesh: Mesh, n_microbatches: int,
                  axis: str = "pipe"):
    """Build fn(stacked_params, x) running stage_fn through the pipe ring.

    stage_fn(stage_params, mb) -> mb', same shape. ``x`` is [B, ...]; it is
    split into ``n_microbatches`` along dim 0 (B % n_microbatches == 0).
    Returns the full output batch, replicated over the pipe axis.
    """
    n_stages = mesh.shape[axis]

    def local(params, x):
        # params leaves arrive [1, ...] (this stage's slice); drop the axis.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_id = lax.axis_index(axis)
        mb = x.reshape(n_microbatches, x.shape[0] // n_microbatches, *x.shape[1:])
        # activations become device-varying over 'pipe' after the first stage_fn;
        # cast the loop carry up front so the fori_loop carry type is stable
        state = compat.pcast(jnp.zeros_like(mb[0]), axis, to="varying")
        out_buf = compat.pcast(jnp.zeros_like(mb), axis, to="varying")
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        total = n_microbatches + n_stages - 1

        def tick(t, carry):
            state, out_buf = carry
            # stage 0 injects microbatch t (garbage-in after the last one;
            # results of those ticks are never collected)
            inj = mb[jnp.minimum(t, n_microbatches - 1)]
            inp = jnp.where(stage_id == 0, inj, state)
            out = stage_fn(params, inp)
            # last stage owns microbatch t-(n_stages-1) at tick t
            done_idx = t - (n_stages - 1)
            is_done = jnp.logical_and(stage_id == n_stages - 1, done_idx >= 0)
            write_at = jnp.clip(done_idx, 0, n_microbatches - 1)
            upd = jnp.where(is_done, out, out_buf[write_at])
            out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, write_at, 0)
            state = lax.ppermute(out, axis, fwd)
            return state, out_buf

        state, out_buf = lax.fori_loop(0, total, tick, (state, out_buf))
        # replicate the collected outputs (held by the last stage) to all stages
        mask = (stage_id == n_stages - 1).astype(out_buf.dtype)
        out_buf = lax.psum(out_buf * mask, axis)
        return out_buf.reshape(x.shape[0], *out_buf.shape[2:])

    pspec = P(axis)   # prefix spec: applies to every leaf of the params pytree
    xspec = P()
    return jax.jit(compat.shard_map(local, mesh=mesh, in_specs=(pspec, xspec),
                                 out_specs=xspec))


def pipeline_1f1b(stage_fn: Callable, loss_fn: Callable, mesh: Mesh,
                  n_microbatches: int, axis: str = "pipe"):
    """1F1B (PipeDream-flush) training schedule over the ``pipe`` axis.

    Builds ``step(stacked_params, x, y) -> (loss, stacked_grads)``.

    GPipe (``jax.grad`` through :func:`pipeline_spmd`) runs all M forwards
    then all M backwards, so every stage stashes M microbatch activations.
    1F1B interleaves: stage s's timetable is forwards at ticks ``s + 2m`` and
    backwards at ``2S - s - 1 + 2m`` (parities never collide), so at most
    ``S - s`` microbatches are in flight per stage and the input stash is a
    circular buffer of S slots — the memory bound is min(S, M) activations
    instead of M. The bubble fraction is the same (S-1)/(M+S-1) for both
    schedules (each does M+S-1 forward slots and M+S-1 backward slots);
    1F1B's win is memory, which is what lets M grow to amortize the bubble.
    Backward recomputes the stage forward from the stashed INPUT (standard
    rematerialization), so the stash holds inputs, not full residuals.

    Reference analog: ParallelNeuralNetwork.h:23-34 streams batches through
    per-device fwd/bwd task queues — 1F1B is that interleave, made explicit
    as a static SPMD timetable instead of threads.

    stage_fn(stage_params, mb) -> mb' (same shape); loss_fn(out_mb, y_mb) ->
    scalar mean loss for the microbatch. Returned loss/grads are averaged
    over microbatches; grads keep the stacked [n_stages, ...] leading axis.
    """
    n_stages = mesh.shape[axis]

    def local(params, x, y):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        S, M = n_stages, n_microbatches
        s = lax.axis_index(axis)
        mbx = x.reshape(M, x.shape[0] // M, *x.shape[1:])
        mby = y.reshape(M, y.shape[0] // M, *y.shape[1:])
        mb_shape = mbx[0]
        fwd_perm = [(i, (i + 1) % S) for i in range(S)]
        bwd_perm = [((i + 1) % S, i) for i in range(S)]

        def bwd_of(saved_inp, cot, y_mb, is_last):
            """Recompute-vjp one stage. The last stage seeds from the loss."""
            def last_branch(p, inp):
                lv, vjp = jax.vjp(
                    lambda pp, xx: loss_fn(stage_fn(pp, xx), y_mb), p, inp)
                dp, dx = vjp(jnp.ones_like(lv))
                return lv.astype(jnp.float32), dp, dx

            def mid_branch(p, inp):
                _, vjp = jax.vjp(stage_fn, p, inp)
                dp, dx = vjp(cot)
                return jnp.float32(0), dp, dx

            return lax.cond(is_last, last_branch, mid_branch,
                            params, saved_inp)

        def tick(t, carry):
            fwd_msg, bwd_msg, stash, dparams, loss_acc = carry
            # static timetable, evaluated per device from its axis index
            tf = t - s
            do_fwd = (tf >= 0) & (tf % 2 == 0) & (tf // 2 < M)
            m_f = jnp.clip(tf // 2, 0, M - 1)
            tb = t - (2 * S - s - 1)
            do_bwd = (tb >= 0) & (tb % 2 == 0) & (tb // 2 < M)
            m_b = jnp.clip(tb // 2, 0, M - 1)

            inp = jnp.where(s == 0, mbx[m_f], fwd_msg)
            saved = lax.dynamic_index_in_dim(stash, m_b % S, 0,
                                             keepdims=False)

            def do_backward(_):
                lv, dp, dx = bwd_of(saved, bwd_msg, mby[m_b], s == S - 1)
                return jnp.zeros_like(mb_shape), dx, dp, lv

            def do_forward(_):
                out = stage_fn(params, inp)
                zp = jax.tree_util.tree_map(jnp.zeros_like, params)
                return out, jnp.zeros_like(mb_shape), zp, jnp.float32(0)

            send_f, send_b, dp, lv = lax.cond(do_bwd, do_backward,
                                              do_forward, None)
            # mask edges: idle ticks run the forward branch on garbage input
            send_f = jnp.where(do_fwd, send_f, 0).astype(mb_shape.dtype)
            stash = lax.cond(
                do_fwd,
                lambda st: lax.dynamic_update_index_in_dim(
                    st, inp, m_f % S, 0),
                lambda st: st, stash)
            dparams = jax.tree_util.tree_map(jnp.add, dparams, dp)
            loss_acc = loss_acc + lv
            fwd_msg = lax.ppermute(send_f, axis, fwd_perm)
            bwd_msg = lax.ppermute(send_b, axis, bwd_perm)
            return fwd_msg, bwd_msg, stash, dparams, loss_acc

        zero_mb = compat.pcast(jnp.zeros_like(mb_shape), axis, to="varying")
        stash0 = compat.pcast(
            jnp.zeros((S,) + mb_shape.shape, mb_shape.dtype), axis,
            to="varying")
        dp0 = compat.pcast(jax.tree_util.tree_map(jnp.zeros_like, params),
                        axis, to="varying")
        carry = (zero_mb, zero_mb, stash0, dp0, jnp.float32(0))
        total = 2 * (M + S - 1)
        _, _, _, dparams, loss_acc = lax.fori_loop(0, total, tick, carry)
        loss = lax.psum(loss_acc, axis) / M
        dparams = jax.tree_util.tree_map(lambda g: (g / M)[None], dparams)
        return loss, dparams

    pspec = P(axis)
    return jax.jit(compat.shard_map(
        local, mesh=mesh, in_specs=(pspec, P(), P()),
        out_specs=(P(), pspec), check_vma=False))
