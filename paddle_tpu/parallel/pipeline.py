"""Pipeline parallelism over a ``pipe`` mesh axis (GPipe-style SPMD).

The reference pipelines by placing whole layers on devices and streaming
batches through per-device threads (ParallelNeuralNetwork.h:23-34, TaskType
fwd/bwd queues). TPU-native: all stages run the SAME jitted SPMD program; stage
parameters are stacked on a leading axis sharded over ``pipe``, microbatch
activations hop stage->stage via ``ppermute`` over ICI, and the schedule is a
``lax.fori_loop`` of (n_microbatches + n_stages - 1) ticks. Autodiff flows
through ppermute, so the same program trains (XLA overlaps the transfers —
recovering the reference's thread-pipelined overlap, SURVEY §2.5 row
'Pipeline-ish overlap').

Constraint inherited from SPMD: every stage must share one activation shape
(equal-width trunk), the usual homogeneous-transformer-stack case.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..nn.module import Module


class PipelineStage(Module):
    """Repeats one stage Module across pipeline stages with stacked params.

    ``init`` produces params with a leading [n_stages] axis on every leaf;
    shard that axis over ``pipe`` and run via :func:`pipeline_spmd`.
    """

    def __init__(self, make_stage: Callable[[], Module], n_stages: int):
        super().__init__()
        self.n_stages = n_stages
        self.stage = make_stage()

    def init(self, rng):
        keys = jax.random.split(rng, self.n_stages)
        per_stage = [self.stage.init(k) for k in keys]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)

    def __call__(self, params, x, **kw):
        """Reference (non-pipelined) execution: fold over stages sequentially."""
        def body(x, stage_params):
            return self.stage(stage_params, x, **kw), None
        out, _ = lax.scan(body, x, params)
        return out


def pipeline_spmd(stage_fn: Callable, mesh: Mesh, n_microbatches: int,
                  axis: str = "pipe"):
    """Build fn(stacked_params, x) running stage_fn through the pipe ring.

    stage_fn(stage_params, mb) -> mb', same shape. ``x`` is [B, ...]; it is
    split into ``n_microbatches`` along dim 0 (B % n_microbatches == 0).
    Returns the full output batch, replicated over the pipe axis.
    """
    n_stages = mesh.shape[axis]

    def local(params, x):
        # params leaves arrive [1, ...] (this stage's slice); drop the axis.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_id = lax.axis_index(axis)
        mb = x.reshape(n_microbatches, x.shape[0] // n_microbatches, *x.shape[1:])
        # activations become device-varying over 'pipe' after the first stage_fn;
        # cast the loop carry up front so the fori_loop carry type is stable
        state = lax.pcast(jnp.zeros_like(mb[0]), axis, to="varying")
        out_buf = lax.pcast(jnp.zeros_like(mb), axis, to="varying")
        fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        total = n_microbatches + n_stages - 1

        def tick(t, carry):
            state, out_buf = carry
            # stage 0 injects microbatch t (garbage-in after the last one;
            # results of those ticks are never collected)
            inj = mb[jnp.minimum(t, n_microbatches - 1)]
            inp = jnp.where(stage_id == 0, inj, state)
            out = stage_fn(params, inp)
            # last stage owns microbatch t-(n_stages-1) at tick t
            done_idx = t - (n_stages - 1)
            is_done = jnp.logical_and(stage_id == n_stages - 1, done_idx >= 0)
            write_at = jnp.clip(done_idx, 0, n_microbatches - 1)
            upd = jnp.where(is_done, out, out_buf[write_at])
            out_buf = lax.dynamic_update_index_in_dim(out_buf, upd, write_at, 0)
            state = lax.ppermute(out, axis, fwd)
            return state, out_buf

        state, out_buf = lax.fori_loop(0, total, tick, (state, out_buf))
        # replicate the collected outputs (held by the last stage) to all stages
        mask = (stage_id == n_stages - 1).astype(out_buf.dtype)
        out_buf = lax.psum(out_buf * mask, axis)
        return out_buf.reshape(x.shape[0], *out_buf.shape[2:])

    pspec = P(axis)   # prefix spec: applies to every leaf of the params pytree
    xspec = P()
    return jax.jit(jax.shard_map(local, mesh=mesh, in_specs=(pspec, xspec),
                                 out_specs=xspec))
