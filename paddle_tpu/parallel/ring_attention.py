"""Sequence/context parallelism: ring attention over a ``seq`` mesh axis.

The 2017 reference's longest-sequence story is padding-free LoD batching
(SURVEY.md §5 long-context) — there is no sequence-dim sharding to port. This
module provides the modern first-class capability the TPU build is required to
have: sequences sharded over a mesh axis, attention computed exactly via a ring
of ``ppermute`` steps with online-softmax (flash-style) accumulation, so each
chip only ever holds 1/N of the KV cache and the KV blocks ride the ICI ring.

Layout: q/k/v are [batch, time_local, heads, head_dim] inside ``shard_map`` over
the ``seq`` axis; time_local = T_global / n_shards.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG = -1e30


def _online_update(o, l, m, scores, v):
    """One flash-attention accumulation step.

    o [B,T,H,D] running numerator; l [B,H,T] running denominator; m [B,H,T]
    running max; scores [B,H,T,S]; v [B,S,H,D].
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    p = jnp.exp(scores - m_new[..., None])             # [B,H,T,S]
    corr = jnp.exp(m - m_new)                          # [B,H,T]
    l = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhts,bshd->bthd", p, v)
    o = o * corr.transpose(0, 2, 1)[..., None] + pv
    return o, l, m_new


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_size: int = 512, causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Single-device memory-efficient attention: scan over KV blocks.

    Never materialises the [T, S] score matrix beyond one [T, block] tile —
    the host-memory analog of what the Pallas flash kernel does in VMEM.
    q,k,v: [B, T, H, D] -> [B, T, H, D].
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    nblk = -(-S // block_size)
    pad = nblk * block_size - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kb = k.reshape(B, nblk, block_size, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nblk, block_size, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(T)

    def body(carry, blk):
        o, l, m, i = carry
        kblk, vblk = blk
        scores = jnp.einsum("bthd,bshd->bhts", q, kblk) * scale
        k_pos = i * block_size + jnp.arange(block_size)
        valid = k_pos < S
        mask = valid[None, :]
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])
        scores = jnp.where(mask[None, None], scores, _NEG)
        o, l, m = _online_update(o, l, m, scores, vblk)
        return (o, l, m, i + 1), None

    # derive accumulator initials from q so they carry q's device-varying type
    # (required for the scan carry when running inside shard_map)
    o0 = (q * 0).astype(jnp.float32)
    l0 = (q[..., 0] * 0).astype(jnp.float32).transpose(0, 2, 1)
    m0 = l0 + _NEG
    (o, l, m, _), _ = lax.scan(body, (o0, l0, m0, 0), (kb, vb))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False, scale: Optional[float] = None) -> jax.Array:
    """Exact attention with KV rotating around the ``axis_name`` ring.

    Call inside shard_map with q/k/v time-sharded: [B, T_local, H, D]. Each of
    the n ring steps computes attention of the local Q block against the
    currently-held KV block, then passes KV to the neighbour (ppermute over
    ICI). Online softmax keeps the result exact.
    """
    B, T, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    t_local = jnp.arange(T)
    q_pos = my * T + t_local

    # derive accumulator initials from q so the fori_loop carry keeps q's
    # device-varying type under shard_map's varying-axes check
    o = (q * 0).astype(jnp.float32)
    l = (q[..., 0] * 0).astype(jnp.float32).transpose(0, 2, 1)
    m = l + _NEG
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        o, l, m, k, v = carry
        src = (my - i) % n                       # whose KV block we hold now
        scores = jnp.einsum("bthd,bshd->bhts", q, k) * scale
        if causal:
            k_pos = src * T + t_local
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)
        o, l, m = _online_update(o, l, m, scores, v)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return o, l, m, k, v

    o, l, m, k, v = lax.fori_loop(0, n, body, (o, l, m, k, v))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def ring_self_attention(mesh: Mesh, q, k, v, seq_axis: str = "seq",
                        causal: bool = False):
    """Host-level wrapper: shard_map ring_attention over the mesh's seq axis.

    q/k/v: [B, T_global, H, D] (replicated or already seq-sharded on dim 1).
    """
    spec = P(None, seq_axis, None, None)
    fn = jax.shard_map(
        partial(ring_attention, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def ulysses_attention(mesh: Mesh, q, k, v, seq_axis: str = "seq",
                      causal: bool = False):
    """DeepSpeed-Ulysses-style sequence parallelism: all_to_all re-shards
    time-sharded q/k/v to head-sharded, runs full attention locally over the
    whole sequence, then all_to_alls back. Complements ring attention when
    heads >= shards: two a2a's instead of n ppermute steps.
    """
    spec = P(None, seq_axis, None, None)

    def local(q, k, v):
        # [B, T/n, H, D] -> a2a -> [B, T, H/n, D]
        q = lax.all_to_all(q, seq_axis, split_axis=2, concat_axis=1, tiled=True)
        k = lax.all_to_all(k, seq_axis, split_axis=2, concat_axis=1, tiled=True)
        v = lax.all_to_all(v, seq_axis, split_axis=2, concat_axis=1, tiled=True)
        o = blockwise_attention(q, k, v, block_size=max(q.shape[1] // 4, 128),
                                causal=causal)
        return lax.all_to_all(o, seq_axis, split_axis=1, concat_axis=2, tiled=True)

    fn = jax.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec)
    return fn(q, k, v)
