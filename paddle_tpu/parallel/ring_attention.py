"""Sequence/context parallelism: ring attention over a ``seq`` mesh axis.

The 2017 reference's longest-sequence story is padding-free LoD batching
(SURVEY.md §5 long-context) — there is no sequence-dim sharding to port. This
module provides the modern first-class capability the TPU build is required to
have: sequences sharded over a mesh axis, attention computed exactly via a ring
of ``ppermute`` steps with online-softmax (flash-style) accumulation, so each
chip only ever holds 1/N of the KV cache and the KV blocks ride the ICI ring.

Per-step compute runs the Pallas flash kernel (ops/pallas_kernels.py), so the
[T_local, T_local] score tile lives only in VMEM. The backward pass is
hand-written: because flash-attention block gradients factor over key blocks
given the *global* logsumexp and delta = rowsum(dO·O), each ring step computes
one block's (dq, dk, dv) with the Pallas backward kernels while the dk/dv
accumulators ride the ring alongside their KV block — after n steps every
accumulator is back home with contributions from all devices.

Layout: q/k/v are [batch, time_local, heads, head_dim] inside ``shard_map`` over
the ``seq`` axis; time_local = T_global / n_shards.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops import pallas_kernels as pk
from . import compat

_NEG = -1e30


def _online_update(o, l, m, scores, v):
    """One flash-attention accumulation step.

    o [B,T,H,D] running numerator; l [B,H,T] running denominator; m [B,H,T]
    running max; scores [B,H,T,S]; v [B,S,H,D].
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    p = jnp.exp(scores - m_new[..., None])             # [B,H,T,S]
    corr = jnp.exp(m - m_new)                          # [B,H,T]
    l = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhts,bshd->bthd", p, v)
    o = o * corr.transpose(0, 2, 1)[..., None] + pv
    return o, l, m_new


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_size: int = 512, causal: bool = False,
                        scale: Optional[float] = None) -> jax.Array:
    """Single-device memory-efficient attention: scan over KV blocks.

    Never materialises the [T, S] score matrix beyond one [T, block] tile —
    the host-memory analog of what the Pallas flash kernel does in VMEM.
    q,k,v: [B, T, H, D] -> [B, T, H, D].
    """
    B, T, H, D = q.shape
    S = k.shape[1]
    scale = scale if scale is not None else D ** -0.5
    nblk = -(-S // block_size)
    pad = nblk * block_size - S
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    kb = k.reshape(B, nblk, block_size, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nblk, block_size, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(T)

    def body(carry, blk):
        o, l, m, i = carry
        kblk, vblk = blk
        scores = jnp.einsum("bthd,bshd->bhts", q, kblk) * scale
        k_pos = i * block_size + jnp.arange(block_size)
        valid = k_pos < S
        mask = valid[None, :]
        if causal:
            mask = mask & (q_pos[:, None] >= k_pos[None, :])
        scores = jnp.where(mask[None, None], scores, _NEG)
        o, l, m = _online_update(o, l, m, scores, vblk)
        return (o, l, m, i + 1), None

    # derive accumulator initials from q so they carry q's device-varying type
    # (required for the scan carry when running inside shard_map)
    o0 = (q * 0).astype(jnp.float32)
    l0 = (q[..., 0] * 0).astype(jnp.float32).transpose(0, 2, 1)
    m0 = l0 + _NEG
    (o, l, m, _), _ = lax.scan(body, (o0, l0, m0, 0), (kb, vb))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l.transpose(0, 2, 1)[..., None]).astype(q.dtype)


def _merge_partials(o1, lse1, o2, lse2):
    """Exactly combine two attention partials over disjoint key sets.

    o_i are softmax-normalised within their key set, lse_i the corresponding
    logsumexp [B,T,H]. Returns the merged (o, lse).
    """
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = w1 + w2
    safe = jnp.where(denom == 0.0, 1.0, denom)
    o = (o1 * w1[..., None] + o2 * w2[..., None]) / safe[..., None]
    return o, m + jnp.log(safe)


def _step_attention(q, k, v, diag, causal, scale, interpret):
    """One ring step's partial attention: Pallas flash kernel, (o_f32, lse).

    ``diag`` (traced bool) selects the causally-masked kernel when this step
    holds the device's own KV block.
    """
    if not causal:
        o, lse = pk.flash_attention_with_lse(q, k, v, causal=False,
                                             scale=scale, interpret=interpret)
        return o.astype(jnp.float32), lse
    o, lse = lax.cond(
        diag,
        lambda args: pk.flash_attention_with_lse(*args, causal=True,
                                                 scale=scale,
                                                 interpret=interpret),
        lambda args: pk.flash_attention_with_lse(*args, causal=False,
                                                 scale=scale,
                                                 interpret=interpret),
        (q, k, v))
    return o.astype(jnp.float32), lse


# ---------------------------------------------------------------------------
# zigzag (load-balanced causal) layout
#
# The contiguous layout wastes ~(n-1)/2n of causal ring FLOPs: whole KV
# blocks from the future are computed then discarded. The zigzag layout
# (llama3-style: split the sequence into 2n chunks, device d holds chunks
# (d, 2n-1-d)) makes every step do the same ~half-block of useful work:
#
#   * src == my (diagonal): the local 2c-causal mask is EXACTLY right for
#     the (d, 2n-1-d) chunk pair — chunk d attends itself causally and never
#     reaches chunk 2n-1-d's keys; chunk 2n-1-d attends chunk d fully and
#     itself causally. One plain causal flash call, nothing wasted.
#   * src < my (block from the past): both local q chunks attend only the
#     held block's FIRST chunk (its second chunk 2n-1-src is in both q
#     chunks' future) -> one half-width kernel call.
#   * src > my (block from the future): only the local SECOND q chunk
#     attends (the held block is entirely in chunk 2n-1-my's past) -> one
#     half-height kernel call.
#
# _zigzag_step_pairs() is the work accounting used by the balance test.
# ---------------------------------------------------------------------------

def zigzag_order(T: int, n: int):
    """Global position order such that contiguous equal shards of the
    REORDERED sequence give device d chunks (d, 2n-1-d) of the original."""
    if T % (2 * n):
        raise ValueError(f"T={T} must divide into 2*{n} zigzag chunks")
    c = T // (2 * n)
    idx = []
    for d in range(n):
        idx.extend(range(d * c, (d + 1) * c))
        idx.extend(range((2 * n - 1 - d) * c, (2 * n - d) * c))
    return jnp.asarray(idx, jnp.int32)


def zigzag_inverse(T: int, n: int):
    order = zigzag_order(T, n)
    inv = jnp.zeros((T,), jnp.int32).at[order].set(jnp.arange(T, dtype=jnp.int32))
    return inv


def _zigzag_step_pairs(c: int):
    """(diagonal, off-diagonal) attended (q, key) pair counts per ring step
    per device — the layout's work model. Diagonal: the 2c-causal triangle
    (= 2c^2 + c pairs); every off-diagonal step: exactly half the 2c x 2c
    block (2c^2), whichever direction the held block came from."""
    diag = 2 * c * (2 * c + 1) // 2
    off = 2 * c * c
    return diag, off


def _zigzag_step(q, k, v, case, scale, interpret):
    """One zigzag ring step: lax.switch over diagonal/past/future shapes.

    Returns (o [B,2c,H,D] f32, lse [B,2c,H]) with -inf lse on rows that
    attend nothing this step (only q chunk 1 on future steps)."""
    B, T2, H, D = q.shape
    c = T2 // 2

    def diag(_):
        o, lse = pk.flash_attention_with_lse(q, k, v, causal=True,
                                             scale=scale, interpret=interpret)
        return o.astype(jnp.float32), lse

    def past(_):
        # all q rows vs the held block's first chunk
        o, lse = pk.flash_attention_with_lse(q, k[:, :c], v[:, :c],
                                             causal=False, scale=scale,
                                             interpret=interpret)
        return o.astype(jnp.float32), lse

    def future(_):
        # only the local second q chunk vs the whole held block; padding
        # rows derive from q so they carry its device-varying type under
        # shard_map
        o2, lse2 = pk.flash_attention_with_lse(q[:, c:], k, v, causal=False,
                                               scale=scale,
                                               interpret=interpret)
        zo = (q[:, :c] * 0).astype(jnp.float32)
        zl = (q[:, :c, :, 0] * 0).astype(jnp.float32) + _NEG
        o = jnp.concatenate([zo, o2.astype(jnp.float32)], axis=1)
        lse = jnp.concatenate([zl, lse2], axis=1)
        return o, lse

    return lax.switch(case, (diag, past, future), None)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
                   causal: bool = False, scale: Optional[float] = None,
                   interpret: Optional[bool] = None,
                   zigzag: bool = False) -> jax.Array:
    """Exact attention with KV rotating around the ``axis_name`` ring.

    Call inside shard_map with q/k/v time-sharded: [B, T_local, H, D]. Each of
    the n ring steps runs the Pallas flash kernel on the local Q block against
    the currently-held KV block, then passes KV to the neighbour (ppermute
    over ICI); partials merge exactly via logaddexp.

    ``zigzag`` (causal only): the local block must hold chunks
    (d, 2n-1-d) of the zigzag-reordered sequence (zigzag_order();
    ring_self_attention does the reordering) — every ring step then does
    ~half-block useful work instead of discarding whole future blocks,
    recovering the ~(n-1)/2n of FLOPs the contiguous layout wastes.
    """
    o, _ = _ring_forward(q, k, v, axis_name, causal, scale, interpret, zigzag)
    return o


def _ring_forward(q, k, v, axis_name, causal, scale, interpret, zigzag=False):
    B, T, H, D = q.shape
    scale_v = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = not pk._on_tpu()
    if zigzag and not causal:
        raise ValueError("zigzag layout only applies to causal attention")
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)

    # derive accumulator initials from q so the fori_loop carry keeps q's
    # device-varying type under shard_map's varying-axes check
    o = (q * 0).astype(jnp.float32)
    lse = (q[..., 0] * 0).astype(jnp.float32) + _NEG    # [B,T,H]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(i, carry):
        o, lse, k, v = carry
        src = (my - i) % n                   # whose KV block we hold now
        if zigzag:
            case = jnp.where(src == my, 0, jnp.where(src < my, 1, 2))
            o_i, lse_i = _zigzag_step(q, k, v, case, scale_v, interpret)
        else:
            o_i, lse_i = _step_attention(q, k, v, src == my, causal, scale_v,
                                         interpret)
            if causal:
                # blocks strictly in the future contribute nothing
                skip = src > my
                lse_i = jnp.where(skip, _NEG, lse_i)
        o, lse = _merge_partials(o, lse, o_i, lse_i)
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        return o, lse, k, v

    o, lse, k, v = lax.fori_loop(0, n, body, (o, lse, k, v))
    return o.astype(q.dtype), lse


def _ring_fwd(q, k, v, axis_name, causal, scale, interpret, zigzag=False):
    o, lse = _ring_forward(q, k, v, axis_name, causal, scale, interpret,
                           zigzag)
    return o, (q, k, v, o, lse)


def _ring_bwd(axis_name, causal, scale, interpret, zigzag, res, g):
    q, k, v, o, lse = res
    B, T, H, D = q.shape
    c = T // 2
    scale_v = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = not pk._on_tpu()
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # loop-invariant across ring steps: compute once, pass into each block
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    def block_grads(k_blk, v_blk, diag):
        """(dq, dk, dv) for the local Q against one KV block, using the
        global lse/delta (flash block gradients factor over key blocks)."""
        if not causal:
            return pk.flash_block_grads(q, k_blk, v_blk, o, lse, g,
                                        causal=False, scale=scale_v,
                                        interpret=interpret, delta=delta)
        return lax.cond(
            diag,
            lambda args: pk.flash_block_grads(q, *args, o, lse, g,
                                              causal=True, scale=scale_v,
                                              interpret=interpret,
                                              delta=delta),
            lambda args: pk.flash_block_grads(q, *args, o, lse, g,
                                              causal=False, scale=scale_v,
                                              interpret=interpret,
                                              delta=delta),
            (k_blk, v_blk))

    def zz_block_grads(k_blk, v_blk, case):
        """Zigzag block gradients — the same three work shapes as
        _zigzag_step, zero-padded to full-block accumulators."""
        f32 = lambda *ts: tuple(t.astype(jnp.float32) for t in ts)

        def diag(_):
            return f32(*pk.flash_block_grads(
                q, k_blk, v_blk, o, lse, g, causal=True, scale=scale_v,
                interpret=interpret, delta=delta))

        def past(_):
            dq, dk1, dv1 = pk.flash_block_grads(
                q, k_blk[:, :c], v_blk[:, :c], o, lse, g, causal=False,
                scale=scale_v, interpret=interpret, delta=delta)
            z = (q[:, :c] * 0).astype(jnp.float32)   # device-varying zeros
            return (dq.astype(jnp.float32),
                    jnp.concatenate([dk1.astype(jnp.float32), z], axis=1),
                    jnp.concatenate([dv1.astype(jnp.float32), z], axis=1))

        def future(_):
            dq2, dk, dv = pk.flash_block_grads(
                q[:, c:], k_blk, v_blk, o[:, c:], lse[:, c:], g[:, c:],
                causal=False, scale=scale_v, interpret=interpret,
                delta=delta[:, c:])
            z = (q[:, :c] * 0).astype(jnp.float32)   # device-varying zeros
            return (jnp.concatenate([z, dq2.astype(jnp.float32)], axis=1),
                    dk.astype(jnp.float32), dv.astype(jnp.float32))

        return lax.switch(case, (diag, past, future), None)

    dq0 = (q * 0).astype(jnp.float32)
    dk0 = (k * 0).astype(jnp.float32)
    dv0 = (v * 0).astype(jnp.float32)

    def body(i, carry):
        dq, k_blk, v_blk, dk, dv = carry
        src = (my - i) % n
        if zigzag:
            case = jnp.where(src == my, 0, jnp.where(src < my, 1, 2))
            dq_i, dk_i, dv_i = zz_block_grads(k_blk, v_blk, case)
        else:
            dq_i, dk_i, dv_i = block_grads(k_blk, v_blk, src == my)
            if causal:
                skip = src > my
                dq_i = jnp.where(skip, 0.0, dq_i.astype(jnp.float32))
                dk_i = jnp.where(skip, 0.0, dk_i.astype(jnp.float32))
                dv_i = jnp.where(skip, 0.0, dv_i.astype(jnp.float32))
        dq = dq + dq_i.astype(jnp.float32)
        dk = dk + dk_i.astype(jnp.float32)
        dv = dv + dv_i.astype(jnp.float32)
        # dk/dv accumulators travel WITH their KV block; after n hops each is
        # back at its home device having collected every device's contribution
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, k_blk, v_blk, dk, dv

    dq, _, _, dk, dv = lax.fori_loop(0, n, body, (dq0, k, v, dk0, dv0))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_attention.defvjp(_ring_fwd, _ring_bwd)


def ring_self_attention(mesh: Mesh, q, k, v, seq_axis: str = "seq",
                        causal: bool = False,
                        layout: Optional[str] = None):
    """Host-level wrapper: shard_map ring_attention over the mesh's seq axis.

    q/k/v: [B, T_global, H, D] in ORIGINAL sequence order (replicated or
    already seq-sharded on dim 1). ``layout``: "zigzag" (default for
    causal — load-balanced, no discarded future blocks) or "contiguous".
    The zigzag permutation and its inverse are applied here, so callers
    always see original-order tensors.
    """
    if layout is None:
        layout = "zigzag" if causal else "contiguous"
    zigzag = layout == "zigzag" and causal
    spec = P(None, seq_axis, None, None)
    n = mesh.shape[seq_axis]
    T = q.shape[1]
    if zigzag and T % (2 * n):
        # the contiguous causal layout computes-and-discards roughly half the
        # ring's K/V blocks (device i skips blocks from devices > i), so the
        # fallback costs ~2x the balanced zigzag FLOPs — never take it
        # silently
        import warnings
        warnings.warn(
            f"ring_self_attention: T={T} is not divisible by 2*n_shards"
            f"={2 * n}; falling back to the CONTIGUOUS causal layout, which "
            "wastes ~half the attention FLOPs vs zigzag. Pad the sequence "
            f"to a multiple of {2 * n} to keep the load-balanced layout.",
            stacklevel=2)
        zigzag = False                       # shape can't chunk: fall back
    if zigzag:
        order = zigzag_order(T, n)
        q, k, v = (jnp.take(x, order, axis=1) for x in (q, k, v))
    # check_vma=False: pallas_call out_shapes carry no varying-mesh-axes info
    fn = compat.shard_map(
        partial(ring_attention, axis_name=seq_axis, causal=causal,
                zigzag=zigzag),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    o = fn(q, k, v)
    if zigzag:
        o = jnp.take(o, zigzag_inverse(T, n), axis=1)
    return o


def ulysses_attention(mesh: Mesh, q, k, v, seq_axis: str = "seq",
                      causal: bool = False):
    """DeepSpeed-Ulysses-style sequence parallelism: all_to_all re-shards
    time-sharded q/k/v to head-sharded, runs the Pallas flash kernel locally
    over the whole sequence, then all_to_alls back. Complements ring attention
    when heads >= shards: two a2a's instead of n ppermute steps.
    """
    spec = P(None, seq_axis, None, None)

    def local(q, k, v):
        # [B, T/n, H, D] -> a2a -> [B, T, H/n, D]
        q = lax.all_to_all(q, seq_axis, split_axis=2, concat_axis=1, tiled=True)
        k = lax.all_to_all(k, seq_axis, split_axis=2, concat_axis=1, tiled=True)
        v = lax.all_to_all(v, seq_axis, split_axis=2, concat_axis=1, tiled=True)
        o = pk.flash_attention(q, k, v, causal=causal)
        return lax.all_to_all(o, seq_axis, split_axis=1, concat_axis=2, tiled=True)

    fn = compat.shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
