"""Sharding rules: map parameter paths / batch tensors to NamedShardings.

The reference decides placement imperatively (per-layer ``device`` field,
proto/ModelConfig.proto:362, executed by ParallelNeuralNetwork.h:23-34; parameter
blocks hashed to pservers, ParameterClient2.cpp). TPU-native: placement is a pure
function from a parameter's *path* to a PartitionSpec; XLA's SPMD partitioner does
the rest.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

def shard(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    """NamedSharding with one mesh axis (or None) per tensor dim."""
    return NamedSharding(mesh, P(*axes))


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Put a host batch onto the mesh, sharding dim 0 of every leaf over ``axis``.

    The analog of MultiGradientMachine's batch split across TrainerThreads
    (MultiGradientMachine.h:44-60), but done by sharding, not slicing.
    """
    if axis not in mesh.shape:
        sh = replicate(mesh)
        return jax.device_put(batch, sh)

    def put(x):
        nd = getattr(x, "ndim", 0)
        spec = P(axis, *([None] * (nd - 1))) if nd >= 1 else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


class ShardingRules:
    """Ordered (path-regex -> PartitionSpec) table for parameter pytrees.

    Example (megatron-style 2D for a transformer block)::

        rules = ShardingRules([
            (r".*/attn/.*proj_qkv/w$", P(None, "model")),   # column parallel
            (r".*/attn/.*proj_out/w$", P("model", None)),   # row parallel
            (r".*/embed/table$",       P("model", None)),   # vocab-sharded
            (r".*",                    P()),                # replicate the rest
        ])
        params = rules.apply(mesh, params)
    """

    def __init__(self, rules: Sequence[Tuple[str, P]]):
        self.rules: List[Tuple[re.Pattern, P]] = [(re.compile(pat), spec)
                                                  for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        for pat, spec in self.rules:
            if pat.fullmatch(path) or pat.match(path):
                return spec
        return P()

    def apply(self, mesh: Mesh, params):
        """device_put every leaf per its matched spec."""
        flat = _flatten_with_paths(params)
        out = {}
        for path, leaf in flat:
            sh = NamedSharding(mesh, self.spec_for(path))
            out[path] = jax.device_put(leaf, sh)
        return _unflatten_paths(out)

    def shardings(self, mesh: Mesh, params):
        """A pytree of NamedShardings matching ``params`` (for jit in_shardings)."""
        flat = _flatten_with_paths(params)
        out = {p: NamedSharding(mesh, self.spec_for(p)) for p, _ in flat}
        return _unflatten_paths(out)


def shard_params(params, mesh: Mesh, rules: Optional[ShardingRules] = None):
    """Place a params pytree on the mesh (replicated unless rules say otherwise)."""
    if rules is None:
        return jax.device_put(params, replicate(mesh))
    return rules.apply(mesh, params)


def with_sharding_constraint(x, mesh: Mesh, *axes: Optional[str]):
    """In-jit resharding hint (the layer-boundary layout conversion point — the
    analog of MKLDNN's convertWeightsFromPaddle boundary, SURVEY §8.3)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*axes)))


# -- path-dict helpers: the canonical codec shared with trainer/checkpoint -----
from ..core.pytree import flatten_path_tree as _flatten_with_paths  # noqa: E402
from ..core.pytree import unflatten_path_tree as _unflatten_paths  # noqa: E402
