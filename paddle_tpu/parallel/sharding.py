"""Sharding rules: map parameter paths / batch tensors to NamedShardings.

The reference decides placement imperatively (per-layer ``device`` field,
proto/ModelConfig.proto:362, executed by ParallelNeuralNetwork.h:23-34; parameter
blocks hashed to pservers, ParameterClient2.cpp). TPU-native: placement is a pure
function from a parameter's *path* to a PartitionSpec; XLA's SPMD partitioner does
the rest.
"""

from __future__ import annotations

import re
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

def shard(mesh: Mesh, *axes: Optional[str]) -> NamedSharding:
    """NamedSharding with one mesh axis (or None) per tensor dim."""
    return NamedSharding(mesh, P(*axes))


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Put a host batch onto the mesh, sharding dim 0 of every leaf over ``axis``.

    The analog of MultiGradientMachine's batch split across TrainerThreads
    (MultiGradientMachine.h:44-60), but done by sharding, not slicing.
    """
    if axis not in mesh.shape:
        sh = replicate(mesh)
        return jax.device_put(batch, sh)

    def put(x):
        nd = getattr(x, "ndim", 0)
        spec = P(axis, *([None] * (nd - 1))) if nd >= 1 else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(put, batch)


def _first_match(rules, path: str) -> Optional[P]:
    """First spec whose compiled pattern matches ``path`` (prefix match,
    the ParamAttr-era regex contract) — the ONE implementation of rule
    lookup, shared by ShardingRules and SpecLayout so their semantics
    cannot drift."""
    for pat, spec in rules:
        if pat.match(path):
            return spec
    return None


class ShardingRules:
    """Ordered (path-regex -> PartitionSpec) table for parameter pytrees.

    Example (megatron-style 2D for a transformer block)::

        rules = ShardingRules([
            (r".*/attn/.*proj_qkv/w$", P(None, "model")),   # column parallel
            (r".*/attn/.*proj_out/w$", P("model", None)),   # row parallel
            (r".*/embed/table$",       P("model", None)),   # vocab-sharded
            (r".*",                    P()),                # replicate the rest
        ])
        params = rules.apply(mesh, params)
    """

    def __init__(self, rules: Sequence[Tuple[str, P]]):
        self.rules: List[Tuple[re.Pattern, P]] = [(re.compile(pat), spec)
                                                  for pat, spec in rules]

    def spec_for(self, path: str) -> P:
        spec = _first_match(self.rules, path)
        return spec if spec is not None else P()

    def apply(self, mesh: Mesh, params):
        """device_put every leaf per its matched spec."""
        flat = _flatten_with_paths(params)
        out = {}
        for path, leaf in flat:
            sh = NamedSharding(mesh, self.spec_for(path))
            out[path] = jax.device_put(leaf, sh)
        return _unflatten_paths(out)

    def shardings(self, mesh: Mesh, params):
        """A pytree of NamedShardings matching ``params`` (for jit in_shardings)."""
        flat = _flatten_with_paths(params)
        out = {p: NamedSharding(mesh, self.spec_for(p)) for p, _ in flat}
        return _unflatten_paths(out)


class SpecLayout:
    """Resolve parameters/persistables to PartitionSpecs on a named mesh.

    The layout-resolution contract (docs/design/spmd.md), highest wins:

    1. an explicit per-variable ``sharding`` annotation (``Variable.sharding``
       riding Program JSON, or the ``annotation`` argument here),
    2. the first matching user rule — an ordered (path-regex ->
       PartitionSpec) table, :class:`ShardingRules` style,
    3. built-in role rules (``roles=True``): embedding tables shard their
       vocab dim over ``(fsdp, tp)``; other 2-D weights shard
       ``(fsdp, tp)``; >=3-D kernels shard the output-channel (last) dim
       over ``tp``; 1-D vectors and scalars replicate,
    4. replicated.

    Every resolved spec is then *fitted* to the actual mesh and value
    shape: axes the mesh does not carry drop out of the spec, and a dim
    whose extent is not divisible by its axes' total size falls back to
    replicated on that dim — an annotation written for a 256-way pod
    degrades gracefully on a 8-chip test mesh instead of erroring at
    placement time.
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, P]]] = None, *,
                 data_axis: str = "data", fsdp_axis: str = "fsdp",
                 tp_axis: str = "tp", roles: bool = True):
        self.data_axis = data_axis
        self.fsdp_axis = fsdp_axis
        self.tp_axis = tp_axis
        self.roles = roles
        self.rules = ShardingRules(rules) if rules else None

    # -- resolution --------------------------------------------------------
    def spec_for(self, path: str, shape: Sequence[int] = (),
                 annotation: Optional[Sequence] = None) -> P:
        """The un-fitted spec for one value (contract order above)."""
        if annotation is not None:
            return P(*annotation)
        if self.rules is not None:
            spec = _first_match(self.rules.rules, path)
            if spec is not None:
                return spec
        if not self.roles:
            return P()
        ndim = len(shape)
        if ndim >= 2 and "embed" in path.lower():
            # vocab rows over fsdp x tp, feature dim replicated (the
            # SNIPPETS [3] embeddings() layout)
            return P((self.fsdp_axis, self.tp_axis),
                     *([None] * (ndim - 1)))
        if ndim == 2:
            return P(self.fsdp_axis, self.tp_axis)
        if ndim >= 3:
            return P(*([None] * (ndim - 1)), self.tp_axis)
        return P()

    @staticmethod
    def fit(mesh: Mesh, spec: P, shape: Sequence[int]) -> P:
        """Trim ``spec`` to what ``mesh`` and ``shape`` support."""
        entries = list(spec)[:len(shape)]
        entries += [None] * (len(shape) - len(entries))
        out = []
        for dim, entry in zip(shape, entries):
            axes = (entry,) if isinstance(entry, str) else tuple(entry or ())
            axes = tuple(a for a in axes if a in mesh.shape)
            total = 1
            for a in axes:
                total *= mesh.shape[a]
            if not axes or total <= 1 or dim % total:
                out.append(None)
            elif len(axes) == 1:
                out.append(axes[0])
            else:
                out.append(axes)
        while out and out[-1] is None:      # canonical short form
            out.pop()
        return P(*out)

    def resolve(self, mesh: Mesh, path: str, shape: Sequence[int],
                annotation: Optional[Sequence] = None) -> NamedSharding:
        spec = self.spec_for(path, shape, annotation)
        return NamedSharding(mesh, self.fit(mesh, spec, shape))

    def batch_spec(self, ndim: int) -> P:
        """Activations/feeds: leading (batch) dim over ``data``."""
        if ndim < 1:
            return P()
        return P(self.data_axis, *([None] * (ndim - 1)))

    # -- ShardingRules-compatible pytree interface -------------------------
    def apply(self, mesh: Mesh, params):
        """device_put every leaf per its resolved sharding."""
        flat = _flatten_with_paths(params)
        out = {p: jax.device_put(l, self.resolve(mesh, p, np.shape(l)))
               for p, l in flat}
        return _unflatten_paths(out)

    def shardings(self, mesh: Mesh, params):
        """A pytree of NamedShardings matching ``params`` (jit in_shardings)."""
        flat = _flatten_with_paths(params)
        out = {p: self.resolve(mesh, p, np.shape(l)) for p, l in flat}
        return _unflatten_paths(out)


def shard_params(params, mesh: Mesh, rules=None):
    """Place a params pytree on the mesh (replicated unless rules say
    otherwise); ``rules`` is a :class:`ShardingRules` or :class:`SpecLayout`."""
    if rules is None:
        return jax.device_put(params, replicate(mesh))
    return rules.apply(mesh, params)


def with_sharding_constraint(x, mesh: Mesh, *axes: Optional[str]):
    """In-jit resharding hint (the layer-boundary layout conversion point — the
    analog of MKLDNN's convertWeightsFromPaddle boundary, SURVEY §8.3)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(*axes)))


# -- path-dict helpers: the canonical codec shared with trainer/checkpoint -----
from ..core.pytree import flatten_path_tree as _flatten_with_paths  # noqa: E402
from ..core.pytree import unflatten_path_tree as _unflatten_paths  # noqa: E402
