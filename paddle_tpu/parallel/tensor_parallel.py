"""Tensor (model) parallel layers — the ParallelNeuralNetwork replacement.

The reference's model parallelism puts whole layers on different devices
(--parallel_nn, per-layer ``device``, ParallelNeuralNetwork.h:23-34). TPU-native
model parallelism shards *within* the layer over the ``model`` mesh axis so the
matmul itself runs on all chips (megatron-style), which is what the MXU + ICI
topology wants:

* ColumnParallelLinear: W [in, out] sharded on out — output activations carry the
  ``model`` shard; no communication on forward.
* RowParallelLinear:    W [in, out] sharded on in — partial products all-reduced
  (psum over ICI) to finish the contraction.
* ShardedEmbedding:     vocab-sharded table; each chip looks up its vocab slice and
  the results are summed (the sparse 'which pserver owns this row' hash of
  SparseParameterDistribution.cpp becomes a static shard + masked gather).

These are Modules (nn/module.py) whose __call__ takes the mesh implicitly from the
enclosing pjit: they express layout via with_sharding_constraint, and the
column->row pair composes into an MLP with exactly one psum, matching the classic
2-collective-per-block transformer recipe.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..nn.initializer import Initializer, gen1_default
from ..nn.module import Module


def _constrain(x, spec: Optional[P]):
    """Apply a sharding constraint if running under a mesh context."""
    if spec is None:
        return x
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        # no mesh context (single-device tests) — constraint is advisory only
        return x


class ColumnParallelLinear(Module):
    """y = act(x @ W + b); W sharded [None, 'model'] -> y sharded on features."""

    def __init__(self, in_dim: int, out_dim: int, act=None,
                 init: Optional[Initializer] = None, bias: bool = True,
                 axis: str = "model"):
        super().__init__()
        self.axis = axis
        self.act = act
        self.w = self.param("w", (in_dim, out_dim), init or gen1_default())
        self.has_bias = bias
        if bias:
            self.b = self.param("b", (out_dim,))

    def partition_specs(self):
        specs = {"w": P(None, self.axis)}
        if self.has_bias:
            specs["b"] = P(self.axis)
        return specs

    def __call__(self, params, x, **kw):
        w = _constrain(params["w"], P(None, self.axis))
        y = x @ w
        if self.has_bias:
            y = y + params["b"]
        y = _constrain(y, P(None, self.axis))
        if self.act is not None:
            y = self.act(y)
        return y


class RowParallelLinear(Module):
    """y = x @ W + b; W sharded ['model', None]; XLA inserts the psum."""

    def __init__(self, in_dim: int, out_dim: int, act=None,
                 init: Optional[Initializer] = None, bias: bool = True,
                 axis: str = "model"):
        super().__init__()
        self.axis = axis
        self.act = act
        self.w = self.param("w", (in_dim, out_dim), init or gen1_default())
        self.has_bias = bias
        if bias:
            self.b = self.param("b", (out_dim,))

    def partition_specs(self):
        specs = {"w": P(self.axis, None)}
        if self.has_bias:
            specs["b"] = P()
        return specs

    def __call__(self, params, x, **kw):
        # incoming x is feature-sharded (from a column-parallel predecessor)
        x = _constrain(x, P(None, self.axis))
        w = _constrain(params["w"], P(self.axis, None))
        y = x @ w                      # partial sums; SPMD partitioner psums over ICI
        y = _constrain(y, P())         # replicated output
        if self.has_bias:
            y = y + params["b"]
        if self.act is not None:
            y = self.act(y)
        return y


class ShardedEmbedding(Module):
    """Embedding with the table sharded over 'model' on the vocab dim.

    The capability analog of the sparse-row pserver tables
    (math/SparseRowMatrix.h + getParameterSparse, ParameterServer2.h:510): a table
    too big for one chip's HBM lives sharded; lookups become a masked local gather
    + psum. Falls back to a plain gather when unsharded.
    """

    def __init__(self, vocab_size: int, dim: int,
                 init: Optional[Initializer] = None, axis: str = "model"):
        super().__init__()
        self.vocab_size = vocab_size
        self.dim = dim
        self.axis = axis
        self.table = self.param("table", (vocab_size, dim), init or gen1_default())

    def partition_specs(self):
        return {"table": P(self.axis, None)}

    def __call__(self, params, ids, **kw):
        table = _constrain(params["table"], P(self.axis, None))
        return jnp.take(table, ids, axis=0)


def collect_tp_rules(module: Module, prefix: str = ""):
    """Walk a module tree collecting (path-regex, spec) rules from any layer that
    defines partition_specs() — feed to ShardingRules for placement."""
    rules = []
    module._assign_paths(prefix)

    def walk(m: Module, path: str):
        if hasattr(m, "partition_specs"):
            for name, spec in m.partition_specs().items():
                pat = f"{path}/{name}" if path else name
                rules.append((pat + "$", spec))
        for cname, child in m.sublayers().items():
            walk(child, f"{path}/{cname}" if path else cname)

    walk(module, prefix)
    return rules
