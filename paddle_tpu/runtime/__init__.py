"""Native host runtime bindings (ctypes over native/libpaddle_tpu_host.so).

The C++ components the TPU build re-provides natively (SURVEY.md §2 'every
C++/CUDA/Go row'):
* :mod:`master`   — task-queue data master (go/master/service.go semantics)
* :mod:`recordio` — CRC-checked chunked record files (recordio / DataFormat)
* :mod:`arena`    — host buddy allocator (paddle/memory BuddyAllocator)

The library auto-builds from source on first import when a toolchain is
available (make -C native), mirroring how the reference builds vendored
externals at configure time.
"""

from .lib import load_library, native_available
from .master import TaskMaster
from .recordio import RecordReader, RecordWriter
from .arena import HostArena
from .optimizer import HostOptimizer
from .lease import FileLease, LeaseKeeper
from .coord import CoordServer, NetworkFencedStore, NetworkLease
from .master_service import StaleMemberError
from .membership import (HeartbeatKeeper, MembershipClient,
                         MembershipService, autoscale_recommendation)
from .host_embedding import (HostEmbedBatch, HostEmbeddingTable,
                             HostEmbedPrefetcher)

__all__ = ["load_library", "native_available", "TaskMaster",
           "FileLease", "LeaseKeeper",
           "CoordServer", "NetworkLease", "NetworkFencedStore",
           "MembershipService", "MembershipClient", "HeartbeatKeeper",
           "StaleMemberError", "autoscale_recommendation",
           "HostEmbeddingTable", "HostEmbedBatch", "HostEmbedPrefetcher",
           "RecordReader", "RecordWriter", "HostArena", "HostOptimizer"]
