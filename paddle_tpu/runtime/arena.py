"""Host buddy-allocator arena (native/buddy_allocator.cc)."""

from __future__ import annotations

import ctypes
from typing import Tuple

from .lib import load_library

OOM = (1 << 64) - 1


class HostArena:
    """Power-of-two buddy allocator over a host staging arena; returns offsets
    into ``self.buffer`` (a bytearray the feeder writes batches into)."""

    def __init__(self, total: int = 1 << 24, min_block: int = 256):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native host runtime unavailable")
        self._lib = lib
        self._h = lib.pta_create(total, min_block)
        if not self._h:
            raise ValueError("total/min_block must be powers of two")
        self.buffer = bytearray(total)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pta_destroy(self._h)
            self._h = None

    def alloc(self, size: int) -> int:
        off = self._lib.pta_alloc(self._h, size)
        if off == OOM:
            raise MemoryError(f"arena OOM for {size} bytes")
        return int(off)

    def free(self, offset: int):
        if self._lib.pta_free(self._h, offset) != 0:
            raise ValueError(f"offset {offset} was not allocated")

    def stats(self) -> Tuple[int, int, int]:
        vals = [ctypes.c_uint64() for _ in range(3)]
        self._lib.pta_stats(self._h, *[ctypes.byref(v) for v in vals])
        return tuple(int(v.value) for v in vals)  # total, in_use, largest_free
