"""Python side of the C inference API (native/capi_inference.cc).

The C ABI embeds CPython and drives this class; it loads the exported
inference bundle (fluid/io.py export_inference_model — program JSON +
params tar, the merged-model artifact of trainer/MergeModel.cpp:29 /
capi/gradient_machine.h:36) and runs the real XLA-backed Executor.
Forward-only; the executor's shape-keyed compile cache makes repeated
fixed-shape calls cheap.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

# The C ABI's embedded interpreter must honor an explicit JAX_PLATFORMS
# request (e.g. a test pinning the example to CPU while another process
# holds the accelerator). Some images install a sitecustomize that forces
# its own platform list, silently overriding the env var — re-apply it
# here, before the Executor first touches a backend. No-op when unset or
# when a backend is already live (then the process owner chose already).
if os.environ.get("JAX_PLATFORMS"):
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # backend already initialized: keep its choice
        pass

_DTYPES = {0: np.float32, 1: np.int32}


class InferenceHost:
    def __init__(self, model_dir: str):
        from ..fluid.executor import Executor
        from ..fluid.io import load_inference_model

        self.exe = Executor()
        self.program, self.feed_names, self.fetch_names = \
            load_inference_model(model_dir, self.exe)

    def run(self, arrays: List[np.ndarray], fetch_index: int = 0) -> np.ndarray:
        feed = dict(zip(self.feed_names, arrays))
        outs = self.exe.run(self.program, feed=feed,
                            fetch_list=[self.fetch_names[fetch_index]])
        return np.asarray(outs[0])

    def run_raw(self, raw: List[Tuple[bytes, Tuple[int, ...], int]],
                fetch_index: int = 0) -> Tuple[bytes, Tuple[int, ...]]:
        """C-ABI entry: [(buffer, dims, dtype_code)] -> (f32 buffer, dims)."""
        arrays = [np.frombuffer(buf, _DTYPES[code]).reshape(dims)
                  for buf, dims, code in raw]
        out = self.run(arrays, fetch_index).astype(np.float32)
        return out.tobytes(), tuple(int(d) for d in out.shape)
