"""Network coordination service — the etcd analog, served over TCP.

The reference coordinates masters/pservers through etcd: TTL leases + locks
for election (go/master/etcd_client.go concurrency.NewSession under a TTL
lease), etcd revisions for fencing, and the master snapshots its task queues
*into* etcd so a successor on a different host recovers state
(go/master/service.go snapshot-to-etcd). :class:`~paddle_tpu.runtime.lease.
FileLease` provides those semantics on shared storage; this module provides
them over the network, so multi-host failover needs no NFS:

* :class:`CoordServer` — one small TCP service (same length-prefixed JSON
  framing as the master RPC) holding, under one lock:
  - TTL leases per name, expiry judged by the SERVER clock (contender clock
    skew cannot extend a lease), fencing tokens minted from a per-name
    monotonic epoch counter;
  - fence-claim records per resource (etcd revision compare-and-claim);
  - a fenced small-blob store — the snapshot's network home. ``blob_put``
    is check-token-and-publish under the server lock, the same atomicity
    FencedFile gets from its flock.
* :class:`NetworkLease` — FileLease's exact interface (try_acquire / renew /
  release / holder / current_token / held_by_me / wait_acquire / ``token``)
  against a CoordServer, so :class:`~paddle_tpu.runtime.lease.LeaseKeeper`
  and :class:`~paddle_tpu.runtime.master_service.MasterServer` work
  unchanged.
* :class:`NetworkFencedStore` — FencedFile's interface (claim / write /
  _recorded) plus ``fetch_to`` for successor restore, backed by the blob
  store.

Deployment: run ``CoordServer`` where etcd would run (any host the workers
can reach, typically alongside the first master candidate); masters elect
through it and push fenced snapshots into it; a standby on a *different*
host restores from it. Single-host jobs keep FileLease and never need this.
"""

from __future__ import annotations

import base64
import socket
import socketserver
import threading
import time
from typing import Dict, Optional, Tuple

from .. import faults, obs
from .master_service import _recv_msg, _RpcClient, _send_msg


class CoordServer:
    """In-memory lease/fence/blob coordination service (etcd stand-in)."""

    #: requests_total `type` label values — arbitrary op strings off the
    #: wire clamp to "unknown" so a peer cannot mint unbounded series
    _KNOWN_OPS = frozenset({
        "lease_acquire", "lease_renew", "lease_release", "lease_holder",
        "fence_claim", "blob_put", "blob_get", "fence_recorded", "ping"})

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        # name -> (owner, expires_at_monotonic, token)
        self._leases: Dict[str, Tuple[str, float, int]] = {}
        self._epochs: Dict[str, int] = {}        # name -> token high-water
        self._fences: Dict[str, int] = {}        # resource -> claimed token
        self._blobs: Dict[str, bytes] = {}       # key -> payload
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    req = _recv_msg(self.request)
                    if req is None:
                        return
                    _send_msg(self.request, outer._dispatch(req))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address: Tuple[str, int] = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    # -- ops (all under one lock: every read-check-write is atomic) ---------
    def _dispatch(self, req):
        op = str(req.get("op"))
        label = op if op in self._KNOWN_OPS else "unknown"
        obs.count("coord.requests_total", type=label)
        # server-side span parented on the client's rpc.call wire context —
        # the same cross-process edge MasterServer._dispatch records
        try:
            with obs.server_span("coord.dispatch", req.get("trace"), op=op):
                resp = self._dispatch_op(req)
        except Exception:
            # malformed requests (missing field, bad type) must land in
            # the error counter even though the exception severs the conn
            obs.count("coord.request_errors_total", type=label)
            raise
        # key on the error FIELD (the master-dispatch rule): an ok=true
        # answer with renewed/acquired/claimed=false is a normal outcome
        if resp.get("error") is not None:
            obs.count("coord.request_errors_total", type=label)
        return resp

    def _dispatch_op(self, req):
        op = req.get("op")
        with self._lock:
            if op == "lease_acquire":
                return self._acquire(req["name"], req["owner"],
                                     float(req["ttl"]))
            if op == "lease_renew":
                return self._renew(req["name"], req["owner"],
                                   float(req["ttl"]))
            if op == "lease_release":
                h = self._leases.get(req["name"])
                if h is not None and h[0] == req["owner"]:
                    del self._leases[req["name"]]
                return {"ok": True}
            if op == "lease_holder":
                h = self._leases.get(req["name"])
                now = time.monotonic()
                if h is None:
                    return {"ok": True, "holder": None, "token": None}
                # report remaining TTL, not the server-monotonic stamp: the
                # client turns it back into its own clock's terms
                return {"ok": True,
                        "holder": [h[0], max(0.0, h[1] - now)],
                        "token": h[2], "expired": h[1] <= now}
            if op == "fence_claim":
                return self._fence_claim(req["resource"], int(req["token"]))
            if op == "blob_put":
                r = self._fence_claim(req["key"], int(req["token"]))
                if r["claimed"]:
                    self._blobs[req["key"]] = base64.b64decode(req["data"])
                return r
            if op == "blob_get":
                data = self._blobs.get(req["key"])
                return {"ok": True,
                        "data": None if data is None
                        else base64.b64encode(data).decode()}
            if op == "fence_recorded":
                return {"ok": True,
                        "token": self._fences.get(req["resource"], 0)}
            if op == "ping":
                return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}

    def _acquire(self, name: str, owner: str, ttl: float):
        now = time.monotonic()
        h = self._leases.get(name)
        if h is not None and h[0] != owner and h[1] > now:
            return {"ok": True, "acquired": False,
                    "holder": [h[0], h[1] - now], "token": h[2]}
        if h is not None and h[0] == owner and h[1] > now:
            token = h[2]                     # same-owner refresh keeps token
        else:
            # free or expired: mint a strictly larger token (etcd revision)
            cur = max(self._epochs.get(name, 0), h[2] if h else 0)
            token = cur + 1
            self._epochs[name] = token
        self._leases[name] = (owner, now + ttl, token)
        return {"ok": True, "acquired": True, "token": token}

    def _renew(self, name: str, owner: str, ttl: float):
        h = self._leases.get(name)
        if h is None or h[0] != owner:
            return {"ok": True, "renewed": False}
        self._leases[name] = (owner, time.monotonic() + ttl, h[2])
        return {"ok": True, "renewed": True, "token": h[2]}

    def _fence_claim(self, resource: str, token: int):
        recorded = self._fences.get(resource, 0)
        if token < recorded:
            return {"ok": True, "claimed": False, "recorded": recorded}
        self._fences[resource] = max(recorded, token)
        return {"ok": True, "claimed": True, "recorded": token}


class _CoordClient(_RpcClient):
    """Reconnecting client for CoordServer calls: the shared
    :class:`_RpcClient` plumbing (RetryPolicy backoff, per-call socket
    deadline, drop-socket-on-error) against one endpoint, exposing the raw
    request interface."""

    _rpc_name = "coord rpc"

    def call(self, req):
        return self._call(req)


class NetworkLease:
    """TTL lease on a CoordServer with FileLease's interface.

    Expiry is judged by the server's clock, so the ``now=`` overrides the
    FileLease tests use for time travel are accepted but ignored — a
    contender cannot argue a foreign lease expired when the server says
    otherwise (the whole point of central coordination).
    """

    def __init__(self, host: str, port: int, name: str = "master",
                 owner: Optional[str] = None, ttl: float = 10.0):
        import os
        import uuid
        self.path = f"coord://{host}:{port}/{name}"   # diagnostic parity
        self.name = name
        self.owner = owner or (f"{socket.gethostname()}-{os.getpid()}-"
                               f"{uuid.uuid4().hex[:8]}")
        self.ttl = ttl
        self.token: Optional[int] = None
        self._client = _CoordClient(host, port)

    # -- inspection ---------------------------------------------------------
    def holder(self) -> Optional[Tuple[str, float]]:
        r = self._client.call({"op": "lease_holder", "name": self.name})
        if r.get("holder") is None or r.get("expired"):
            return None
        owner, remaining = r["holder"]
        return owner, time.time() + remaining

    def current_token(self) -> Optional[int]:
        r = self._client.call({"op": "lease_holder", "name": self.name})
        return r.get("token")

    def held_by_me(self, now: Optional[float] = None) -> bool:
        h = self.holder()
        return h is not None and h[0] == self.owner

    # -- acquisition --------------------------------------------------------
    def try_acquire(self, now: Optional[float] = None) -> bool:
        r = self._client.call({"op": "lease_acquire", "name": self.name,
                               "owner": self.owner, "ttl": self.ttl})
        if r.get("acquired"):
            self.token = r["token"]
            return True
        return False

    def renew(self, now: Optional[float] = None) -> bool:
        obs.count("lease.renews_total")
        faults.fire("lease.renew")
        r = self._client.call({"op": "lease_renew", "name": self.name,
                               "owner": self.owner, "ttl": self.ttl})
        if r.get("renewed"):
            if self.token is None:
                self.token = r.get("token")   # recover after restart
            return True
        obs.count("lease.renew_failures_total")
        return False

    def release(self):
        self._client.call({"op": "lease_release", "name": self.name,
                           "owner": self.owner})
        self.token = None

    def wait_acquire(self, poll: float = 0.5,
                     timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and time.time() > deadline:
                return False
            time.sleep(poll)

    def close(self):
        self._client.close()


class NetworkFencedStore:
    """Fenced snapshot home on a CoordServer (FencedFile's interface).

    ``write`` runs the caller's path-writer locally, then pushes the bytes
    with its fencing token; the server's atomic check-and-publish refuses a
    deposed generation. A successor — on any host — ``fetch_to``\\ s the
    blob before restore. No filesystem is shared.
    """

    def __init__(self, host: str, port: int, key: str = "master.snap"):
        self.key = key
        self._client = _CoordClient(host, port)

    def _recorded(self) -> int:
        return int(self._client.call({"op": "fence_recorded",
                                      "resource": self.key}).get("token", 0))

    def claim(self, token: Optional[int]) -> bool:
        if token is None:
            return True
        return bool(self._client.call({"op": "fence_claim",
                                       "resource": self.key,
                                       "token": token}).get("claimed"))

    def write(self, token: Optional[int], writer) -> bool:
        import os
        import tempfile
        fd, tmp = tempfile.mkstemp(prefix="coordsnap.")
        os.close(fd)
        try:
            writer(tmp)
            with open(tmp, "rb") as f:
                data = f.read()
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        r = self._client.call({"op": "blob_put", "key": self.key,
                               "token": int(token) if token is not None else 0,
                               "data": base64.b64encode(data).decode()})
        return bool(r.get("claimed"))

    def fetch_to(self, path: str) -> bool:
        """Download the snapshot blob to ``path``; False if none stored."""
        r = self._client.call({"op": "blob_get", "key": self.key})
        if r.get("data") is None:
            return False
        with open(path, "wb") as f:
            f.write(base64.b64decode(r["data"]))
        return True

    def close(self):
        self._client.close()


def main(argv=None):
    """``python -m paddle_tpu.runtime.coord [--host H] [--port P]`` — run the
    coordination service standalone (where the reference runs etcd)."""
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    srv = CoordServer(args.host, args.port)
    print(f"LISTENING {srv.address[0]} {srv.address[1]}", flush=True)
    srv.start()
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
