"""Host-offloaded embedding tables: >HBM tables streamed by touched rows.

The reference's sparse-remote path ships only the rows a batch touches
between trainer and pserver so the table never has to fit in device memory
(trainer/RemoteParameterUpdater.h:265 SparseRemoteParameterUpdater,
pserver/ParameterServer2.h:510 getParameterSparse,
pserver/SparseParameterDistribution.cpp splits the vocab across pservers).
TPU-native mapping: the master table lives in HOST RAM inside a
:class:`~paddle_tpu.runtime.optimizer.HostOptimizer` (native f32 storage
with sparse row updates, native/optimizer.cc), and each step:

1. **prefetch** — ``np.unique(ids)`` -> ``pto_get_rows`` gathers the C
   touched rows -> one small [capacity, D] device array (padded to a
   static capacity so the jitted step never re-traces);
2. **device step** — the lookup is ``rows[inverse]``, a dense gather the
   model differentiates; the grad w.r.t. ``rows`` IS the merged
   SelectedRows gradient (duplicate ids already summed by autodiff);
3. **apply** — ``pto_update_rows`` updates only the touched rows on host.

:class:`HostEmbedPrefetcher` overlaps step 1 for batch i+1 with the device
compute of batch i WITHOUT the pserver path's staleness: the speculative
gather happens concurrently, and after batch i's update lands, the (usually
small) intersection of batch i's touched rows with batch i+1's prefetch is
re-gathered and patched — every step reads exactly the post-update table,
so the offloaded path is bit-equivalent to an on-HBM table (verified in
tests/test_host_embedding.py).

Multi-host: split the vocab range across hosts (each host owns
``vocab/num_hosts`` rows in its own HostOptimizer) and route each unique id
to its owner — the SparseParameterDistribution layout; the per-host
machinery below is unchanged.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Iterator, Optional, Tuple

import numpy as np

from .optimizer import HostOptimizer


@dataclass
class HostEmbedBatch:
    """One batch's streamed slice of the table."""

    rows: "jax.Array"        # [capacity, D] on device (f32 or bf16)
    inverse: "jax.Array"     # ids.shape, int32 — indices into rows
    unique: np.ndarray       # [capacity] host ids (padded with 0)
    count: int               # number of REAL unique ids (<= capacity)


class HostEmbeddingTable:
    """A vocab x dim table resident in host memory, streamed by touched rows.

    ``capacity`` is the static per-batch unique-row budget (pad target); it
    bounds the device working set at ``capacity * dim`` regardless of vocab
    size. ``compute_dtype`` controls the streamed copy (bf16 halves H2D
    bytes; the host master and updates stay f32).
    """

    def __init__(self, vocab_size: int, dim: int, *, optimizer: str = "sgd",
                 lr: float = 0.01, capacity: int = 4096,
                 compute_dtype=None, init: Optional[np.ndarray] = None,
                 seed: int = 0, **opt_kw):
        self.vocab_size, self.dim, self.capacity = vocab_size, dim, capacity
        if init is None:
            rs = np.random.RandomState(seed)
            init = (rs.standard_normal((vocab_size, dim)) * 0.01).astype(
                np.float32)
        elif isinstance(init, str) and init == "zeros":
            # native zero-fill: no numpy source buffer, no 20 GB memcpy
            init = (vocab_size, dim)
        self.opt = HostOptimizer(optimizer, init, lr=lr, **opt_kw)
        # np.dtype resolves jnp.bfloat16 via ml_dtypes; f32 = exact master
        self.compute_dtype = np.dtype(compute_dtype if compute_dtype
                                      is not None else np.float32)

    # -- step protocol ------------------------------------------------------
    def prefetch(self, ids: np.ndarray) -> HostEmbedBatch:
        """Gather the batch's touched rows to the device (padded)."""
        import jax

        unique, inverse = np.unique(np.asarray(ids), return_inverse=True)
        if unique.size > self.capacity:
            raise ValueError(
                f"batch touches {unique.size} unique rows > capacity "
                f"{self.capacity}; raise capacity (device working set is "
                f"capacity*dim)")
        padded = np.zeros(self.capacity, np.int32)
        padded[:unique.size] = unique
        rows = self.opt.get_rows(padded, self.dim)
        return HostEmbedBatch(
            rows=jax.device_put(rows.astype(self.compute_dtype)),
            inverse=jax.device_put(
                inverse.reshape(np.shape(ids)).astype(np.int32)),
            unique=padded, count=int(unique.size))

    @staticmethod
    def lookup(rows, inverse):
        """Device-side lookup — differentiable; grad wrt ``rows`` is the
        merged SelectedRows gradient."""
        import jax.numpy as jnp
        return jnp.take(rows, inverse, axis=0)

    def apply_grad(self, batch: HostEmbedBatch, grad_rows) -> None:
        """Apply the [capacity, D] device grad to the host master rows.
        Padded tail rows receive exactly-zero grads from autodiff (no
        inverse index maps to them) but are sliced off anyway so adagrad
        accumulators never see even a zero step for untouched rows."""
        import jax
        g = np.asarray(jax.device_get(grad_rows), np.float32)
        self.opt.update_rows(batch.unique[:batch.count], g[:batch.count])

    # -- inspection / checkpoint -------------------------------------------
    def rows_host(self, ids: np.ndarray) -> np.ndarray:
        return self.opt.get_rows(np.asarray(ids, np.int32), self.dim)

    def serialize(self) -> bytes:
        return self.opt.serialize()

    def deserialize(self, blob: bytes) -> None:
        self.opt.deserialize(blob)


class HostEmbedPrefetcher:
    """Exactness-preserving overlap of host gather/H2D with device compute.

    Usage::

        pf = HostEmbedPrefetcher(table, ids_iterator)
        for _ in range(steps):
            batch = pf.next()              # rows already on device
            grads, aux = device_step(batch.rows, batch.inverse, ...)
            pf.commit(batch, grads)        # update + patch next prefetch

    ``next()`` kicks off the gather for the FOLLOWING batch on a worker
    thread, so it runs while the devices compute. ``commit`` applies the
    sparse update, then re-gathers and patches the rows of the pending
    prefetch that this update touched (intersection fix-up) — the pending
    batch becomes exactly post-update, with only the intersection paying a
    second (tiny) H2D.
    """

    def __init__(self, table: HostEmbeddingTable, ids_iter: Iterator):
        self.table = table
        self._ids_iter = iter(ids_iter)
        self._pending: Optional[Tuple[HostEmbedBatch, threading.Event]] = None
        self._kick()

    def _kick(self):
        try:
            ids = next(self._ids_iter)
        except StopIteration:
            self._pending = None
            return
        done = threading.Event()
        holder = [None, None]                     # batch, exception

        def work():
            try:
                holder[0] = self.table.prefetch(ids)
            except BaseException as e:            # surfaced in next()
                holder[1] = e
            done.set()

        threading.Thread(target=work, daemon=True).start()
        self._pending = (holder, done)

    def next(self) -> Optional[HostEmbedBatch]:
        if self._pending is None:
            return None
        holder, done = self._pending
        done.wait()
        if holder[1] is not None:
            raise holder[1]
        batch = holder[0]
        self._kick()                              # overlap the NEXT gather
        return batch

    def commit(self, batch: HostEmbedBatch, grad_rows) -> None:
        pend = self._pending
        if pend is not None:
            # the speculative gather must FINISH before the update mutates
            # the table: pto_update_rows and pto_get_rows both release the
            # GIL, so overlapping them on shared rows would be a C-level
            # data race. The gather's overlap window was the device compute
            # that already happened, so this wait is ~free.
            pend[1].wait()
        self.table.apply_grad(batch, grad_rows)
        if pend is None:
            return
        holder, done = pend
        if holder[1] is not None:                 # gather failed:
            return                                # next() will raise it
        nxt: HostEmbedBatch = holder[0]
        # fix-up: rows the just-applied update touched that the pending
        # prefetch had already (speculatively) read
        touched = np.intersect1d(batch.unique[:batch.count],
                                 nxt.unique[:nxt.count])
        if touched.size:
            import jax
            pos = np.searchsorted(nxt.unique[:nxt.count], touched)
            fresh = self.table.opt.get_rows(touched, self.table.dim)
            dt = nxt.rows.dtype
            nxt.rows = nxt.rows.at[jax.device_put(pos.astype(np.int32))].set(
                jax.device_put(fresh.astype(dt)))
