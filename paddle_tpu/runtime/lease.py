"""Lease/lock primitives for master election — the etcd analog.

The reference elects and registers its masters/pservers through etcd leases
and locks (go/master/etcd_client.go: concurrency.NewSession + lock under
a TTL lease; go/pserver/etcd_client.go slot registration). A TPU pod has no
etcd, but every host mounts shared storage; :class:`FileLease` provides the
same primitive there: a lock file holding ``owner expires_at token``,
acquirable when absent/expired and renewed by its holder. A standby master
blocks on the lease and takes over (restoring the CRC-checked snapshot) when
the active master dies — removing the single-point-of-failure the round-1
review flagged.

Contention protocol: every lease mutation (acquire / renew / release) is
serialized under an ``flock`` on a sidecar ``<path>.lock`` file, so
read-check-write sequences are atomic among contenders; readers see
consistent contents because the lease file itself is replaced via
write-temp-then-rename. flock is advisory but all participants go through
this class; it holds across NFSv4 (and NFSv3 with lockd), the shared-storage
deployments a TPU pod actually uses.

Fencing: every acquisition is stamped with a monotonically increasing
*fencing token* (persisted in a sidecar ``<path>.epoch`` counter, bumped
under the same kind of flock so it never goes backwards, even across
release/re-acquire cycles) — the role etcd revisions play in
go/master/etcd_client.go. Resources that must never accept writes from a
deposed master (the snapshot file) are guarded by :class:`FencedFile`: the
check-and-publish runs under an flock, so a writer that stalls mid-operation
(GC pause, NFS hiccup) either completes before the new generation's claim or
finds itself refused — there is no window where a stale write lands on top
of a newer generation's.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import socket
import threading
import time
import uuid
from typing import Optional, Tuple

from .. import faults, obs


@contextlib.contextmanager
def _flocked(lock_path: str):
    """Exclusive advisory lock scope on ``lock_path`` (created if absent)."""
    fd = os.open(lock_path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        fcntl.flock(fd, fcntl.LOCK_UN)
        os.close(fd)


class FileLease:
    """A TTL lease on shared storage (etcd lease/lock stand-in)."""

    def __init__(self, path: str, owner: Optional[str] = None,
                 ttl: float = 10.0):
        self.path = path
        self.owner = owner or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.ttl = ttl
        self._lock_path = f"{path}.lock"
        #: fencing token of OUR current acquisition (None until we hold it)
        self.token: Optional[int] = None

    # -- inspection ---------------------------------------------------------
    def holder(self) -> Optional[Tuple[str, float]]:
        """(owner, expires_at) of the current lease file, None if absent/bad."""
        h = self._read(self.path)
        return None if h is None else (h[0], h[1])

    def current_token(self) -> Optional[int]:
        """Fencing token of the current lease file (whoever holds it)."""
        h = self._read(self.path)
        return None if h is None else h[2]

    def held_by_me(self, now: Optional[float] = None) -> bool:
        h = self.holder()
        now = time.time() if now is None else now
        return h is not None and h[0] == self.owner and h[1] > now

    # -- acquisition --------------------------------------------------------
    def try_acquire(self, now: Optional[float] = None) -> bool:
        """Take the lease if it is free, expired, or already ours.

        The whole read-check-write runs under the contender flock, so
        exactly one contender wins an expired/free lease and nobody can
        clobber a live holder's renewal.
        """
        now = time.time() if now is None else now
        with _flocked(self._lock_path):
            h = self._read(self.path)
            if h is not None and h[0] != self.owner and h[1] > now:
                return False                 # live foreign lease
            if h is not None and h[0] == self.owner:
                if self.token is None:
                    self.token = h[2]        # recover after restart
            else:
                self.token = self._next_token()
            self._write(now)
            # confirm-after-write: on mounts where advisory flock silently
            # no-ops (NFSv3 without lockd, some FUSE/SMB), a concurrent
            # writer's os.replace can land after ours — only believe we
            # hold the lease if the file still names us
            return self.held_by_me(now)

    def renew(self, now: Optional[float] = None) -> bool:
        """Extend our lease; False (lease LOST) if someone else took it."""
        obs.count("lease.renews_total")
        faults.fire("lease.renew")   # chaos: stall/FS-outage injection point
        now = time.time() if now is None else now
        with _flocked(self._lock_path):
            h = self._read(self.path)
            if h is None or h[0] != self.owner:
                obs.count("lease.renew_failures_total")
                return False
            if self.token is None:
                self.token = h[2]            # recover after restart
            self._write(now)
            return True

    def release(self):
        with _flocked(self._lock_path):
            h = self._read(self.path)
            if h is not None and h[0] == self.owner:
                try:
                    os.remove(self.path)
                except OSError:
                    pass
        self.token = None

    def wait_acquire(self, poll: float = 0.5,
                     timeout: Optional[float] = None) -> bool:
        """Block until the lease is ours (standby-master loop)."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and time.time() > deadline:
                return False
            time.sleep(poll)

    def _read(self, path: str) -> Optional[Tuple[str, float, int]]:
        try:
            with open(path) as f:
                fields = f.read().split()
                owner, expires = fields[0], float(fields[1])
                token = int(fields[2]) if len(fields) > 2 else 0
                return owner, expires, token
        except (OSError, ValueError, IndexError):
            return None

    def _next_token(self) -> int:
        """Monotonic across every acquisition, including after release():
        the high-water mark lives in a sidecar counter file. The
        read-bump-write is serialized under its own flock so a contender
        that stalls mid-bump can never roll the counter backwards and mint
        a duplicate token."""
        epoch_path = f"{self.path}.epoch"
        fd = os.open(epoch_path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
            raw = os.read(fd, 64)
            try:
                cur = int(raw) if raw else 0
            except ValueError:
                cur = 0
            h = self._read(self.path)
            if h is not None:
                cur = max(cur, h[2])
            nxt = cur + 1
            os.lseek(fd, 0, os.SEEK_SET)
            os.ftruncate(fd, 0)
            os.write(fd, str(nxt).encode())
            os.fsync(fd)
            return nxt
        finally:
            fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _write(self, now: float):
        # caller holds the contender flock; rename keeps readers consistent
        tmp = f"{self.path}.{self.owner}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{self.owner} {now + self.ttl} {self.token or 0}")
        os.replace(tmp, self.path)


class FencedFile:
    """Token-checked write guard for a resource shared across master
    generations (the snapshot file). A writer presents its fencing token;
    once any higher token has claimed the resource, lower tokens are
    refused — etcd-revision fencing (go/master/etcd_client.go) on a plain
    filesystem. Check-and-publish is atomic under an flock: a stale writer
    cannot land its file after a newer generation's claim."""

    def __init__(self, path: str):
        self.path = path
        self.fence_path = f"{path}.fence"
        self._lock_path = f"{path}.fencelock"

    def _recorded(self) -> int:
        try:
            with open(self.fence_path) as f:
                return int(f.read())
        except (OSError, ValueError):
            return 0

    def _claim_locked(self, token: int) -> bool:
        recorded = self._recorded()
        if token < recorded:
            return False
        if token > recorded:
            tmp = f"{self.fence_path}.{token}.tmp"
            with open(tmp, "w") as f:
                f.write(str(token))
            os.replace(tmp, self.fence_path)
        return True

    def claim(self, token: Optional[int]) -> bool:
        """Record `token` as the current generation; False if a higher
        token already claimed the resource (caller is deposed)."""
        if token is None:
            return True                      # fencing not in use
        with _flocked(self._lock_path):
            return self._claim_locked(token)

    def write(self, token: Optional[int], writer) -> bool:
        """Run ``writer(tmp)`` then publish the result iff `token` is still
        current. The (possibly slow) write happens outside the lock; the
        check + rename are one atomic critical section, so a deposed
        writer's file can never replace a newer generation's."""
        tmp = f"{self.path}.w{token if token is not None else 0}.tmp"
        writer(tmp)
        if token is None:
            os.replace(tmp, self.path)
            return True
        with _flocked(self._lock_path):
            if not self._claim_locked(token):
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                return False
            os.replace(tmp, self.path)
            return True


class LeaseKeeper:
    """Background renewal thread; fires ``on_lost`` if the lease slips away
    (the etcd session-expired event)."""

    def __init__(self, lease: FileLease, interval: Optional[float] = None,
                 on_lost=None):
        self.lease = lease
        self.interval = interval if interval is not None else lease.ttl / 3
        self.on_lost = on_lost
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        last_ok = time.time()
        while not self._stop.wait(self.interval):
            # stamp BEFORE the RPC: the server's expiry clock starts when it
            # handles the request, so measuring our grace window from the
            # request's issue time keeps the client strictly conservative
            # relative to server-side expiry (never "held" past the server)
            attempt_at = time.time()
            try:
                renewed = self.lease.renew()
            except (OSError, ConnectionError):
                # transient store outage (NFS blip, coord-server restart):
                # keep trying while our TTL could still be running; once the
                # lease must have expired server-side, it is LOST
                renewed = time.time() - last_ok < self.lease.ttl
            else:
                if renewed:
                    last_ok = attempt_at
            if not renewed:
                if self.on_lost is not None:
                    self.on_lost()
                return

    def stop(self, release: bool = True):
        self._stop.set()
        # on_lost callbacks run ON the keeper thread and may call stop();
        # joining ourselves would raise RuntimeError
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        if release:
            self.lease.release()
