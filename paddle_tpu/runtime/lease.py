"""Lease/lock primitives for master election — the etcd analog.

The reference elects and registers its masters/pservers through etcd leases
and locks (go/master/etcd_client.go: concurrency.NewSession + lock under
a TTL lease; go/pserver/etcd_client.go slot registration). A TPU pod has no
etcd, but every host mounts shared storage; :class:`FileLease` provides the
same primitive there: a lock file holding ``owner expires_at``, acquirable
when absent/expired, renewed by its holder, atomically replaced via
write-temp-then-rename. A standby master blocks on the lease and takes over
(restoring the CRC-checked snapshot) when the active master dies — removing
the single-point-of-failure the round-1 review flagged.

Contention protocol: writers re-read after renaming and only believe they
hold the lease if the file names them (last-writer-wins + confirm), which is
safe on POSIX rename atomicity for the single-shared-filesystem deployment.
For cross-datacenter placement, point the path at a fencing-capable store.
"""

from __future__ import annotations

import os
import socket
import threading
import time
import uuid
from typing import Optional, Tuple


class FileLease:
    """A TTL lease on shared storage (etcd lease/lock stand-in)."""

    def __init__(self, path: str, owner: Optional[str] = None,
                 ttl: float = 10.0):
        self.path = path
        self.owner = owner or f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.ttl = ttl

    # -- inspection ---------------------------------------------------------
    def holder(self) -> Optional[Tuple[str, float]]:
        """(owner, expires_at) of the current lease file, None if absent/bad."""
        return self._read(self.path)

    def held_by_me(self, now: Optional[float] = None) -> bool:
        h = self.holder()
        now = time.time() if now is None else now
        return h is not None and h[0] == self.owner and h[1] > now

    # -- acquisition --------------------------------------------------------
    def try_acquire(self, now: Optional[float] = None) -> bool:
        """Take the lease if it is free, expired, or already ours.

        Mutual exclusion among contenders: a FREE lease is taken by O_EXCL
        creation (exactly one creator wins); an EXPIRED lease is first
        *claimed* by renaming it to a contender-unique path (exactly one
        rename succeeds — the loser gets ENOENT), verified expired, then
        replaced via O_EXCL. Residual race vs a live holder's renewal is
        bounded by the renewal cadence (ttl/3 ≪ ttl); true fencing needs a
        coordination service (see module docstring).
        """
        now = time.time() if now is None else now
        h = self.holder()
        if h is not None:
            if h[0] == self.owner:
                self._write(now)             # refresh our own lease
                return self.held_by_me(now)
            if h[1] > now:
                return False                 # live foreign lease
            # expired foreign lease: claim it by rename — only ONE contender
            # can win this rename; everyone else fails with ENOENT
            claim = f"{self.path}.claim.{self.owner}"
            try:
                os.rename(self.path, claim)
            except OSError:
                return False
            claimed = self._read(claim)
            if claimed is not None and claimed[1] > now and \
                    claimed[0] != self.owner:
                # it was renewed between our read and our claim: give it back
                try:
                    os.rename(claim, self.path)
                except OSError:
                    os.remove(claim)
                return False
            os.remove(claim)
        return self._create_excl(now)

    def renew(self, now: Optional[float] = None) -> bool:
        """Extend our lease; False (lease LOST) if someone else took it."""
        now = time.time() if now is None else now
        h = self.holder()
        if h is None or h[0] != self.owner:
            return False
        self._write(now)
        return self.held_by_me(now)

    def release(self):
        if self.held_by_me():
            try:
                os.remove(self.path)
            except OSError:
                pass

    def wait_acquire(self, poll: float = 0.5,
                     timeout: Optional[float] = None) -> bool:
        """Block until the lease is ours (standby-master loop)."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.try_acquire():
                return True
            if deadline is not None and time.time() > deadline:
                return False
            time.sleep(poll)

    def _read(self, path: str) -> Optional[Tuple[str, float]]:
        try:
            with open(path) as f:
                owner, expires = f.read().split()
                return owner, float(expires)
        except (OSError, ValueError):
            return None

    def _create_excl(self, now: float) -> bool:
        try:
            fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
        except FileExistsError:
            return self.held_by_me(now)      # maybe we lost to a peer
        with os.fdopen(fd, "w") as f:
            f.write(f"{self.owner} {now + self.ttl}")
        return True

    def _write(self, now: float):
        tmp = f"{self.path}.{self.owner}.tmp"
        with open(tmp, "w") as f:
            f.write(f"{self.owner} {now + self.ttl}")
        os.replace(tmp, self.path)


class LeaseKeeper:
    """Background renewal thread; fires ``on_lost`` if the lease slips away
    (the etcd session-expired event)."""

    def __init__(self, lease: FileLease, interval: Optional[float] = None,
                 on_lost=None):
        self.lease = lease
        self.interval = interval if interval is not None else lease.ttl / 3
        self.on_lost = on_lost
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval):
            if not self.lease.renew():
                if self.on_lost is not None:
                    self.on_lost()
                return

    def stop(self, release: bool = True):
        self._stop.set()
        # on_lost callbacks run ON the keeper thread and may call stop();
        # joining ourselves would raise RuntimeError
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5)
        if release:
            self.lease.release()
