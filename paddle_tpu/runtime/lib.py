"""Load (building if needed) the native host-runtime library."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

#: signature of the unknown-op fallback (master_server.cc ptms_set_fallback):
#: (request bytes, length, opaque reply handle) -> None; the callback
#: answers via ptms_reply(handle, data, len) before returning. Callers must
#: keep the CFUNCTYPE instance alive while the server runs.
PTMS_FALLBACK_FN = ctypes.CFUNCTYPE(None, ctypes.POINTER(ctypes.c_char),
                                    ctypes.c_int, ctypes.c_void_p)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO = os.path.join(_NATIVE_DIR, "libpaddle_tpu_host.so")
# wheel installs ship the .so inside the package (setup.py copies it here;
# the repo-relative path above covers source checkouts)
_PKG_SO = os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native",
                       "libpaddle_tpu_host.so")


def load_library() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        candidates = [_SO, _PKG_SO]
        if os.path.isdir(_NATIVE_DIR) and _needs_build():
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-j4"],
                               check=True, capture_output=True, timeout=120)
            except Exception:
                # build failed with sources newer than the repo .so: loading
                # that stale binary against new argtypes is the old-ABI
                # hazard — only the packaged copy is eligible now
                candidates = [_PKG_SO]
        lib = None
        for so in candidates:
            try:
                lib = ctypes.CDLL(so)
                break
            except OSError:
                continue
        if lib is None:
            return None
        _configure(lib)
        _lib = lib
        return _lib


def _needs_build() -> bool:
    """Rebuild when any source is newer than the .so — a stale binary with an
    old C ABI would be silently called with the new signature otherwise."""
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    try:
        entries = os.listdir(_NATIVE_DIR)
    except OSError:
        return False
    return any(os.path.getmtime(os.path.join(_NATIVE_DIR, n)) > so_mtime
               for n in entries if n.endswith((".cc", ".h")) or n == "Makefile")


def native_available() -> bool:
    return load_library() is not None


def _configure(lib: ctypes.CDLL):
    c = ctypes
    # task master
    lib.ptm_create.restype = c.c_void_p
    lib.ptm_create.argtypes = [c.c_double, c.c_int]
    lib.ptm_destroy.argtypes = [c.c_void_p]
    lib.ptm_set_dataset.argtypes = [c.c_void_p, c.POINTER(c.c_char_p), c.c_int]
    lib.ptm_get_task.restype = c.c_int
    lib.ptm_get_task.argtypes = [c.c_void_p, c.c_double, c.c_char_p, c.c_int,
                                 c.POINTER(c.c_int)]
    lib.ptm_task_finished.argtypes = [c.c_void_p, c.c_int]
    lib.ptm_new_pass.restype = c.c_int
    lib.ptm_new_pass.argtypes = [c.c_void_p]
    lib.ptm_task_failed.argtypes = [c.c_void_p, c.c_int]
    lib.ptm_tick.restype = c.c_int
    lib.ptm_tick.argtypes = [c.c_void_p, c.c_double]
    lib.ptm_stats.argtypes = [c.c_void_p] + [c.POINTER(c.c_int)] * 5
    lib.ptm_snapshot.restype = c.c_int
    lib.ptm_snapshot.argtypes = [c.c_void_p, c.c_char_p]
    lib.ptm_restore.restype = c.c_int
    lib.ptm_restore.argtypes = [c.c_void_p, c.c_char_p]
    # master RPC server (master_server.cc — ProtoServer-analog data plane)
    lib.ptms_start.restype = c.c_void_p
    lib.ptms_start.argtypes = [c.c_void_p, c.c_char_p, c.c_int,
                               c.POINTER(c.c_int)]
    lib.ptms_port.restype = c.c_int
    lib.ptms_port.argtypes = [c.c_void_p]
    if hasattr(lib, "ptms_active_conns"):   # absent in a stale packaged .so
        lib.ptms_active_conns.restype = c.c_int
        lib.ptms_active_conns.argtypes = [c.c_void_p]
    lib.ptms_set_fenced.argtypes = [c.c_void_p, c.c_int]
    lib.ptms_set_fallback.argtypes = [c.c_void_p, PTMS_FALLBACK_FN]
    lib.ptms_reply.argtypes = [c.c_void_p, c.POINTER(c.c_char), c.c_int]
    lib.ptms_stop.argtypes = [c.c_void_p]
    # recordio
    lib.ptr_writer_open.restype = c.c_void_p
    lib.ptr_writer_open.argtypes = [c.c_char_p]
    lib.ptr_writer_write.restype = c.c_int
    lib.ptr_writer_write.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.ptr_writer_close.restype = c.c_int64
    lib.ptr_writer_close.argtypes = [c.c_void_p]
    lib.ptr_reader_open.restype = c.c_void_p
    lib.ptr_reader_open.argtypes = [c.c_char_p]
    lib.ptr_reader_next.restype = c.c_int
    lib.ptr_reader_next.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.ptr_reader_close.argtypes = [c.c_void_p]
    # arena
    lib.pta_create.restype = c.c_void_p
    lib.pta_create.argtypes = [c.c_uint64, c.c_uint64]
    lib.pta_destroy.argtypes = [c.c_void_p]
    lib.pta_alloc.restype = c.c_uint64
    lib.pta_alloc.argtypes = [c.c_void_p, c.c_uint64]
    lib.pta_free.restype = c.c_int
    lib.pta_free.argtypes = [c.c_void_p, c.c_uint64]
    lib.pta_stats.argtypes = [c.c_void_p] + [c.POINTER(c.c_uint64)] * 3
