"""TaskMaster — fault-tolerant data-shard dispatch (native/task_master.cc).

Go master client semantics (go/master/client.go + python/paddle/v2/master/
client.py): set a dataset of chunk payloads, consume tasks, report
finished/failed; timed-out tasks re-dispatch; over-failed tasks are discarded;
snapshot/restore covers master crash recovery (SURVEY.md §5 failure detection).
"""

from __future__ import annotations

import ctypes
import time
from typing import List, Optional, Tuple

from .lib import load_library


class TaskMaster:
    def __init__(self, timeout_s: float = 60.0, failure_max: int = 3):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native host runtime unavailable (no toolchain?)")
        self._lib = lib
        self._h = lib.ptm_create(ctypes.c_double(timeout_s), failure_max)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.ptm_destroy(self._h)
            self._h = None

    def set_dataset(self, payloads: List[str]):
        arr = (ctypes.c_char_p * len(payloads))(
            *[p.encode() for p in payloads])
        self._lib.ptm_set_dataset(self._h, arr, len(payloads))

    def get_task(self, now: Optional[float] = None) -> Optional[Tuple[int, str]]:
        """-> (task_id, payload) | None when nothing currently available."""
        ts = ctypes.c_double(time.monotonic() if now is None else now)
        size = 4096
        while True:
            buf = ctypes.create_string_buffer(size)
            needed = ctypes.c_int(0)
            tid = self._lib.ptm_get_task(self._h, ts, buf, len(buf),
                                         ctypes.byref(needed))
            if tid == -3:  # buffer too small; task not consumed — retry bigger
                size = max(needed.value, size * 2)
                continue
            if tid < 0:
                return None
            return tid, buf.value.decode()

    def pass_finished(self) -> bool:
        """True when todo and pending are both empty (end of pass)."""
        t, p, d, x, e = self.stats()
        return t == 0 and p == 0

    def task_finished(self, task_id: int):
        self._lib.ptm_task_finished(self._h, task_id)

    def new_pass(self) -> bool:
        """Refill todo from done for the next pass; False if pass unfinished."""
        return self._lib.ptm_new_pass(self._h) == 0

    def task_failed(self, task_id: int) -> bool:
        """Returns True if the task was discarded (failure_max reached)."""
        return self._lib.ptm_task_failed(self._h, task_id) == 1

    def tick(self, now: Optional[float] = None) -> int:
        """Requeue timed-out pending tasks; returns how many moved."""
        return self._lib.ptm_tick(
            self._h, ctypes.c_double(time.monotonic() if now is None else now))

    def stats(self) -> Tuple[int, int, int, int, int]:
        vals = [ctypes.c_int() for _ in range(5)]
        self._lib.ptm_stats(self._h, *[ctypes.byref(v) for v in vals])
        return tuple(v.value for v in vals)  # todo, pending, done, discarded, epoch

    def snapshot(self, path: str):
        if self._lib.ptm_snapshot(self._h, path.encode()) != 0:
            raise IOError(f"snapshot to {path} failed")

    def restore(self, path: str):
        rc = self._lib.ptm_restore(self._h, path.encode())
        if rc != 0:
            raise IOError(f"restore from {path} failed ({rc})")
