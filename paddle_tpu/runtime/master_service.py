"""Network service + client for the data master — the trainer-facing RPC.

Re-provides the reference's distributed data-dispatch plane:
* Go master RPC service (go/master/service.go GetTask/TaskFinished/TaskFailed
  RPCs) -> :class:`MasterServer` serving the native C++ TaskMaster
  (native/task_master.cc) over a length-prefixed JSON protocol — the framing
  discipline of ProtoServer (pserver/ProtoServer.h:36: length-framed proto
  messages over raw sockets).
* auto-reconnecting client (go/connection/conn.go) -> :class:`MasterClient`.
* periodic timeout tick + snapshot (service.go:198-200, :166-227) -> the
  server's housekeeping thread.

Trainers are stateless consumers: a consumer that dies mid-task simply lets
the lease expire; the task re-dispatches to a healthy one (elastic training,
SURVEY.md §5 'Failure detection').
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import List, Optional, Tuple

from .master import TaskMaster

_HDR = struct.Struct("<I")


def _send_msg(sock: socket.socket, obj) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return json.loads(body.decode())


def _recv_exact(sock, n) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class MasterServer:
    """Serve a TaskMaster over TCP with timeout housekeeping + snapshots."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 timeout_s: float = 60.0, failure_max: int = 3,
                 snapshot_path: Optional[str] = None,
                 tick_interval: float = 1.0):
        self.master = TaskMaster(timeout_s=timeout_s, failure_max=failure_max)
        if snapshot_path:
            try:
                self.master.restore(snapshot_path)
            except IOError:
                pass  # no snapshot yet
        self.snapshot_path = snapshot_path
        self._tick_interval = tick_interval
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    req = _recv_msg(self.request)
                    if req is None:
                        return
                    _send_msg(self.request, outer._dispatch(req))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address: Tuple[str, int] = self._server.server_address
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        t = threading.Thread(target=self._server.serve_forever, daemon=True)
        t.start()
        h = threading.Thread(target=self._housekeeping, daemon=True)
        h.start()
        self._threads = [t, h]
        return self

    def stop(self):
        self._stop.set()
        self._server.shutdown()
        self._server.server_close()

    def _housekeeping(self):
        while not self._stop.wait(self._tick_interval):
            self.master.tick()
            if self.snapshot_path:
                try:
                    self.master.snapshot(self.snapshot_path)
                except IOError:
                    pass

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, req):
        op = req.get("op")
        if op == "set_dataset":
            self.master.set_dataset(req["payloads"])
            return {"ok": True}
        if op == "get_task":
            t = self.master.get_task()
            if t is None:
                return {"ok": True, "task": None,
                        "pass_finished": self.master.pass_finished()}
            return {"ok": True, "task": {"id": t[0], "payload": t[1]}}
        if op == "task_finished":
            self.master.task_finished(req["task_id"])
            return {"ok": True}
        if op == "task_failed":
            return {"ok": True,
                    "discarded": self.master.task_failed(req["task_id"])}
        if op == "new_pass":
            return {"ok": self.master.new_pass()}
        if op == "stats":
            todo, pending, done, disc, epoch = self.master.stats()
            return {"ok": True, "todo": todo, "pending": pending,
                    "done": done, "discarded": disc, "epoch": epoch}
        return {"ok": False, "error": f"unknown op {op!r}"}


class MasterClient:
    """Auto-reconnecting client (go/connection/conn.go semantics)."""

    def __init__(self, host: str, port: int, *, retries: int = 5,
                 retry_delay: float = 0.2):
        self.addr = (host, port)
        self.retries = retries
        self.retry_delay = retry_delay
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=10.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)  # LightNetwork
        self._sock = s

    def _call(self, req):
        with self._lock:
            last_err = None
            for attempt in range(self.retries):
                try:
                    if self._sock is None:
                        self._connect()
                    _send_msg(self._sock, req)
                    resp = _recv_msg(self._sock)
                    if resp is None:
                        raise ConnectionError("server closed connection")
                    return resp
                except (OSError, ConnectionError) as e:
                    last_err = e
                    self._sock = None
                    time.sleep(self.retry_delay * (attempt + 1))
            raise ConnectionError(f"master unreachable: {last_err}")

    # -- API ---------------------------------------------------------------
    def set_dataset(self, payloads: List[str]):
        self._call({"op": "set_dataset", "payloads": payloads})

    def get_task(self) -> Optional[Tuple[int, str]]:
        r = self._call({"op": "get_task"})
        if r.get("task") is None:
            return None
        return r["task"]["id"], r["task"]["payload"]

    def task_finished(self, task_id: int):
        self._call({"op": "task_finished", "task_id": task_id})

    def task_failed(self, task_id: int) -> bool:
        return bool(self._call({"op": "task_failed",
                                "task_id": task_id}).get("discarded"))

    def new_pass(self) -> bool:
        return bool(self._call({"op": "new_pass"})["ok"])

    def stats(self):
        r = self._call({"op": "stats"})
        return (r["todo"], r["pending"], r["done"], r["discarded"], r["epoch"])

    def close(self):
        if self._sock is not None:
            self._sock.close()
            self._sock = None
