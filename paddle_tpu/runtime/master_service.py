"""Network service + client for the data master — the trainer-facing RPC.

Re-provides the reference's distributed data-dispatch plane:
* Go master RPC service (go/master/service.go GetTask/TaskFinished/TaskFailed
  RPCs) -> :class:`MasterServer`. The accept/dispatch loop itself is C++
  (native/master_server.cc serving the native TaskMaster,
  native/task_master.cc) over a length-prefixed JSON protocol — the framing
  discipline AND the native socket plane of ProtoServer
  (pserver/ProtoServer.h:36: length-framed messages over raw sockets).
  Python keeps the control plane (lease election, fencing, snapshots) and
  pushes the fencing flag down to the native dispatch.
* auto-reconnecting client (go/connection/conn.go) -> :class:`MasterClient`.
* periodic timeout tick + snapshot (service.go:198-200, :166-227) -> the
  server's housekeeping thread.

Trainers are stateless consumers: a consumer that dies mid-task simply lets
the lease expire; the task re-dispatches to a healthy one (elastic training,
SURVEY.md §5 'Failure detection').
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

from .. import faults, obs
from ..utils.retry import RetryBudgetExceeded, RetryPolicy
from .master import TaskMaster

_HDR = struct.Struct("<I")
# same guard as the C++ plane (master_server.cc kMaxFrame): a hostile
# 4-byte header must not make the daemon attempt a multi-GiB allocation
_MAX_FRAME = 64 << 20

#: structured error codes of the membership/epoch fencing contract
#: (runtime/membership.py + trainer/elastic.py). Defined ONCE here — the
#: client's fail-fast behavior keys on these exact strings, so emitters
#: import the constants instead of respelling them.
CODE_UNKNOWN_MEMBER = "unknown_member"
CODE_STALE_MEMBER = "stale_member"
CODE_STALE_EPOCH = "stale_epoch"
CODE_STALE_STEP = "stale_step"
#: AUTHORITATIVE refusals — the server is healthy and said no — so the
#: client fails fast with a typed error instead of burning its reconnect
#: budget the way it does (correctly) against a connection-refused master
#: that is restarting from snapshot.
FENCE_CODES = frozenset({CODE_UNKNOWN_MEMBER, CODE_STALE_MEMBER,
                         CODE_STALE_EPOCH, CODE_STALE_STEP})


class StaleMemberError(RuntimeError):
    """A structured membership/epoch fencing refusal (``code`` in
    :data:`FENCE_CODES`). Deliberately NOT a ConnectionError: the shared
    RetryPolicy's retryable set never re-sends a fenced request, and the
    caller gets the refusal on the FIRST attempt with the server's current
    epoch attached — resync-and-retry is the caller's decision."""

    def __init__(self, msg: str, *, code: str, epoch=None, attempts: int = 1):
        super().__init__(msg)
        self.code = code
        self.epoch = epoch
        self.attempts = attempts


def _send_msg(sock: socket.socket, obj, *, chaos: bool = False) -> None:
    payload = json.dumps(obj).encode()
    if len(payload) > _MAX_FRAME:
        raise ValueError(f"frame too large ({len(payload)} bytes)")
    # chaos plane (client edges only — ``chaos=True``; a server handler
    # sharing this framing must not double-count the site): rpc.send can
    # raise (dropped request), delay, or mangle the frame. The header is
    # packed BEFORE the hook so a truncate fault produces a genuinely torn
    # frame (header promises more bytes than arrive — the receiver blocks,
    # the sender's call timeout fires), and a corrupt fault turns into a
    # parse failure at the receiver
    hdr = _HDR.pack(len(payload))
    if chaos:
        payload = faults.filter_bytes("rpc.send", payload)
    sock.sendall(hdr + payload)


def _recv_msg(sock: socket.socket, *, chaos: bool = False):
    if chaos:
        faults.fire("rpc.recv")
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    if n > _MAX_FRAME:
        return None                     # drop the connection, not the heap
    body = _recv_exact(sock, n)
    if body is None:
        return None
    try:
        return json.loads(body.decode())
    except (UnicodeDecodeError, ValueError):
        # a frame that fails to parse means the stream is desynchronized or
        # corrupt: sever the connection (the retry layer reconnects) rather
        # than propagate garbage into the caller
        raise ConnectionError("corrupt frame from peer (json parse failed)")


def _recv_exact(sock, n) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class MasterServer:
    """Serve a TaskMaster over TCP with timeout housekeeping + snapshots.

    Pass a :class:`~paddle_tpu.runtime.lease.FileLease` to run under master
    election: the server renews the lease while alive and shuts itself down
    if the lease is lost (split-brain guard) — the etcd-session semantics of
    go/master/etcd_client.go.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 timeout_s: float = 60.0, failure_max: int = 3,
                 snapshot_path: Optional[str] = None,
                 snapshot_store=None,
                 tick_interval: float = 1.0, lease=None):
        self.master = TaskMaster(timeout_s=timeout_s, failure_max=failure_max)
        if snapshot_store is not None and snapshot_path:
            raise ValueError("pass snapshot_path (shared/local file) OR "
                             "snapshot_store (network blob), not both")
        if snapshot_store is not None:
            # network snapshot home (coord.NetworkFencedStore): a successor
            # on ANY host fetches before serving — no shared filesystem
            import os
            import tempfile
            fd, tmp = tempfile.mkstemp(prefix="mastersnap.")
            os.close(fd)
            try:
                if snapshot_store.fetch_to(tmp):
                    self.master.restore(tmp)
            finally:
                try:
                    os.remove(tmp)
                except OSError:
                    pass
            self._fence = snapshot_store
        elif snapshot_path:
            import os
            if os.path.exists(snapshot_path):
                # corruption (CRC/parse failure) must surface loudly — only a
                # genuinely absent snapshot means "fresh start"
                self.master.restore(snapshot_path)
            from .lease import FencedFile
            self._fence = FencedFile(snapshot_path)
        else:
            self._fence = None
        self.snapshot_path = snapshot_path
        self._tick_interval = tick_interval
        self.lease = lease
        self._keeper = None
        self.fence_token = None   # set from the lease at start()
        self._deposed = False
        self._fence_checked_at = float("-inf")
        self.lease_lost = threading.Event()
        self._host, self._port = host, port
        self._srv_h = None        # native server handle (master_server.cc)
        self._lib = None          # set (before handle publication) in start()
        # guards every read/swap of _srv_h: stop() can race a housekeeping
        # tick (or a second stop() from LeaseKeeper.on_lost), and the
        # native handle must never be ptms_stop'd twice or fenced after
        # free — both are a native crash, precisely during failover
        self._srv_lock = threading.Lock()
        self.address: Tuple[str, int] = (host, port)
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # cluster telemetry home: workers obs_push their registry
        # snapshots here; obs_stats serves the merged, worker-tagged view
        from ..obs.aggregate import ClusterAggregator
        self.aggregator = ClusterAggregator()
        self._fallback_cb = None  # keepalive for the ctypes callback
        # control-plane extension ops (the serving daemon's srv_submit /
        # srv_poll / srv_cancel ride here): served through the native
        # unknown-op fallback exactly like obs_push — the C++ data plane
        # never learns their payloads. Registered BEFORE start() so no
        # request can observe a half-wired op table.
        self._ext_ops = {}
        self._known_ops = set(self._KNOWN_OPS)

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        if self.lease is not None:
            from .lease import LeaseKeeper
            # try_acquire (not held_by_me) even when the lease already names
            # us: it refreshes the TTL and recovers the fencing token after
            # a same-owner restart
            if not self.lease.try_acquire():
                raise RuntimeError(
                    f"lease {self.lease.path} held by {self.lease.holder()}")
            self.fence_token = self.lease.token
            if self._fence is not None and \
                    not self._fence.claim(self.fence_token):
                self.lease.release()   # don't wedge standby takeover
                fence_loc = getattr(self._fence, "fence_path",
                                    getattr(self._fence, "key", "?"))
                raise RuntimeError(
                    "snapshot fence already claimed by a newer master "
                    f"(our token {self.fence_token} < recorded "
                    f"{self._fence._recorded()}); if the lease epoch state "
                    f"was lost, clear the fence record at {fence_loc} or "
                    "seed the lease epoch past the recorded value")
            self._keeper = LeaseKeeper(self.lease, on_lost=self._on_lease_lost)
            self._keeper.start()
        # the accept/dispatch loop is NATIVE (master_server.cc, the
        # ProtoServer-analog): it serves the ptm_* data plane directly;
        # Python retains the control plane and pushes the fenced flag down
        import ctypes

        from .lib import load_library
        lib = load_library()
        if lib is None:
            if self._keeper is not None:
                self._keeper.stop(release=True)
                self._keeper = None
            raise RuntimeError("native host runtime unavailable "
                               "(libpaddle_tpu_host.so)")
        out_port = ctypes.c_int(0)
        h = lib.ptms_start(self.master._h, self._host.encode(), self._port,
                           ctypes.byref(out_port))
        if not h:
            if self._keeper is not None:
                self._keeper.stop(release=True)
                self._keeper = None
            raise OSError(f"ptms_start failed to bind "
                          f"{self._host}:{self._port}")
        # _lib must be live BEFORE the handle is published: the keeper
        # thread is already running, and an _on_lease_lost -> stop() that
        # observes the handle must be able to ptms_stop it. A stop() that
        # ran to COMPLETION before publication saw _srv_h=None and stopped
        # nothing — publishing now would leave a deposed master's native
        # listener serving forever, so detect it and stop the handle here.
        self._lib = lib
        with self._srv_lock:
            if self._stop.is_set():
                lib.ptms_stop(h)
                h = None
            else:
                self._srv_h = h
        if h is None:
            raise RuntimeError(
                "master stopped during start-up (lease lost or stop() "
                "called before the server handle was published)")
        self.address = (self._host, out_port.value)
        # ops the native dispatch does not know (obs_push/obs_stats and
        # anything future) fall back into Python's _dispatch: the C++
        # handler hands us the raw frame, we reply via ptms_reply. The
        # CFUNCTYPE object must outlive the server (ctypes keepalive).
        # Registration happens a few lines after ptms_start begins
        # accepting; in-repo clients only learn the port after start()
        # returns, and a fixed-port client racing the window just gets one
        # "unknown op" answer (raised by obs_push, retried by ObsPusher).
        from .lib import PTMS_FALLBACK_FN

        def _fallback(buf, n, reply):
            try:
                req = json.loads(ctypes.string_at(buf, n).decode())
                resp = self._dispatch(req) if isinstance(req, dict) else \
                    {"ok": False, "error": "bad request"}
            except Exception as e:   # never let an exception cross into C++
                resp = {"ok": False,
                        "error": f"{type(e).__name__}: {e}"}
            try:
                data = json.dumps(resp).encode()
            except (TypeError, ValueError):
                data = b'{"ok": false, "error": "unserializable response"}'
            lib.ptms_reply(reply, data, len(data))

        self._fallback_cb = PTMS_FALLBACK_FN(_fallback)
        # initial fencing state computed OUTSIDE the lock (filesystem read);
        # the native calls re-read the handle under it — a stop() racing
        # start() (lease lost mid-bring-up) may already have freed `h`, and
        # these must then be skipped, not crash on a dead handle
        fenced0 = 1 if self._fenced_out() else 0
        with self._srv_lock:
            if self._srv_h is not None:
                lib.ptms_set_fallback(self._srv_h, self._fallback_cb)
                # push the fencing state before any request can mutate
                lib.ptms_set_fenced(self._srv_h, fenced0)
        hk = threading.Thread(target=self._housekeeping, daemon=True)
        hk.start()
        self._threads = [hk]
        return self

    def _on_lease_lost(self):
        # another master was elected: stop serving immediately (split-brain
        # guard); task state survives in the CRC-checked snapshot
        self.lease_lost.set()
        self.stop(release_lease=False)

    def stop(self, release_lease: bool = True):
        self._stop.set()
        if self._keeper is not None:
            self._keeper.stop(release=release_lease)
            self._keeper = None
        # native stop severs the listener AND every live connection — a
        # deposed master must not keep answering connected clients. The
        # handle SWAP happens under the lock (a concurrent stop() or
        # housekeeping tick can never double-free or fence a freed
        # handle), but ptms_stop itself runs OUTSIDE it: it drains the
        # handler threads, and a handler that takes _srv_lock
        # (active_connections via srv_stats) would otherwise deadlock the
        # shutdown. After the swap `h` is privately owned — no other path
        # can reach it.
        with self._srv_lock:
            h, self._srv_h = self._srv_h, None
        if h:
            self._lib.ptms_stop(h)

    def try_snapshot(self) -> bool:
        """Fenced snapshot write: refused (False) once a newer master has
        claimed the snapshot — a deposed master that wakes after its TTL
        cannot clobber the new generation's state."""
        if self._fence is None:
            return False
        try:
            ok = self._fence.write(
                self.fence_token, lambda p: self.master.snapshot(p))
        except IOError:
            return False
        if not ok:
            self._deposed = True   # refusal is authoritative — don't wait
        return ok

    def _housekeeping(self):
        while not self._stop.wait(self._tick_interval):
            self.master.tick()
            if self._fence is not None and not self.try_snapshot() \
                    and self._fenced_out():
                # a newer master owns the snapshot: we are deposed
                self._on_lease_lost()
                return
            # keep the native server's fencing flag current (the C++
            # dispatch consults only this flag — same staleness bound as
            # the old per-request cached check, one tick/renewal window).
            # _fenced_out() runs OUTSIDE the lock (it can hit the
            # filesystem); only the handle read + native call are guarded
            fenced = 1 if self._fenced_out() else 0
            with self._srv_lock:
                if self._srv_h is not None:
                    self._lib.ptms_set_fenced(self._srv_h, fenced)

    def _fenced_out(self) -> bool:
        """Deposed-master check. Deposition is permanent, so a positive
        result is cached; negative results are re-checked at most once per
        tick_interval to keep filesystem reads off the RPC hot path."""
        if self.fence_token is None:
            return False
        if self._deposed or self.lease_lost.is_set():
            return True
        # staleness bound = the lease renewal cadence: a takeover is
        # reflected here no later than it would be noticed by the keeper
        window = (self.lease.ttl / 3.0 if self.lease is not None
                  else self._tick_interval)
        now = time.monotonic()
        if now - self._fence_checked_at < window:
            return False
        self._fence_checked_at = now
        # a transient coord-server outage must not crash housekeeping or a
        # handler thread: reads fail OPEN (not deposed — writes still fail
        # CLOSED via try_snapshot, so a deposed master can't publish while
        # the question is unanswerable) and the next window re-asks
        try:
            deposed = (self._fence is not None and
                       self._fence._recorded() > self.fence_token)
            if not deposed and self.lease is not None:
                cur = self.lease.current_token()
                deposed = cur is not None and cur > self.fence_token
        except (OSError, ConnectionError):
            return False
        if deposed:
            self._deposed = True
        return deposed

    # get_task is included: it moves a task todo->pending, and a deposed
    # master handing out tasks from its stale queue is exactly the
    # split-brain fencing exists to stop
    _MUTATING_OPS = frozenset(
        {"set_dataset", "get_task", "task_finished", "task_failed",
         "new_pass"})
    #: ops allowed as the requests_total `type` label value — anything
    #: else (arbitrary strings off the wire, since the native server
    #: forwards every unknown op here) is clamped to "unknown" so a
    #: hostile/buggy peer cannot mint unbounded counter series (the
    #: failure mode our own L005 cardinality lint flags)
    _KNOWN_OPS = _MUTATING_OPS | frozenset({"stats", "obs_push",
                                            "obs_stats", "obs_health"})

    # -- dispatch ----------------------------------------------------------
    # The network path dispatches in C++ (master_server.cc, byte-identical
    # protocol) for the hot data-plane ops; unknown ops (obs_push,
    # obs_stats) fall back here via ptms_set_fallback. This Python twin is
    # also the readable protocol reference and the in-process entry the
    # fencing tests drive directly.
    def register_op(self, name: str, handler) -> None:
        """Register a control-plane op served via the native fallback path:
        ``handler(req dict) -> resp dict``. The op joins the requests_total
        label allowlist (a registered name is bounded by construction).
        Raises if the name would shadow a built-in or an earlier
        registration — op names are a wire contract, not a namespace to
        last-write-win over."""
        if name in self._known_ops or name in self._ext_ops:
            raise ValueError(f"op {name!r} already registered")
        self._ext_ops[name] = handler
        self._known_ops.add(name)

    def active_connections(self) -> int:
        """Live client connections on the native server (0 when stopped) —
        the serving daemon's drain/telemetry signal. Check
        :attr:`conn_count_supported` before treating 0 as authoritative:
        a stale packaged .so without the symbol also reads 0."""
        with self._srv_lock:
            if self._srv_h is None or self._lib is None or \
                    not hasattr(self._lib, "ptms_active_conns"):
                return 0
            return int(self._lib.ptms_active_conns(self._srv_h))

    @property
    def conn_count_supported(self) -> bool:
        """True when the loaded native library actually exports
        ``ptms_active_conns`` and the server is running."""
        with self._srv_lock:
            return (self._srv_h is not None and self._lib is not None
                    and hasattr(self._lib, "ptms_active_conns"))

    def _dispatch(self, req):
        op = str(req.get("op"))
        label = op if op in self._known_ops else "unknown"
        obs.count("master.requests_total", type=label)
        # server-side span parented on the client's rpc.call via the wire
        # context — the cross-process edge the merged Chrome trace stitches
        try:
            with obs.server_span("master.dispatch", req.get("trace"), op=op):
                resp = self._dispatch_op(req)
        except Exception:
            # a malformed request (missing field, bad type) is exactly
            # what the error counter exists to surface
            obs.count("master.request_errors_total", type=label)
            raise
        # key on the error FIELD, not ok alone: new_pass answers
        # {"ok": false} with no error when the pass simply isn't finished
        # — routine polling must not read as an error stream
        if resp.get("error") is not None:
            obs.count("master.request_errors_total", type=label)
        return resp

    def _dispatch_op(self, req):
        op = req.get("op")
        if op in self._MUTATING_OPS and self._fenced_out():
            return {"ok": False,
                    "error": f"fenced: stale master token {self.fence_token}"}
        ext = self._ext_ops.get(op)
        if ext is not None:
            return ext(req)
        if op == "obs_push":
            # telemetry is read-only w.r.t. task state: accepted even from
            # a fenced master's clients (the fleet view must survive
            # failover windows)
            n = self.aggregator.push(str(req.get("worker", "?")),
                                     req.get("samples"))
            return {"ok": True, "accepted": n}
        if op == "obs_stats":
            return {"ok": True, "workers": self.aggregator.workers(),
                    "samples": self.aggregator.merged_samples()}
        if op == "obs_health":
            # the fleet health plane's read surface: derived per-worker
            # health, live alerts, and the bounded transition log
            # (obs/health.py, obs/alerts.py)
            agg = self.aggregator
            agg.maybe_evaluate()
            return {"ok": True, "health": agg.health_snapshot(),
                    "active": agg.alerts.active(),
                    "events": agg.alerts.recent_events(),
                    "actions": agg.recent_actions(),
                    # raw request-timeline legs + slow exemplars (obs/
                    # requests.py): obs trace / obs serve stitch them
                    "requests": agg.requests.export_legs(),
                    "exemplars": agg.requests.exemplars()}
        if op == "set_dataset":
            self.master.set_dataset(req["payloads"])
            return {"ok": True}
        if op == "get_task":
            t = self.master.get_task()
            if t is None:
                return {"ok": True, "task": None,
                        "pass_finished": self.master.pass_finished()}
            return {"ok": True, "task": {"id": t[0], "payload": t[1]}}
        if op == "task_finished":
            self.master.task_finished(req["task_id"])
            return {"ok": True}
        if op == "task_failed":
            return {"ok": True,
                    "discarded": self.master.task_failed(req["task_id"])}
        if op == "new_pass":
            return {"ok": self.master.new_pass()}
        if op == "stats":
            todo, pending, done, disc, epoch = self.master.stats()
            return {"ok": True, "todo": todo, "pending": pending,
                    "done": done, "discarded": disc, "epoch": epoch}
        return {"ok": False, "error": f"unknown op {op!r}"}


class _RpcClient:
    """Reconnecting JSON-frame RPC plumbing shared by every client in the
    runtime (master + coordinator): one socket under a lock, a per-call
    deadline, the shared :class:`RetryPolicy`, endpoint-failover rotation,
    and drop-the-socket-on-any-error discipline (a stream in an unknown
    state is never reused). Subclasses add their service API on top of
    :meth:`_call` and set ``_rpc_name`` for error messages.
    """

    _rpc_name = "rpc"

    def __init__(self, host=None, port: Optional[int] = None, *,
                 endpoints: Optional[List[Tuple[str, int]]] = None,
                 retries: int = 5, retry_delay: float = 0.2,
                 call_timeout: float = 10.0,
                 retry_policy: Optional[RetryPolicy] = None):
        if endpoints is None:
            if host is None or port is None:
                raise ValueError("pass (host, port) or endpoints=[...]")
            endpoints = [(host, port)]
        self.endpoints = list(endpoints)
        self._ep_idx = 0
        #: per-call socket deadline: a wedged master surfaces as a timeout
        #: (retried against the next endpoint), never an indefinite hang
        self.call_timeout = call_timeout
        # capped exponential backoff with jitter, replacing the old
        # retry_delay * (attempt + 1) linear sleep (ISSUE 2 satellite)
        self.policy = retry_policy or RetryPolicy(
            max_attempts=retries, base_delay=retry_delay, multiplier=2.0,
            max_delay=2.0, jitter=0.25)
        if retry_policy is None:
            # retry telemetry (rpc.retries_total / giveups / backoff) — a
            # no-op callable until an ObsSession is installed. Only on OUR
            # policy: a caller-supplied (possibly shared) instance is never
            # mutated, and its observer choice is the caller's
            self.policy.observer = obs.retry_observer("rpc")
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        #: last membership epoch seen in ANY reply (None until one carries
        #: it) — stamped into the final reconnect error so an operator
        #: reading "unreachable after N attempts" also sees how current
        #: this client's view was when the master went away
        self.last_epoch = None

    @property
    def addr(self) -> Tuple[str, int]:
        return self.endpoints[self._ep_idx]

    def _connect(self):
        last = None
        for _ in range(len(self.endpoints)):
            try:
                s = socket.create_connection(self.addr,
                                             timeout=self.call_timeout)
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)  # LightNetwork
                self._sock = s
                return
            except OSError as e:
                last = e
                self._ep_idx = (self._ep_idx + 1) % len(self.endpoints)
        raise ConnectionError(
            f"no {self._rpc_name} endpoint reachable: {last}")

    def _drop_sock(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call_once(self, req):
        try:
            if self._sock is None:
                self._connect()
            self._sock.settimeout(self.call_timeout)
            _send_msg(self._sock, req, chaos=True)
            resp = _recv_msg(self._sock, chaos=True)
        except (OSError, ConnectionError):
            # the stream is in an unknown state: never reuse the socket
            self._drop_sock()
            raise
        if resp is None:
            self._drop_sock()
            raise ConnectionError("server closed connection")
        if isinstance(resp, dict) and resp.get("epoch") is not None and \
                str(req.get("op", "")).startswith(
                    ("mbr_", "ela_", "srv_", "route_")):
            # only membership-plane replies stamp the epoch (serving and
            # router replies carry the membership epoch of the cluster
            # they are joined to): the built-in "stats" op also answers
            # an "epoch" field, but that one is the TaskMaster's
            # pass/dataset generation — reporting it as a membership
            # epoch would mislead whoever correlates the final reconnect
            # error against cluster.epoch
            self.last_epoch = resp["epoch"]
        if not resp.get("ok"):
            if resp.get("code") in FENCE_CODES:
                # authoritative membership/epoch refusal: fail FAST (no
                # reconnect budget spent — retrying a fence cannot help)
                raise StaleMemberError(
                    f"{self._rpc_name} fenced: {resp.get('error')}",
                    code=resp["code"], epoch=resp.get("epoch"))
            if str(resp.get("error", "")).startswith("fenced"):
                # deposed server: rotate to the standby and retry
                self._ep_idx = (self._ep_idx + 1) % len(self.endpoints)
                self._drop_sock()
                raise ConnectionError(resp["error"])
        return resp

    def _call(self, req):
        # span + latency histogram cover the WHOLE call incl. retries —
        # what the caller experienced, not one socket round trip
        with self._lock, \
                obs.span("rpc.call", metric="rpc.call_seconds",
                         metric_labels={"rpc": self._rpc_name},
                         rpc=self._rpc_name, op=req.get("op")) as sp:
            obs.count("rpc.calls_total", rpc=self._rpc_name,
                      op=str(req.get("op")))
            # distributed tracing: stamp this span's identity into the
            # envelope so the server parents its dispatch span on it. None
            # when no session is installed — the wire bytes then stay
            # identical to an un-instrumented client's (obs/context.py)
            ctx = obs.wire_context(sp)
            if ctx is not None:
                req = dict(req, trace=ctx)
            try:
                return self.policy.call(
                    self._call_once, req,
                    describe=f"{self._rpc_name} {req.get('op')!r}")
            except RetryBudgetExceeded as e:
                # connection-refused/timeout class: the reconnect budget
                # WAS the right response (a restarting master comes back
                # inside the snapshot/restore window) — report how hard we
                # tried and how current our membership view was
                seen = ("unknown" if self.last_epoch is None
                        else str(self.last_epoch))
                raise ConnectionError(
                    f"{self._rpc_name} server unreachable after "
                    f"{e.attempts} attempt(s) (last seen membership epoch "
                    f"{seen}): {e.last_error}") from e.last_error

    def close(self):
        with self._lock:
            self._drop_sock()


class MasterClient(_RpcClient):
    """Auto-reconnecting master client (go/connection/conn.go semantics).

    Accepts either one address or a failover list of candidate master
    endpoints (active + standbys); reconnection rotates through them, so a
    master failover is transparent to the trainer — the role etcd master
    discovery plays for go/master/client.go.
    """

    _rpc_name = "master rpc"

    def set_dataset(self, payloads: List[str]):
        self._call({"op": "set_dataset", "payloads": payloads})

    def get_task(self) -> Optional[Tuple[int, str]]:
        r = self._call({"op": "get_task"})
        if not r.get("ok") and r.get("error"):
            # a structured server error ("payload too large: ..." when the
            # escaped response would blow the frame limit) must surface as
            # an exception, not read as an innocent empty queue
            raise RuntimeError(f"get_task failed: {r['error']}")
        if r.get("task") is None:
            return None
        return r["task"]["id"], r["task"]["payload"]

    def task_finished(self, task_id: int):
        self._call({"op": "task_finished", "task_id": task_id})

    def task_failed(self, task_id: int) -> bool:
        return bool(self._call({"op": "task_failed",
                                "task_id": task_id}).get("discarded"))

    def new_pass(self) -> bool:
        return bool(self._call({"op": "new_pass"})["ok"])

    def stats(self):
        r = self._call({"op": "stats"})
        return (r["todo"], r["pending"], r["done"], r["discarded"], r["epoch"])

    # -- cluster telemetry (obs plane) -------------------------------------
    def obs_push(self, worker: str, samples) -> int:
        """Push this worker's metric snapshot (``MetricsRegistry.collect()``
        samples) to the master's aggregator; returns the accepted count.
        An ok=false answer (e.g. a server whose dispatch predates obs_push)
        raises, so ObsPusher counts it as a push failure, not a success."""
        from ..obs.aggregate import wire_safe_samples
        r = self._call({"op": "obs_push", "worker": str(worker),
                        "samples": wire_safe_samples(list(samples))})
        if not r.get("ok"):
            raise ConnectionError(
                f"obs_push rejected: {r.get('error', 'unknown error')}")
        return int(r.get("accepted", 0))

    def obs_stats(self):
        """The merged fleet view: ``(workers, samples)`` where every sample
        carries a ``worker=<id>`` label (the merged-registry contract)."""
        r = self._call({"op": "obs_stats"})
        if not r.get("ok"):
            raise ConnectionError(
                f"obs_stats rejected: {r.get('error', 'unknown error')}")
        return list(r.get("workers", ())), list(r.get("samples", ()))

    def obs_health(self):
        """The fleet health view (ISSUE 15): ``{"health": per-worker
        derived health, "active": firing alerts, "events": recent alert
        transitions, "actions": committed autoscale actions (ISSUE 18),
        "requests": raw request-timeline legs, "exemplars": the
        slowest-K stitched timelines (ISSUE 19)}`` — what ``paddle_tpu
        obs top/trace --master`` render."""
        r = self._call({"op": "obs_health"})
        if not r.get("ok"):
            raise ConnectionError(
                f"obs_health rejected: {r.get('error', 'unknown error')}")
        return {"health": r.get("health") or {},
                "active": list(r.get("active", ())),
                "events": list(r.get("events", ())),
                "actions": list(r.get("actions", ())),
                "requests": list(r.get("requests", ())),
                "exemplars": list(r.get("exemplars", ()))}
