"""Cluster membership — heartbeat-leased worker registration on the master.

The reference registers trainers/pservers in etcd (go/pserver/etcd_client.go
slot registration under a TTL lease; doc/design/cluster_train/README.md
"trainers are stateless consumers") but its master still assumed a FIXED
worker set. This module makes membership first-class, reusing the repo's
own lease semantics (:mod:`paddle_tpu.runtime.lease` — TTL + monotonic
fencing tokens) over the master's RPC plane:

* workers ``mbr_join`` under a heartbeat lease and receive a **member
  fencing token** (monotonic per service — the etcd-revision discipline of
  :class:`~paddle_tpu.runtime.lease.FileLease`). A re-join under the same
  worker name mints a NEW token; the old incarnation's heartbeats and
  submissions are refused with structured ``stale_member`` errors — a
  partitioned-but-alive zombie can never act for its replacement.
* the master maintains an **epoch-numbered membership view**: every change
  (join, graceful ``mbr_leave``, missed-heartbeat eviction) bumps the
  epoch and notifies ``on_change`` subscribers (the elastic trainer
  re-buckets its task queue there, :mod:`paddle_tpu.trainer.elastic`).
* requests that mutate shared training state carry their sender's epoch;
  :func:`MembershipService.fence` answers an outdated one with a
  structured ``stale_epoch`` error instead of applying a stale worker's
  work — the split-brain guard the Ascend field study (PAPERS.md) shows
  accelerator clusters dying without.

Ops ride :meth:`MasterServer.register_op` (the native unknown-op fallback
path, like ``srv_submit``): ``mbr_join`` / ``mbr_heartbeat`` /
``mbr_leave`` / ``mbr_view``. ``mbr_view`` additionally carries the
**autoscale hook**: :func:`autoscale_recommendation` folds the master's
task-queue depth and the aggregated ``goodput.ratio`` / starvation
telemetry (PR 9's gauges, via the in-process ClusterAggregator) into a
``join`` / ``leave`` / ``hold`` recommendation an external scaler can act
on without understanding the internals.

Worker side: :class:`MembershipClient` (a :class:`MasterClient` with the
mbr ops) and :class:`HeartbeatKeeper` (the LeaseKeeper analog). The
keeper distinguishes failure classes the way the hardened
``MasterClient._call`` reports them: connection-refused (master
restarting) is retried against the snapshot/restore window, and a
structured ``unknown_member``/``stale_member`` answer (we were evicted,
or the master restarted and lost the ephemeral member table) triggers an
automatic **re-join** — a rolling master restart costs one epoch bump,
not the fleet. The chaos site ``mbr.heartbeat`` (faults plane) injects
heartbeat failures to drive the eviction path deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import faults, obs
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy
from .master_service import (CODE_STALE_EPOCH, CODE_STALE_MEMBER,
                             CODE_UNKNOWN_MEMBER, MasterClient,
                             StaleMemberError)

log = get_logger(__name__)


def _err(code: str, msg: str, **extra) -> Dict[str, Any]:
    d = {"ok": False, "code": code, "error": msg}
    d.update(extra)
    return d


class _Member:
    __slots__ = ("worker", "token", "deadline", "caps", "joined_at")

    def __init__(self, worker: str, token: int, deadline: float, caps,
                 joined_at: float):
        self.worker = worker
        self.token = token
        self.deadline = deadline
        self.caps = caps or {}
        self.joined_at = joined_at

    def describe(self) -> Dict[str, Any]:
        return {"worker": self.worker, "token": self.token,
                "caps": dict(self.caps)}


class MembershipService:
    """Epoch-numbered, heartbeat-leased membership table on the master.

    Args:
      ttl: seconds a member survives without a heartbeat before eviction
        (the lease TTL; workers heartbeat at ``ttl / 3``).
      clock: injectable monotonic clock — chaos tests time-travel
        evictions instead of sleeping.
      epoch0: starting epoch; a restarted master seeds it from its
        snapshot so epoch fencing stays monotonic ACROSS restarts (the
        FileLease ``.epoch`` sidecar discipline).
      tick_interval: expiry-check cadence of :meth:`start`'s thread.
    """

    def __init__(self, *, ttl: float = 10.0,
                 clock: Callable[[], float] = time.monotonic,
                 epoch0: int = 0, tick_interval: Optional[float] = None):
        self.ttl = ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._members: Dict[str, _Member] = {}
        self.epoch = epoch0
        self._next_token = 0
        #: the registered fleet actor, (name, token) — single writer of
        #: committed autoscale actions (ISSUE 18); None until one registers
        self._actor: Optional[Tuple[str, int]] = None
        self._server = None
        self._on_change: List[Callable] = []
        self._tick_interval = (tick_interval if tick_interval is not None
                               else max(ttl / 4.0, 0.05))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring -------------------------------------------------------------
    def _aggregator(self):
        """The attached server's ClusterAggregator (None when detached —
        unit tests on a bare service)."""
        srv = self._server
        return getattr(srv, "aggregator", None) if srv is not None else None

    def _fleet_health(self):
        return getattr(self._aggregator(), "health", None)

    def _forget_worker(self, worker: str) -> None:
        """Authoritative departure: reap the worker's health feeds AND
        its history series so no alert freezes on a dead incarnation."""
        agg = self._aggregator()
        if agg is None:
            return
        if hasattr(agg, "forget_worker"):
            agg.forget_worker(worker)
        elif getattr(agg, "health", None) is not None:
            agg.health.forget(worker)

    def attach(self, server) -> "MembershipService":
        """Register the mbr_* ops on a MasterServer (before ``start()`` so
        no request can observe a half-wired op table)."""
        self._server = server
        server.register_op("mbr_join", self._op_join)
        server.register_op("mbr_heartbeat", self._op_heartbeat)
        server.register_op("mbr_leave", self._op_leave)
        server.register_op("mbr_view", self._op_view)
        server.register_op("act_register", self._op_act_register)
        server.register_op("act_report", self._op_act_report)
        return self

    def subscribe(self, fn: Callable[..., None]) -> None:
        """``fn(view, joined=[...], left=[...], reason=str)`` after every
        epoch bump. Called OUTSIDE the membership lock (subscribers
        re-bucket task queues and may call back into stats)."""
        self._on_change.append(fn)

    def start(self) -> "MembershipService":
        """Run the eviction housekeeping thread (real deployments; tests
        with a fake clock call :meth:`expire` directly)."""
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="membership-expiry")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._tick_interval):
            self.expire()

    # -- the table ----------------------------------------------------------
    def members(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [m.describe() for m in self._members.values()]

    def view(self) -> Dict[str, Any]:
        """The epoch-stamped membership view (stable contract: epoch,
        members sorted by worker name)."""
        with self._lock:
            return {"epoch": self.epoch,
                    "members": sorted((m.describe()
                                       for m in self._members.values()),
                                      key=lambda d: d["worker"])}

    def join(self, worker: str, caps=None) -> Tuple[int, int]:
        """Register (or re-register) ``worker``; returns (token, epoch).
        A join over a live same-name member REPLACES it — the newer
        incarnation wins, the older one's token goes stale."""
        now = self._clock()
        with self._lock:
            replaced = worker in self._members
            self._next_token += 1
            token = self._next_token
            self._members[worker] = _Member(worker, token, now + self.ttl,
                                            caps, now)
            self._bump_locked()
            epoch = self.epoch
        obs.count("cluster.joins_total")
        if replaced:
            obs.count("cluster.leaves_total", reason="replaced")
        log.info("member %s joined (token %d) -> epoch %d%s", worker, token,
                 epoch, " [replaced live incarnation]" if replaced else "")
        self._notify(joined=[worker], left=[worker] if replaced else [],
                     reason="join")
        return token, epoch

    def heartbeat(self, worker: str, token: int) -> Optional[Dict[str, Any]]:
        """Extend the member's lease. Returns a structured-error dict on a
        fencing refusal, None when the heartbeat was accepted."""
        with self._lock:
            m = self._members.get(worker)
            if m is None:
                return _err(CODE_UNKNOWN_MEMBER,
                            f"worker {worker!r} is not a member "
                            "(evicted, or the master restarted) — re-join",
                            epoch=self.epoch)
            if token != m.token:
                return _err(CODE_STALE_MEMBER,
                            f"worker {worker!r} token {token} superseded by "
                            f"{m.token} (a newer incarnation joined)",
                            epoch=self.epoch)
            m.deadline = self._clock() + self.ttl
        obs.count("cluster.heartbeats_total")
        # feed the fleet health plane: heartbeat ARRIVAL times are the
        # jitter detector's raw signal (obs/health.py)
        h = self._fleet_health()
        if h is not None:
            h.note_heartbeat(worker)
        return None

    def leave(self, worker: str, token: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            m = self._members.get(worker)
            if m is None:
                return None                   # idempotent: already gone
            if token != m.token:
                return _err(CODE_STALE_MEMBER,
                            f"worker {worker!r} token {token} superseded by "
                            f"{m.token}", epoch=self.epoch)
            del self._members[worker]
            self._bump_locked()
        self._forget_worker(worker)
        obs.count("cluster.leaves_total", reason="graceful")
        log.info("member %s left gracefully -> epoch %d", worker, self.epoch)
        self._notify(joined=[], left=[worker], reason="leave")
        return None

    def expire(self, now: Optional[float] = None) -> List[str]:
        """Evict members whose heartbeat lease lapsed; returns the evicted
        worker names (one epoch bump covers the whole batch)."""
        now = self._clock() if now is None else now
        with self._lock:
            dead = [w for w, m in self._members.items() if m.deadline <= now]
            for w in dead:
                del self._members[w]
            if dead:
                self._bump_locked()
        for w in dead:
            self._forget_worker(w)
            obs.count("cluster.leaves_total", reason="evicted")
            log.warning("member %s missed its heartbeat window (ttl %.1fs): "
                        "evicted -> epoch %d", w, self.ttl, self.epoch)
        if dead:
            self._notify(joined=[], left=dead, reason="evicted")
        return dead

    def validate(self, worker: str, token) -> Optional[Dict[str, Any]]:
        """Member fencing for state-mutating ops (the elastic trainer's
        grad/task RPCs): structured error dict, or None when current."""
        with self._lock:
            m = self._members.get(worker)
            if m is None:
                return _err(CODE_UNKNOWN_MEMBER,
                            f"worker {worker!r} is not a member — re-join",
                            epoch=self.epoch)
            if token != m.token:
                return _err(CODE_STALE_MEMBER,
                            f"worker {worker!r} token {token} superseded by "
                            f"{m.token}", epoch=self.epoch)
        return None

    def fence(self, req_epoch) -> Optional[Dict[str, Any]]:
        """Epoch fencing: a submission stamped with an older view is
        answered with ``stale_epoch`` (and the current epoch, so the
        caller can resync at its next step boundary) instead of applied."""
        with self._lock:
            cur = self.epoch
        if req_epoch is None or int(req_epoch) == cur:
            return None
        obs.count("cluster.stale_rpcs_total", code=CODE_STALE_EPOCH)
        return _err(CODE_STALE_EPOCH,
                    f"request epoch {req_epoch} != current {cur} "
                    "(membership changed; resync and retry)", epoch=cur)

    def _bump_locked(self) -> None:
        self.epoch += 1
        obs.gauge_set("cluster.epoch", float(self.epoch))
        obs.gauge_set("cluster.members", float(len(self._members)))

    def _notify(self, **kw) -> None:
        view = self.view()
        for fn in list(self._on_change):
            try:
                fn(view, **kw)
            except Exception:
                log.exception("membership on_change subscriber failed")

    # -- op handlers (native fallback threads) ------------------------------
    def _fenced_master(self) -> Optional[Dict[str, Any]]:
        # a deposed master must not mutate membership any more than its
        # task queue: same "fenced:" wording, so clients rotate endpoints
        srv = self._server
        if srv is not None and srv._fenced_out():
            return {"ok": False,
                    "error": f"fenced: stale master token {srv.fence_token}"}
        return None

    def _op_join(self, req):
        fenced = self._fenced_master()
        if fenced is not None:
            return fenced
        worker = str(req.get("worker", ""))
        if not worker:
            return {"ok": False, "error": "mbr_join needs a worker name"}
        token, epoch = self.join(worker, req.get("caps"))
        return {"ok": True, "member_token": token, "epoch": epoch,
                "ttl": self.ttl, "view": self.view()}

    def _op_heartbeat(self, req):
        fenced = self._fenced_master()
        if fenced is not None:
            return fenced
        err = self.heartbeat(str(req.get("worker", "")),
                             req.get("member_token"))
        if err is not None:
            for code in (CODE_UNKNOWN_MEMBER, CODE_STALE_MEMBER):
                if err.get("code") == code:
                    obs.count("cluster.stale_rpcs_total", code=code)
            return err
        with self._lock:
            return {"ok": True, "epoch": self.epoch}

    def _op_leave(self, req):
        fenced = self._fenced_master()
        if fenced is not None:
            return fenced
        err = self.leave(str(req.get("worker", "")), req.get("member_token"))
        if err is not None:
            return err
        with self._lock:
            return {"ok": True, "epoch": self.epoch}

    def _op_view(self, req):
        view = self.view()
        rec = None
        srv = self._server
        if srv is not None:
            try:
                todo, pending, _, _, _ = srv.master.stats()
                samples = srv.aggregator.merged_samples()
                rec = autoscale_recommendation(
                    members=len(view["members"]), todo=todo,
                    pending=pending, samples=samples,
                    history=getattr(srv.aggregator, "history", None))
            except Exception as e:   # telemetry must not break the view
                rec = {"action": "hold",
                       "reason": f"recommendation unavailable: {e}"}
        view["ok"] = True
        view["ttl"] = self.ttl
        view["recommendation"] = rec
        return view

    # -- fleet-actor registration (ISSUE 18) --------------------------------
    def _op_act_register(self, req):
        """Register the fleet actor that may journal committed autoscale
        actions. SINGLE-WRITER: a new registration replaces the old one
        and stales its token — two actors fighting over one fleet is the
        flapping the whole plane exists to prevent, so the deposed
        actor's next ``act_report`` gets a fencing refusal and stands
        down. Tokens share the member counter (monotonic per master
        incarnation)."""
        fenced = self._fenced_master()
        if fenced is not None:
            return fenced
        actor = str(req.get("actor", ""))
        if not actor:
            return {"ok": False, "error": "act_register needs an actor name"}
        with self._lock:
            self._next_token += 1
            self._actor = (actor, self._next_token)
            epoch = self.epoch
            token = self._next_token
        log.info("fleet actor %r registered (token %d)", actor, token)
        return {"ok": True, "actor_token": token, "epoch": epoch}

    def _op_act_report(self, req):
        """Journal one COMMITTED autoscale action into the aggregator
        (the ``cluster.autoscale_committed`` satellite): only the
        currently-registered actor's token is accepted, with the same
        structured fencing codes the member plane uses."""
        fenced = self._fenced_master()
        if fenced is not None:
            return fenced
        actor = str(req.get("actor", ""))
        token = req.get("actor_token")
        with self._lock:
            registered = self._actor
            epoch = self.epoch
        if registered is None or registered[0] != actor:
            obs.count("cluster.stale_rpcs_total", code=CODE_UNKNOWN_MEMBER)
            return _err(CODE_UNKNOWN_MEMBER,
                        f"actor {actor!r} is not registered", epoch=epoch)
        if registered[1] != token:
            obs.count("cluster.stale_rpcs_total", code=CODE_STALE_MEMBER)
            return _err(CODE_STALE_MEMBER,
                        f"actor {actor!r} token {token} superseded by a "
                        f"newer registration", epoch=epoch)
        agg = self._aggregator()
        if agg is not None and hasattr(agg, "note_action"):
            agg.note_action({
                "actor": actor,
                "action": str(req.get("action", "")),
                "population": str(req.get("population", "")),
                "worker": str(req.get("worker", "")),
                "reason": str(req.get("reason", "")),
                "signal": float(req.get("signal", 0.0) or 0.0)})
        return {"ok": True, "epoch": epoch}


# -- autoscale hook -------------------------------------------------------------

#: tentative action -> the cluster.autoscale_signal gauge encoding
_SIGNAL = {"join": 1.0, "hold": 0.0, "leave": -1.0}


def autoscale_recommendation(*, members: int, todo: int, pending: int,
                             samples=(), scale_up_backlog: float = 2.0,
                             scale_down_goodput: float = 0.25,
                             history=None, hysteresis_windows: int = 3,
                             now: Optional[float] = None
                             ) -> Dict[str, Any]:
    """Fold queue depth + fleet telemetry into a join/leave recommendation.

    Inputs are the master's own task-queue stats and the aggregated
    cluster samples (``ClusterAggregator.merged_samples()`` — every series
    carries a ``worker=<id>`` label). Heuristics, in priority order:

    * no live members → ``join`` (nothing can drain the queue);
    * backlog per worker above ``scale_up_backlog`` → ``join`` (the queue
      is outrunning the fleet);
    * empty queue AND (mean ``goodput.ratio`` below ``scale_down_goodput``
      OR reader starvation observed — ``data.starved_total`` /
      ``data.giveups_total``) with >1 member → ``leave`` (the fleet idles
      waiting for work);
    * otherwise ``hold``.

    **Hysteresis** (ISSUE 15): with ``history`` (the aggregator's
    :class:`~paddle_tpu.obs.health.TimeSeriesStore`), each call records
    its inputs and TENTATIVE action as master-side series
    (``cluster.backlog_per_worker``, ``cluster.autoscale_signal``) and a
    non-``hold`` action only commits once the signal has pointed the same
    way for the last ``hysteresis_windows`` evaluations — a one-sample
    backlog spike (or one idle scrape) recommends ``hold`` with the
    hysteresis reason instead of flapping the fleet. The "no live
    members" branch bypasses hysteresis: a dead fleet with queued work
    must scale up NOW. Without ``history`` the function stays pure
    (unit tests, external scalers sharing the instantaneous policy).
    """
    ratios: List[float] = []
    starved = 0.0
    for s in samples or ():
        try:
            name, value = s.get("name"), s.get("value")
        except AttributeError:
            continue
        if value is None:
            continue
        if name == "goodput.ratio":
            ratios.append(float(value))
        elif name in ("data.starved_total", "data.giveups_total"):
            starved += float(value)
    goodput = sum(ratios) / len(ratios) if ratios else None
    backlog = todo + pending
    out = {"members": members, "backlog": backlog,
           "backlog_per_worker": (backlog / members) if members else None,
           "goodput_ratio": goodput, "starved": starved}
    if members == 0:
        out.update(action="join",
                   reason=f"no live workers for {backlog} queued task(s)")
        if history is not None:
            _record_autoscale(history, out, now)
        return out                     # bypass hysteresis: fleet is dead
    if backlog / members > scale_up_backlog:
        out.update(action="join",
                   reason=f"backlog {backlog} over {members} worker(s) "
                          f"exceeds {scale_up_backlog}/worker")
    elif backlog == 0 and members > 1 and (
            starved > 0 or (goodput is not None
                            and goodput < scale_down_goodput)):
        why = (f"reader starvation observed ({starved:.0f})" if starved > 0
               else f"mean goodput.ratio {goodput:.2f} < "
                    f"{scale_down_goodput}")
        out.update(action="leave", reason=f"queue empty and {why}")
    else:
        out.update(action="hold", reason="queue and fleet in balance")
    if history is not None:
        past = _record_autoscale(history, out, now)
        if out["action"] != "hold":
            want = _SIGNAL[out["action"]]
            recent = past[-hysteresis_windows:]
            # sustained = the last K evaluations agreed, OR — for callers
            # polling too sparsely to ever land K points inside the store
            # window — every in-window evaluation agreed AND they span at
            # least half the window (a single spike spans nothing; a
            # backlog persisting across sparse polls still scales)
            span = past[-1][0] - past[0][0] if len(past) >= 2 else 0.0
            sustained = (
                (len(recent) >= hysteresis_windows
                 and all(v == want for _, v in recent))
                or (len(past) >= 2
                    and all(v == want for _, v in past)
                    and span >= history.window_s / 2.0))
            if not sustained:
                out["tentative"] = out["action"]
                out.update(action="hold",
                           reason=f"hysteresis: '{out['tentative']}' "
                                  f"signal not sustained over "
                                  f"{hysteresis_windows} window(s)")
    return out


def _record_autoscale(history, out: Dict[str, Any], now) -> list:
    """Record this evaluation's inputs + tentative signal into the
    master-side history series; returns the signal points (incl. this
    one, oldest first). Emits the matching gauges so the flap debugging
    series is visible in every export."""
    from ..obs.health import MASTER_WORKER
    signal = _SIGNAL[out["action"]]
    bpw = out.get("backlog_per_worker")
    if bpw is not None:
        history.record_value(MASTER_WORKER, "cluster.backlog_per_worker",
                             float(bpw), ts=now)
        obs.gauge_set("cluster.backlog_per_worker", float(bpw))
    history.record_value(MASTER_WORKER, "cluster.autoscale_signal",
                         signal, ts=now)
    obs.gauge_set("cluster.autoscale_signal", signal)
    return history.points(MASTER_WORKER, "cluster.autoscale_signal",
                          now=now)


# -- worker side ----------------------------------------------------------------

class MembershipClient(MasterClient):
    """MasterClient + the membership ops. Structured fencing refusals
    surface as :class:`StaleMemberError` (fail fast — the hardened
    ``_call`` contract); transport failures keep the reconnect/backoff
    behavior."""

    _rpc_name = "membership rpc"

    def join(self, worker: str, caps=None) -> Tuple[int, int, dict]:
        """-> (member_token, epoch, reply) — reply carries ``view`` (the
        epoch-stamped member list) and ``ttl`` (the heartbeat lease; beat
        at ttl/3, evicted after ttl)."""
        r = self._call({"op": "mbr_join", "worker": worker,
                        "caps": caps or {}})
        if not r.get("ok"):
            raise RuntimeError(f"mbr_join failed: {r.get('error')}")
        return int(r["member_token"]), int(r["epoch"]), r

    def heartbeat(self, worker: str, member_token: int) -> int:
        """-> current epoch. Raises StaleMemberError on a fencing refusal
        (evicted / superseded / master forgot us) and fires the
        ``mbr.heartbeat`` chaos site (faults plane) on the send edge."""
        faults.fire("mbr.heartbeat")
        r = self._call({"op": "mbr_heartbeat", "worker": worker,
                        "member_token": member_token})
        if not r.get("ok"):
            raise RuntimeError(f"mbr_heartbeat failed: {r.get('error')}")
        return int(r["epoch"])

    def leave(self, worker: str, member_token: int) -> None:
        r = self._call({"op": "mbr_leave", "worker": worker,
                        "member_token": member_token})
        if not r.get("ok"):
            raise RuntimeError(f"mbr_leave failed: {r.get('error')}")

    def cluster_view(self) -> dict:
        return self._call({"op": "mbr_view"})

    # -- fleet-actor plane (ISSUE 18) ---------------------------------------
    def act_register(self, actor: str) -> Tuple[int, int]:
        """Register ``actor`` as THE fleet actor -> (actor_token, epoch).
        Replaces (and fences out) any previously registered actor."""
        r = self._call({"op": "act_register", "actor": actor})
        if not r.get("ok"):
            raise RuntimeError(f"act_register failed: {r.get('error')}")
        return int(r["actor_token"]), int(r["epoch"])

    def act_report(self, actor: str, actor_token: int, *, action: str,
                   population: str, worker: str, reason: str = "",
                   signal: float = 0.0) -> int:
        """Journal one committed autoscale action -> current epoch.
        Raises StaleMemberError when this actor has been superseded (the
        hardened ``_call`` fencing contract — the cue to stand down)."""
        r = self._call({"op": "act_report", "actor": actor,
                        "actor_token": actor_token, "action": action,
                        "population": population, "worker": worker,
                        "reason": reason, "signal": signal})
        if not r.get("ok"):
            raise RuntimeError(f"act_report failed: {r.get('error')}")
        return int(r["epoch"])


class HeartbeatKeeper:
    """Background heartbeat thread for one worker membership.

    The failure ladder, matching the hardened client contract:

    * transport errors (master restarting, connection refused) — already
      retried with backoff inside ``_call``; the keeper additionally
      tolerates them for up to ``grace`` seconds measured from the last
      accepted heartbeat (our server-side lease may still be live), then
      declares the membership LOST;
    * ``unknown_member`` / ``stale_member`` — we were evicted or the
      master restarted with an empty table: **re-join** under a
      RetryPolicy; success reports the new (token, epoch) through
      ``on_rejoin`` so the owner can resync; exhaustion → ``on_lost``;
    * an epoch moving in a heartbeat reply fires ``on_epoch`` — the cheap
      membership-changed signal the elastic worker barriers on.
    """

    def __init__(self, client: MembershipClient, worker: str, token: int,
                 *, ttl: float, epoch: int = 0,
                 on_epoch: Optional[Callable[[int], None]] = None,
                 on_rejoin: Optional[Callable[[int, int], None]] = None,
                 on_lost: Optional[Callable[[], None]] = None,
                 rejoin_policy: Optional[RetryPolicy] = None,
                 caps=None):
        self.client = client
        self.worker = worker
        self.token = token
        self.ttl = ttl
        self.epoch = epoch
        self.caps = caps or {}
        self.on_epoch = on_epoch
        self.on_rejoin = on_rejoin
        self.on_lost = on_lost
        self.grace = ttl * 3.0
        self._rejoin = rejoin_policy or RetryPolicy(
            max_attempts=6, base_delay=0.1, multiplier=2.0, max_delay=1.0,
            jitter=0.25)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatKeeper":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"heartbeat-{self.worker}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and \
                self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        last_ok = time.monotonic()
        while not self._stop.wait(self.ttl / 3.0):
            try:
                epoch = self.client.heartbeat(self.worker, self.token)
            except StaleMemberError:
                if not self._try_rejoin():
                    self._lost()
                    return
                last_ok = time.monotonic()
                continue
            except Exception:
                # transport outage or injected chaos: our lease may still
                # be running server-side; only give up past the grace
                if time.monotonic() - last_ok >= self.grace:
                    self._lost()
                    return
                continue
            last_ok = time.monotonic()
            if epoch != self.epoch:
                self.epoch = epoch
                if self.on_epoch is not None:
                    self.on_epoch(epoch)

    def _try_rejoin(self) -> bool:
        def attempt():
            return self.client.join(self.worker, self.caps)
        try:
            token, epoch, _ = self._rejoin.call(
                attempt, describe=f"re-join {self.worker!r}")
        except Exception as e:  # noqa: BLE001 - any failure = not rejoined
            log.warning("worker %s could not re-register: %s", self.worker, e)
            return False
        self.token, old = token, self.epoch
        self.epoch = epoch
        log.info("worker %s re-registered (token %d, epoch %d)",
                 self.worker, token, epoch)
        if self.on_rejoin is not None:
            self.on_rejoin(token, epoch)
        if epoch != old and self.on_epoch is not None:
            self.on_epoch(epoch)
        return True

    def _lost(self) -> None:
        log.error("worker %s lost its membership (heartbeats failing "
                  "past the %.1fs grace)", self.worker, self.grace)
        if self.on_lost is not None:
            self.on_lost()
