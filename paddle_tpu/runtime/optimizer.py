"""Host-side optimizer with serializable state (native/optimizer.cc).

The paddle/optimizer C-ABI library the Go pserver embedded
(go/pserver/optimizer.go). Backs host-offloaded giant embedding tables (SGD /
Adagrad support sparse row updates) and state checkpointing independent of
the device runtime.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from .lib import load_library

_TYPES = {"sgd": 0, "momentum": 1, "adagrad": 2, "adadelta": 3, "adam": 4}
_LR = {"const": 0, "linear": 1}


def _configure(lib):
    c = ctypes
    if getattr(lib, "_pto_configured", False):
        return
    lib.pto_create.restype = c.c_void_p
    lib.pto_create.argtypes = [c.c_int, c.POINTER(c.c_float), c.c_uint64,
                               c.c_double, c.c_int] + [c.c_double] * 7
    lib.pto_destroy.argtypes = [c.c_void_p]
    lib.pto_update.restype = c.c_int
    lib.pto_update.argtypes = [c.c_void_p, c.POINTER(c.c_float), c.c_uint64]
    lib.pto_update_rows.restype = c.c_int
    lib.pto_update_rows.argtypes = [c.c_void_p, c.POINTER(c.c_int),
                                    c.POINTER(c.c_float), c.c_uint64, c.c_uint64]
    lib.pto_get_param.restype = c.POINTER(c.c_float)
    lib.pto_get_param.argtypes = [c.c_void_p, c.POINTER(c.c_uint64)]
    lib.pto_get_rows.restype = c.c_int
    lib.pto_get_rows.argtypes = [c.c_void_p, c.POINTER(c.c_int),
                                 c.POINTER(c.c_float), c.c_uint64, c.c_uint64]
    lib.pto_state_size.restype = c.c_uint64
    lib.pto_state_size.argtypes = [c.c_void_p]
    lib.pto_serialize.restype = c.c_int
    lib.pto_serialize.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib.pto_deserialize.restype = c.c_int
    lib.pto_deserialize.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64]
    lib._pto_configured = True


class HostOptimizer:
    def __init__(self, opt_type: str, param, lr: float = 0.01,
                 lr_policy: str = "const", decay_a: float = 0.0,
                 decay_b: float = 0.0, mu: float = 0.9, rho: float = 0.95,
                 eps: float = 1e-6, beta1: float = 0.9, beta2: float = 0.999):
        """``param`` may be a shape tuple instead of an array: the native
        side then zero-fills in place — no host-side source buffer, no
        copy. The fast path for >HBM embedding tables (a 20 GB table
        starts as ONE allocation instead of numpy-zeros + memcpy)."""
        lib = load_library()
        if lib is None:
            raise RuntimeError("native host runtime unavailable")
        _configure(lib)
        self._lib = lib
        if isinstance(param, tuple):
            self.shape = param
            self.n = int(np.prod(param))
            src = None
        else:
            param = np.asarray(param)
            self.shape = param.shape
            flat = np.ascontiguousarray(param, np.float32).reshape(-1)
            self.n = flat.size
            src = flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        self.opt_type = opt_type
        self._h = lib.pto_create(
            _TYPES[opt_type], src,
            self.n, lr, _LR[lr_policy], decay_a, decay_b, mu, rho, eps,
            beta1, beta2)

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.pto_destroy(self._h)
            self._h = None

    def update(self, grad: np.ndarray):
        g = np.ascontiguousarray(grad, np.float32).reshape(-1)
        if g.size != self.n:
            raise ValueError("gradient size mismatch")
        rc = self._lib.pto_update(
            self._h, g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), self.n)
        if rc != 0:
            raise RuntimeError(f"update failed ({rc})")

    def update_rows(self, rows: np.ndarray, grad: np.ndarray):
        """Sparse rows update: param viewed as [num_rows, width]."""
        rows = np.ascontiguousarray(rows, np.int32)
        g = np.ascontiguousarray(grad, np.float32)
        width = g.shape[-1]
        rc = self._lib.pto_update_rows(
            self._h, rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.size, width)
        if rc != 0:
            raise RuntimeError(f"sparse update failed ({rc}): "
                               f"{self.opt_type} may not support row updates")

    def get_rows(self, rows: np.ndarray, width: int) -> np.ndarray:
        """Gather rows of the param viewed as [num_rows, width] — the
        touched-row prefetch read (pserver getParameterSparse role)."""
        rows = np.ascontiguousarray(rows, np.int32)
        out = np.empty((rows.size, width), np.float32)
        rc = self._lib.pto_get_rows(
            self._h, rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            rows.size, width)
        if rc != 0:
            raise IndexError("row gather out of range")
        return out

    @property
    def param(self) -> np.ndarray:
        n = ctypes.c_uint64()
        ptr = self._lib.pto_get_param(self._h, ctypes.byref(n))
        return np.ctypeslib.as_array(ptr, (n.value,)).reshape(self.shape).copy()

    def serialize(self) -> bytes:
        size = self._lib.pto_state_size(self._h)
        buf = ctypes.create_string_buffer(size)
        if self._lib.pto_serialize(self._h, buf, size) != 0:
            raise RuntimeError("serialize failed")
        return buf.raw

    def deserialize(self, blob: bytes):
        rc = self._lib.pto_deserialize(self._h, blob, len(blob))
        if rc != 0:
            raise RuntimeError(f"deserialize failed ({rc})")
