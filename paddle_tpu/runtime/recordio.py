"""CRC-checked record chunk files (native/recordio.cc)."""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional

from .lib import load_library


class RecordWriter:
    def __init__(self, path: str):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native host runtime unavailable")
        self._lib = lib
        self._h = lib.ptr_writer_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def write(self, payload: bytes):
        rc = self._lib.ptr_writer_write(self._h, payload, len(payload))
        if rc != 0:
            raise IOError("write failed")

    def close(self) -> int:
        if self._h:
            n = self._lib.ptr_writer_close(self._h)
            self._h = None
            return int(n)
        return 0

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class RecordReader:
    def __init__(self, path: str, max_record: int = 1 << 20):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native host runtime unavailable")
        self._lib = lib
        self._h = lib.ptr_reader_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path} (missing or bad magic)")
        self._buf = ctypes.create_string_buffer(max_record)

    def __iter__(self) -> Iterator[bytes]:
        while True:
            n = self._lib.ptr_reader_next(self._h, self._buf, len(self._buf))
            if n == -1:
                return
            if n == -2:
                raise IOError("corrupt record (CRC mismatch or truncation)")
            if n > len(self._buf):
                self._buf = ctypes.create_string_buffer(n)
                continue
            yield self._buf.raw[:n]

    def close(self):
        if self._h:
            self._lib.ptr_reader_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
