"""paddle_tpu.serving — the serving plane.

Layers (each file is one altitude):

* :mod:`.batcher` — in-process continuous batching over the PINNED slot
  pool (per-slot max_len cache rows) + speculative decoding; the
  exact-greedy parity baseline.
* :mod:`.paged` — the paged KV-cache: a shared page pool + per-request
  block tables, so HBM holds live tokens instead of padding
  (:class:`PagePool`, :class:`PagedBatcher`).
* :mod:`.prefix` — the copy-on-write radix index over the paged pool
  (:class:`PrefixIndex`): requests sharing a prompt prefix share full KV
  pages refcounted, and admission prefills only the non-shared suffix.
* :mod:`.engine` — :class:`ServingEngine`: the long-lived scheduler with
  submit/poll/cancel, admission control + backpressure, cancel/timeout
  page reclamation, and TTFT/TPOT SLO telemetry.
* :mod:`.daemon` — ``paddle_tpu serve``: the engine exposed over the
  native RPC plane (srv_submit/srv_poll/srv_cancel via the unknown-op
  fallback) + :class:`ServingClient`; :class:`PrefillDaemon` is the
  prefill-only worker flavor for disaggregated serving.
* :mod:`.ship` — the KV-page shipping wire format (manifest + CRC'd
  chunks) prefill workers use to hand a prefilled slot to a decode
  worker's pool bit-exactly.
* :mod:`.router` — ``paddle_tpu route``: :class:`ServingRouter` places
  client submits over a membership table of prefill/decode workers by
  windowed health trends, aggregates backpressure, and re-routes
  in-flight streams off evicted workers; :class:`RouterClient` adds the
  restart-recovery ladder.

The import surface is flat (``from paddle_tpu.serving import
ContinuousBatcher``) — PR 8 turned the module into a package without
moving any public name.
"""

from .batcher import (SLO_CLASSES, ContinuousBatcher, Request,
                      SpeculativeDecoder, prefix_resubmission_error,
                      validate_request)
from .daemon import PrefillDaemon, ServingClient, ServingDaemon
from .engine import Overloaded, ServingEngine
from .paged import PagedBatcher, PagePool
from .prefix import PrefixIndex
from .router import RouterClient, ServingRouter
from .ship import ShipError

__all__ = ["ContinuousBatcher", "Request", "SpeculativeDecoder",
           "validate_request", "prefix_resubmission_error", "PagePool",
           "PagedBatcher", "PrefixIndex", "SLO_CLASSES", "ServingEngine",
           "Overloaded", "ServingDaemon", "ServingClient", "PrefillDaemon",
           "ServingRouter", "RouterClient", "ShipError"]
