"""Continuous (in-flight) batching for KV-cache decode — the modern serving
loop on top of the incremental-decode path (models/transformer.py
prefill/decode_step; the 2017 reference's serving plane stops at the C
inference ABI, capi/gradient_machine.h:73 — this is the modern capability
axis on top of it).

Design for the TPU/XLA regime:

* The decode state is a fixed pool of ``slots`` — per-layer KV caches
  padded to max_len plus a per-slot position vector. ``decode_step`` is
  already per-sample-positional (writes at ``pos[b]``, masks reads at
  ``j <= pos[b]``), so slots at DIFFERENT sequence positions decode in one
  batched step — the core of continuous batching.
* Host control happens only at SEGMENT boundaries: the device runs a jitted
  ``lax.scan`` of ``segment`` steps, then the host collects the emitted
  block, finishes requests (EOS / budget), and refills free slots by a
  ragged ``prefill`` scattered into the pool. Per-token host round-trips
  would pay a dispatch RTT per token; per-segment sync amortizes it 32x.
* All shapes are bucketed (prompt pad bucket, cache-read bucket, fixed
  segment) so the number of compiled programs is bounded.

Exactness: each request's greedy continuation is token-for-token identical
to running it alone through ``generate_cached`` (tests/test_serving.py).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..core.lod import bucket_length


#: SLO classes a request may declare — the weighted-fair scheduler's queue
#: key (serving/engine.py). "interactive" is the latency class (chat,
#: completions a human is watching); "batch" the throughput class
#: (offline eval, bulk scoring) that yields slots under contention.
SLO_CLASSES = ("interactive", "batch")

#: the bounded-cardinality contract for the ``tenant`` metric label: a
#: short identifier from a closed alphabet (no path separators, no
#: payloads), so per-tenant `serving.*` series stay a bounded enum the
#: L005 lint's value heuristics accept. The engine additionally caps the
#: number of DISTINCT tenants it will mint series for (max_tenants).
TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,31}$")


@dataclass
class Request:
    """One generation request: prompt ids, generation budget, optional EOS
    (generation stops BEFORE emitting eos_id; it is not returned).

    ``tenant``/``slo`` feed multi-tenant scheduling + per-tenant metric
    labels; ``prefix_len`` (optional) declares how many leading prompt
    tokens are a SHARED prefix (a system prompt) — the prefix cache only
    INSERTS blocks inside the declared span, so one-off continuations
    never pollute the radix index (matching is always attempted)."""
    rid: int
    prompt: np.ndarray
    max_new: int
    eos_id: Optional[int] = None
    tenant: str = "default"
    slo: str = "interactive"
    prefix_len: Optional[int] = None


def prefix_resubmission_error(declared, recorded) -> Optional[str]:
    """Replay-hardening shared by engine, daemon and router: a
    router-forwarded RESUBMISSION (same submit_key — a re-route or a
    transport replay) may not declare a ``prefix_len`` exceeding the
    recorded original. An inflated declaration would cache request-unique
    continuation tokens as a "shared" prefix under the original key —
    index poisoning. Returns the structured error string (the
    ``invalid_argument`` body) or None when the declaration is honest."""
    if declared is None:
        return None
    if int(declared) > int(recorded or 0):
        return (f"resubmission declares prefix_len {int(declared)} but the "
                f"recorded original was {int(recorded or 0)} — a forwarded "
                "replay may not inflate its cached-prefix claim "
                "(replay-hardening)")
    return None


def validate_request(r: Request, model, *,
                     max_prefix_len: Optional[int] = None) -> None:
    """Normalize + reject a malformed request AT SUBMIT TIME with a precise
    ValueError — before PR 8 these surfaced as shape errors deep inside the
    ragged prefill (an empty prompt's pos==0 gather wraps; max_new<=0 used
    to idle a slot forever). Mutates ``r.prompt`` to a flat int32 array.
    The paged pool's stronger page-budget check layers on top
    (serving/paged.py PagedBatcher.validate)."""
    r.prompt = np.asarray(r.prompt, np.int32).reshape(-1)
    # engine submissions validate BEFORE a rid exists (placeholder -1);
    # their errors must not name a bogus id to the caller
    who = f"request {r.rid}" if r.rid >= 0 else "request"
    if r.prompt.size == 0:
        # prefill's ragged gather reads logits[b, pos-1]; pos==0 wraps to
        # the last padded position and the "first token" would be silent
        # garbage — exactness demands a real prompt
        raise ValueError(f"{who}: empty prompt (prefill needs at least "
                         "one token)")
    if r.max_new <= 0:
        raise ValueError(f"{who}: max_new must be >= 1, got {r.max_new}")
    if r.prompt.size + 1 > model.max_len:
        raise ValueError(f"{who}: prompt longer than max_len "
                         f"{model.max_len}")
    if not TENANT_RE.match(str(r.tenant)):
        # the tenant value becomes a metric LABEL: an unbounded / path-like
        # value here would mint unbounded series (the L005 cardinality
        # failure mode) — refuse structured at submit, not at scrape
        raise ValueError(
            f"{who}: tenant {str(r.tenant)[:40]!r} violates the bounded-"
            "cardinality label contract (need [A-Za-z0-9][A-Za-z0-9._-]"
            "{0,31})")
    if r.slo not in SLO_CLASSES:
        raise ValueError(f"{who}: unknown slo class {r.slo!r} "
                         f"(one of {SLO_CLASSES})")
    if r.prefix_len is not None:
        if int(r.prefix_len) < 0 or int(r.prefix_len) > r.prompt.size:
            raise ValueError(
                f"{who}: declared prefix_len {r.prefix_len} outside the "
                f"prompt (len {r.prompt.size}) — a shared prefix cannot "
                "be longer than the prompt that carries it")
        r.prefix_len = int(r.prefix_len)
    if max_prefix_len is not None:
        # the resubmission bound (router-forwarded replays): the recorded
        # original caps what this submission may declare
        err = prefix_resubmission_error(r.prefix_len, max_prefix_len)
        if err is not None:
            raise ValueError(f"{who}: {err}")


def clip_emission(row, left: int, eos_id: Optional[int]):
    """Budget-cap + EOS-truncate one slot's emitted token row — the ONE
    owner of the take/done/reason decision every serving loop shares
    (pinned batcher, paged batcher, engine), so the exact-greedy contract
    cannot drift between them. Returns ``(take, done, reason)``; EOS stops
    BEFORE emitting ``eos_id`` (it is never returned)."""
    take = row[:min(int(left), len(row))]
    done, reason = len(take) >= left, "length"
    if eos_id is not None:
        hits = np.nonzero(take == eos_id)[0]
        if hits.size:
            take, done, reason = take[:hits[0]], True, "eos"
    return take, done, reason


@dataclass
class _Slot:
    req: Optional[Request] = None
    left: int = 0
    out: List[int] = field(default_factory=list)


class ContinuousBatcher:
    def __init__(self, model, params, *, slots: int = 8, segment: int = 32,
                 cache_bucket: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128, 256, 512),
                 schedule: str = "longest_first",
                 kv_dtype: Optional[str] = None):
        """``schedule``: admission order over the request queue.
        "longest_first" (default) admits the largest generation budgets
        first — classic longest-processing-time scheduling, which shortens
        the drained-slot tail where short stragglers leave most of the pool
        idle (measured +31% delivered tok/s on a mixed U[32,256] workload
        vs "fifo"). Per-request outputs are identical either way (greedy
        decode is batch-order independent; tests/test_serving.py).

        ``kv_dtype="int8"`` holds the slot pool's KV caches quantized
        (models/transformer.py prefill) — the decode segment's HBM cache
        read halves, which matters exactly here where decode is
        cache-bytes-bound. Tokens then follow the quantized-KV numerics
        contract (docs/design/kernels.md): identical to SOLO decode at the
        same kv_dtype, approximately equal to full-precision decode."""
        if schedule not in ("longest_first", "fifo"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.model, self.params = model, params
        self.n_slots, self.segment = slots, segment
        self.cache_bucket = cache_bucket
        self.prompt_buckets = prompt_buckets
        self.schedule = schedule
        self.kv_dtype = kv_dtype
        self._seg_fns = {}      # cache_len -> jitted segment scan
        self._prefill_fns = {}  # Tpad -> jitted ragged prefill
        self._merge = None      # jitted masked slot merge

    # -- jitted pieces (cached per static shape) ---------------------------
    def _seg_fn(self, cache_len: int):
        fn = self._seg_fns.get(cache_len)
        if fn is None:
            model = self.model

            def seg(params, cell, cur):
                def body(carry, _):
                    cell, cur = carry
                    logits, cell = model.decode_step(params, cell, cur,
                                                     cache_len=cache_len)
                    nxt = jnp.argmax(logits, axis=-1).astype(cur.dtype)
                    return (cell, nxt), cur
                (cell, cur), toks = jax.lax.scan(body, (cell, cur), None,
                                                 length=self.segment)
                return cell, cur, jnp.moveaxis(toks, 0, 1)   # [B, segment]
            fn = self._seg_fns.setdefault(cache_len, jax.jit(seg))
        return fn

    def _prefill_fn(self, tpad: int):
        """Always full-pool-width [slots, tpad]: admissions place each new
        request at ITS slot row (dummies elsewhere), so the only compile
        axis is the prompt pad bucket — never the group size."""
        fn = self._prefill_fns.get(tpad)
        if fn is None:
            model = self.model
            kv_dtype = self.kv_dtype

            def pf(params, prompts, lengths):
                cell, last = model.prefill(params, prompts, lengths,
                                           kv_dtype=kv_dtype)
                first = jnp.argmax(last, axis=-1).astype(prompts.dtype)
                return cell, first
            fn = self._prefill_fns.setdefault(tpad, jax.jit(pf))
        return fn

    def _merge_fn(self):
        if self._merge is None:
            def merge(cell, cur, new_cell, new_cur, mask):
                def mix(old, new):
                    m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
                    return jnp.where(m, new, old)
                cell = {k: mix(v, new_cell[k]) for k, v in cell.items()}
                return cell, jnp.where(mask, new_cur, cur)
            self._merge = jax.jit(merge)
        return self._merge

    # -- the serving loop --------------------------------------------------
    def serve(self, requests: Sequence[Request]) -> Dict[int, np.ndarray]:
        """Run every request to completion; returns {rid: generated ids}.
        Order of completion depends on scheduling; results do not."""
        queue = list(requests)
        for r in queue:
            validate_request(r, self.model)
        if self.schedule == "longest_first":
            # sort by the EFFECTIVE budget (max_len caps it) — the work a
            # slot will actually hold
            queue.sort(key=lambda r: -min(r.max_new,
                                          self.model.max_len - r.prompt.size))
        slots = [_Slot() for _ in range(self.n_slots)]
        results: Dict[int, np.ndarray] = {}

        # device pool: allocate by prefilling a dummy full batch through the
        # JITTED prefill at the smallest prompt bucket — admissions at that
        # bucket reuse the compile, and nothing here runs eagerly (an eager
        # prefill is ~25 dispatch round-trips on a remote-tunnel host)
        tpad0 = min(bucket_length(1, self.prompt_buckets),
                    self.model.max_len - 1)
        dummy = np.zeros((self.n_slots, tpad0), np.int32)
        cell, _ = self._prefill_fn(tpad0)(
            self.params, jnp.asarray(dummy),
            jnp.zeros((self.n_slots,), jnp.int32))
        cur = jnp.zeros((self.n_slots,), jnp.int32)
        pos_host = np.zeros((self.n_slots,), np.int64)

        def admit():
            nonlocal cell, cur
            free = [i for i, s in enumerate(slots) if s.req is None]
            if not queue or not free:
                return
            group = []
            for i in free:
                if not queue:
                    break
                group.append((i, queue.pop(0)))
            tpad = bucket_length(max(r.prompt.size for _, r in group),
                                 self.prompt_buckets)
            tpad = min(tpad, self.model.max_len - 1)
            prompts = np.zeros((self.n_slots, tpad), np.int32)
            lens = np.zeros((self.n_slots,), np.int32)
            mask = np.zeros((self.n_slots,), bool)
            for i, r in group:
                prompts[i, :r.prompt.size] = r.prompt
                lens[i] = r.prompt.size
                mask[i] = True
            new_cell, first = self._prefill_fn(tpad)(
                self.params, jnp.asarray(prompts), jnp.asarray(lens))
            cell, cur = self._merge_fn()(cell, cur, new_cell, first,
                                         jnp.asarray(mask))
            for i, r in group:
                slots[i].req = r
                # the slot emits ``first`` then continues; cap the budget so
                # positions stay inside max_len
                slots[i].left = min(r.max_new,
                                    self.model.max_len - r.prompt.size)
                slots[i].out = []
                pos_host[i] = r.prompt.size

        def park_idle():
            nonlocal cell, cur, pos_host
            idle = [i for i, s in enumerate(slots) if s.req is None
                    and pos_host[i] + 2 * self.segment >= self.model.max_len]
            if idle:
                idx = jnp.asarray(idle, jnp.int32)
                newpos = cell["pos"].at[idx].set(0)
                cell = dict(cell, pos=newpos)
                pos_host[idle] = 0

        admit()
        while any(s.req is not None for s in slots):
            park_idle()
            # cache reads sized to the LIVE slots only: a drained slot
            # decoding garbage at a high position must not drag every
            # sample's HBM reads up (its own out-of-bound mask just reads
            # garbage, which is discarded)
            max_pos = max((int(pos_host[i]) for i, s in enumerate(slots)
                           if s.req is not None), default=0)
            cache_len = min(
                -(-(max_pos + self.segment + 1) // self.cache_bucket)
                * self.cache_bucket, self.model.max_len)
            cell, cur, toks = self._seg_fn(cache_len)(self.params, cell, cur)
            # one dispatch serves `segment` tokens across every live slot
            obs.count("decode.dispatches_total", route="serve_segment")
            pos_host += self.segment
            block = np.asarray(toks)               # [B, segment] host sync
            for i, s in enumerate(slots):
                if s.req is None:
                    continue
                take, done, _ = clip_emission(block[i], s.left,
                                              s.req.eos_id)
                s.out.extend(int(t) for t in take)
                obs.count("decode.tokens_total", len(take), route="serve")
                s.left -= len(take)
                if done:
                    results[s.req.rid] = np.asarray(s.out, np.int32)
                    slots[i] = _Slot()             # free the slot
            admit()
        return results


class SpeculativeDecoder:
    """Speculative greedy decoding: a small DRAFT model proposes ``k-1``
    tokens per round; the target verifies the whole span in ONE batched
    ``verify_step`` pass (models/transformer.py) and emits the longest
    agreeing prefix plus its own correction token.

    Exactness by construction: every emitted token is the target's greedy
    continuation of the emitted prefix — the draft only decides HOW MANY
    tokens each target dispatch yields, never WHICH — so the output equals
    plain greedy decode for ANY acceptance pattern, including an
    adversarial draft that never agrees (tests/test_serving.py). The win
    is dispatch/bytes economics: the target's weights stream once per
    ROUND instead of once per token, amortized over 1 + accepted tokens.

    Rollback rides the existing position-masked cache contract: rejected
    span rows (and the draft's rows for rejected proposals) sit past the
    reset write position, are never readable (mask j <= pos), and are
    overwritten before the position reaches them again — the same
    invariant prefill's ragged tail relies on.

    The draft is any model exposing ``prefill(params, prompt)`` and
    ``decode_step(params, cell, tokens)``; the bench's default is the
    target itself reading an int8 KV cache (a self-speculation draft with
    halved cache bytes and high agreement — docs/design/kernels.md).
    """

    def __init__(self, model, params, draft_model, draft_params, *, k: int = 4,
                 kv_dtype: Optional[str] = None,
                 draft_kv_dtype: Optional[str] = None):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.model, self.params = model, params
        self.draft_model, self.draft_params = draft_model, draft_params
        self.k = k
        self.kv_dtype, self.draft_kv_dtype = kv_dtype, draft_kv_dtype
        draft = draft_model
        dkv = draft_kv_dtype

        def dpf(p, ids):
            cell, last = draft.prefill(p, ids, kv_dtype=dkv) \
                if dkv is not None else draft.prefill(p, ids)
            return cell
        self._draft_prefill = jax.jit(dpf)

        def dstep(p, cell, cur):
            logits, cell = draft.decode_step(p, cell, cur)
            return jnp.argmax(logits, axis=-1).astype(cur.dtype), cell
        self._draft_step = jax.jit(dstep)

    def generate(self, prompt, steps: int) -> Tuple[np.ndarray, Dict]:
        """prompt [B, T0] (or [T0]) -> (tokens [B, steps] int32, stats).
        stats: rounds / proposed / accepted / acceptance_rate — the bench
        row's headline numbers (benchmarks/speculative_decode.py)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        B, T0 = prompt.shape
        if T0 == 0:
            raise ValueError("empty prompt (prefill needs >= 1 token)")
        # frozen samples keep re-writing up to k span rows past their last
        # position, and a final round can overshoot by k-1 — 2k of margin
        # keeps every write inside max_len
        need = T0 + steps + 2 * self.k
        for name, m in (("model", self.model), ("draft", self.draft_model)):
            if need > m.max_len:
                raise ValueError(
                    f"prompt ({T0}) + steps ({steps}) + 2k ({2 * self.k}) "
                    f"exceeds {name} max_len ({m.max_len})")
        ids = jnp.asarray(prompt)
        rng = jax.random.PRNGKey(0)                # greedy: never consumed
        cell, cur, _ = self.model._decode_fn(
            "prefill", kv_dtype=self.kv_dtype, sample="greedy", top_k=None,
            temperature=1.0)(self.params, ids, rng)
        obs.count("decode.dispatches_total", route="spec_prefill")
        dcell = self._draft_prefill(self.draft_params, ids)

        pos = np.full((B,), T0, np.int64)
        # the prefill's greedy token is the first emission; every round
        # then emits the tokens AFTER the current one
        emitted: List[List[int]] = [[int(t)] for t in np.asarray(cur)]
        rounds = proposed = accepted = 0
        verify = self.model._decode_fn("verify", cache_len=None)
        while min(len(e) for e in emitted) < steps:
            # draft proposes k-1 tokens from cur (its positions synced to
            # the target's accepted state), then one cache-fill step
            # consumes the LAST proposal: on a fully-accepted round the
            # next cur sits one past it, so without the fill the draft
            # cache would keep a permanently-live all-zero row at every
            # such round's final position — silently rotting proposal
            # quality (the partial-acceptance rows are overwritten before
            # they become readable, so only the last one needs this)
            dcell = dict(dcell, pos=jnp.asarray(pos, jnp.int32))
            d_cur, props = cur, []
            for i in range(self.k if self.k > 1 else 0):
                d_cur, dcell = self._draft_step(self.draft_params, dcell,
                                                d_cur)
                obs.count("decode.dispatches_total", route="spec_draft")
                if i < self.k - 1:
                    props.append(d_cur)    # the k-th output is discarded
            span = jnp.stack([cur] + props, axis=1)        # [B, k]
            t, cell = verify(self.params, cell, span)      # [B, k] greedy
            obs.count("decode.dispatches_total", route="spec_verify")
            t_np = np.asarray(t)
            props_np = t_np[:, :0] if not props else \
                np.stack([np.asarray(p) for p in props], axis=1)
            next_cur = np.asarray(cur).copy()
            for b in range(B):
                if len(emitted[b]) >= steps:
                    continue                       # frozen: pos/cur hold
                m = 0
                while m < self.k - 1 and props_np[b, m] == t_np[b, m]:
                    m += 1
                emitted[b].extend(int(x) for x in t_np[b, :m + 1])
                next_cur[b] = t_np[b, m]
                pos[b] += m + 1
                proposed += self.k - 1
                accepted += m
            cell = dict(cell, pos=jnp.asarray(pos, jnp.int32))
            cur = jnp.asarray(next_cur)
            rounds += 1
        obs.count("decode.spec_proposed_total", proposed)
        obs.count("decode.spec_accepted_total", accepted)
        obs.count("decode.tokens_total", B * steps, route="spec")
        out = np.asarray([e[:steps] for e in emitted], np.int32)
        return out, {"rounds": rounds, "proposed": proposed,
                     "accepted": accepted,
                     "acceptance_rate": (accepted / proposed if proposed
                                         else 1.0)}
