"""The serving daemon + client — ``paddle_tpu serve`` over the native RPC
plane.

The daemon is a :class:`~paddle_tpu.runtime.master_service.MasterServer`
whose control plane grew three ops (``register_op`` — they ride the
``ptms_set_fallback`` unknown-op path, so the C++ data plane never learns
their payloads):

* ``srv_submit {prompt, max_new, eos_id?, timeout_s?}`` -> ``{rid}``, or a
  STRUCTURED refusal: ``code="overloaded"`` (+ ``retry_after_s``) when the
  admission queue is full — backpressure is a reply, never a dead
  connection — and ``code="invalid_argument"`` for requests the
  validation-hardening layer rejects at submit time;
* ``srv_poll {rid, cursor}`` -> ``{tokens, done, reason}`` — token
  STREAMING is cursor-based polling (tokens materialize at segment
  boundaries, so poll cadence ~ segment cadence loses nothing);
* ``srv_cancel {rid}`` -> frees the request's slot and pages at the next
  segment boundary.

``srv_stats`` rides along for load visibility, and the engine's metric
registry is pushed into the master-side ClusterAggregator (worker label
``serving``) so ``obs_stats`` / ``paddle_tpu obs serve --master`` expose
the TTFT/TPOT histograms exactly like any worker's metrics (PR 4
contract).

Disaggregation (docs/design/serving.md "Disaggregation & routing") adds
two more ops plus a second daemon flavor:

* ``srv_ship_pages {xid, seq, total, data, crc}`` — receive one CRC'd
  chunk of a shipped KV-page payload (serving/ship.py wire contract);
* ``srv_adopt_pages {xid, manifest, max_new, ..., submit_key}`` — verify
  the reassembled shipment and adopt it as a live decode-only request
  (``engine.submit_prefilled``); damaged payloads refuse with
  ``code="data_loss"`` and are NEVER adopted;
* :class:`PrefillDaemon` — a pool-only worker (no decode scheduler) whose
  ``srv_prefill`` admits a prompt, exports the slot's pages, ships them to
  the named decode worker and answers with the DECODE worker's rid.

Both daemons can ``join_router`` a :class:`~.router.ServingRouter`'s
membership table; once joined, every srv_* reply is stamped with the
membership epoch (the ``_RpcClient`` records it, and the final
reconnect error reports how current the client's view was).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import faults, obs
from ..runtime.master_service import MasterServer, _RpcClient
from ..runtime.membership import HeartbeatKeeper, MembershipClient
from ..utils.retry import RetryPolicy
from . import ship as _ship
from .batcher import Request, prefix_resubmission_error
from .engine import Overloaded, ServingEngine

#: ship reassembly buffers a daemon holds at once — a prefill worker that
#: died mid-ship must not leak unbounded half-shipments
_SHIP_CAP = 16


class _RouterMember:
    """Mixin: membership-table residency for a serving-plane daemon.

    ``join_router`` registers the daemon with a router's
    :class:`~..runtime.membership.MembershipService` (caps carry the
    role + this daemon's own RPC address so the router can dial back),
    keeps the lease with a :class:`HeartbeatKeeper`, and tracks the
    latest membership epoch for reply stamping."""

    _epoch: Optional[int] = None
    _keeper: Optional[HeartbeatKeeper] = None
    _mbr_client: Optional[MembershipClient] = None
    _mbr_worker: Optional[str] = None

    def join_router(self, endpoints, worker: str, *,
                    role: str = "decode") -> int:
        """Join the router's membership table; returns the epoch joined
        at. ``endpoints`` is the router address (or failover list)."""
        host, port = self.address
        caps = {"role": role, "rpc_host": host, "rpc_port": int(port)}
        eps = list(endpoints)
        if eps and not isinstance(eps[0], (list, tuple)):
            eps = [tuple(endpoints)]        # a single (host, port) pair
        client = MembershipClient(
            endpoints=[(str(h), int(p)) for h, p in eps])
        token, epoch, reply = client.join(worker, caps)
        self._epoch = epoch
        self._mbr_client = client
        self._mbr_worker = worker
        self._keeper = HeartbeatKeeper(
            client, worker, token, ttl=float(reply.get("ttl", 10.0)),
            epoch=epoch, caps=caps, on_epoch=self._note_epoch).start()
        return epoch

    def _note_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def _leave_router(self) -> None:
        if self._keeper is not None:
            self._keeper.stop()
        if self._mbr_client is not None:
            try:
                if self._keeper is not None:
                    self._mbr_client.leave(self._mbr_worker,
                                           self._keeper.token)
            except Exception:
                pass    # best effort: the lease TTL evicts us anyway
            self._mbr_client.close()
        self._keeper = self._mbr_client = self._mbr_worker = None

    def _stamped(self, fn):
        """Wrap an op handler so its replies carry the membership epoch
        once the daemon joined a router (and never before — a solo
        daemon's replies stay byte-identical to the pre-router wire)."""
        def handler(req):
            resp = fn(req)
            if isinstance(resp, dict) and self._epoch is not None \
                    and "epoch" not in resp:
                resp = dict(resp, epoch=self._epoch)
            return resp
        return handler


def _export_requests(req) -> list:
    """Wire body of the ``srv_requests`` op: this process's recent
    request timelines (empty when the obs plane is off)."""
    led = obs.request_ledger()
    if led is None:
        return []
    try:
        n = int(req.get("n", 128))
    except (TypeError, ValueError):
        n = 128
    return led.export(n=max(1, min(n, 1024)))


class ServingDaemon(_RouterMember):
    """Long-lived serving process: engine + RPC surface + telemetry push.

    ``start()`` registers the srv_* ops, starts the native server and the
    engine's scheduler thread. ``stop(drain_s=N)`` gives in-flight and
    queued requests up to N seconds to finish (and connected clients to
    collect them — ``ptms_active_conns`` is the signal) before tearing
    the server down; the default ``drain_s=0`` stops immediately
    (in-process tests)."""

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0, *, obs_interval_s: float = 1.0):
        self.engine = engine
        self.server = MasterServer(host, port)
        for op, fn in (("srv_submit", self._srv_submit),
                       ("srv_poll", self._srv_poll),
                       ("srv_cancel", self._srv_cancel),
                       ("srv_stats", self._srv_stats),
                       ("srv_requests", self._srv_requests),
                       ("srv_ship_pages", self._srv_ship_pages),
                       ("srv_adopt_pages", self._srv_adopt_pages)):
            self.server.register_op(op, self._stamped(fn))
        # the engine's SLO burn-rate defaults join the aggregator's rule
        # set, so the daemon's own TTFT/TPOT pushes are alertable at the
        # engine's configured targets (obs serve /alerts, obs_health)
        self.server.aggregator.alerts.add_rules(self.engine.alert_rules())
        # per-request timeline capture is always-on whenever the obs
        # plane is (no-op otherwise): engine phases key on submit_key
        obs.ensure_request_ledger()
        self._obs_interval = obs_interval_s
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._obs_thread: Optional[threading.Thread] = None
        # submit idempotency: srv_submit rides the transport's at-least-
        # once retry, so a lost REPLY must not duplicate the admission —
        # replays of a client's submit_key return the original rid
        self._submit_lock = threading.Lock()
        self._submit_seen: "OrderedDict[str, dict]" = OrderedDict()
        # in-flight shipment reassembly (disaggregation receive side)
        self._ship_lock = threading.Lock()
        self._ships: "OrderedDict[str, _ship.ChunkAssembler]" = OrderedDict()

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> "ServingDaemon":
        self.engine.start()
        self.server.start()
        self._obs_thread = threading.Thread(target=self._push_obs,
                                            daemon=True, name="serving-obs")
        self._obs_thread.start()
        return self

    def stop(self, drain_s: float = 0.0) -> None:
        self._draining.set()     # refuse new submissions from here on
        if self._keeper is not None:
            # graceful-drain-before-evict (ISSUE 18): a ROUTED daemon
            # leaves membership FIRST, so the router re-routes in-flight
            # streams and stops placing on us while we drain what's
            # already here — the second _leave_router below is a no-op
            self._leave_router()
        if drain_s > 0:
            # drain: let the scheduler finish live + queued work, then let
            # clients poll the finished results home, all inside one
            # deadline — only then sever connections. The second signal is
            # UNDELIVERED RESULTS, not raw connection count: an idle-but-
            # connected client must not make every shutdown burn the full
            # window (active_connections stays a telemetry signal)
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                st = self.engine.stats()
                if st["slots_live"] == 0 and st["queue_depth"] == 0:
                    break
                time.sleep(0.05)
            while time.monotonic() < deadline \
                    and self.engine.pending_results() > 0:
                # early-out only on an AUTHORITATIVE zero: a stale .so
                # without ptms_active_conns also reads 0, and skipping the
                # collection wait there would sever mid-stream clients
                if self.server.conn_count_supported \
                        and self.server.active_connections() == 0:
                    break
                time.sleep(0.05)
        self._stop.set()
        self._leave_router()
        if self._obs_thread is not None:
            self._obs_thread.join(timeout=5.0)
            self._obs_thread = None
        self.server.stop()
        self.engine.stop()

    # -- telemetry ---------------------------------------------------------
    def _push_obs(self) -> None:
        """Push the installed session's registry into the in-process
        aggregator under worker="serving" — the same snapshots a remote
        worker would obs_push, without a loopback RPC."""
        from ..obs.aggregate import wire_safe_samples
        while not self._stop.wait(self._obs_interval):
            s = obs.session()
            if s is None:
                continue
            try:
                self.server.aggregator.push(
                    "serving", wire_safe_samples(s.registry.collect()))
                led = obs.request_ledger()
                if led is not None:
                    # same loopback: request timelines join the local
                    # aggregator's store for obs_health / /requests
                    self.server.aggregator.push_requests(
                        "serving", led.export(n=256))
            except Exception:
                pass    # telemetry must never take the daemon down

    # -- op handlers (RPC fallback threads) --------------------------------
    def _srv_submit(self, req):
        key = req.get("submit_key")
        if key is None:
            if self._draining.is_set():
                return self._refuse_draining()
            return self._do_submit(req)
        # check + admit + record under ONE lock: a transport-retry replay
        # racing the slow original would otherwise find the cache empty
        # and double-admit. engine.submit is host-side bookkeeping (no
        # device work), so serializing submits here is cheap.
        with self._submit_lock:
            # replay lookup BEFORE the drain gate: a retry of an ALREADY-
            # admitted submit (lost reply) must learn its rid even during
            # shutdown — its result is exactly what the drain window is
            # waiting for the client to collect
            seen = self._submit_seen.get(str(key))
            if seen is not None:
                # replay-hardening (shared with the router): a forwarded
                # resubmission may not inflate its cached-prefix claim
                # past what the recorded original declared — that would
                # poison the radix index with request-unique tokens
                err = prefix_resubmission_error(req.get("prefix_len"),
                                                seen.get("_prefix_len"))
                if err is not None:
                    obs.count("serving.rejected_total",
                              reason="replay_prefix")
                    return {"ok": False, "error": err,
                            "code": "invalid_argument"}
                return {k: v for k, v in seen.items()
                        if not k.startswith("_")}   # replay: same rid
            if self._draining.is_set():
                return self._refuse_draining()
            resp = self._do_submit(req)
            if resp.get("ok"):
                # capacity refusals are NOT remembered: the retry that
                # matters there is the deliberate backoff one (must re-ask)
                cached = dict(resp)
                pfx = req.get("prefix_len")
                cached["_prefix_len"] = None if pfx is None else int(pfx)
                self._submit_seen[str(key)] = cached
                while len(self._submit_seen) > 4096:
                    self._submit_seen.popitem(last=False)
            return resp

    def _refuse_draining(self):
        # shutdown gate: new work is refused structured (clients back
        # off / fail over) so the drain window can actually drain
        obs.count("serving.rejected_total", reason="draining")
        return {"ok": False, "error": "overloaded: daemon is draining "
                "for shutdown", "code": "overloaded",
                "retry_after_s": 2.0}

    def _do_submit(self, req):
        try:
            prompt = np.asarray(req.get("prompt", ()), np.int32)
            max_new = int(req.get("max_new", 0))
            eos = req.get("eos_id")
            timeout = req.get("timeout_s")
            prefix = req.get("prefix_len")
            skey = req.get("submit_key")
            rid = self.engine.submit(
                prompt, max_new, eos_id=None if eos is None else int(eos),
                timeout_s=None if timeout is None else float(timeout),
                tenant=str(req.get("tenant", "default")),
                slo=str(req.get("slo", "interactive")),
                prefix_len=None if prefix is None else int(prefix),
                submit_key=None if skey is None else str(skey))
        except Overloaded as e:
            return {"ok": False, "error": f"overloaded: {e}",
                    "code": "overloaded", "retry_after_s": e.retry_after_s}
        except (ValueError, TypeError, RuntimeError) as e:
            code = ("unavailable" if isinstance(e, RuntimeError)
                    else "invalid_argument")
            return {"ok": False, "error": str(e), "code": code}
        return {"ok": True, "rid": rid}

    def _srv_poll(self, req):
        try:
            rid = int(req["rid"])
            cursor = int(req.get("cursor", 0))
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "error": "srv_poll needs an integer rid "
                    "(+ optional integer cursor)",
                    "code": "invalid_argument"}
        try:
            tokens, done, reason = self.engine.poll(rid, cursor)
        except KeyError:
            return {"ok": False, "error": f"unknown rid {rid}",
                    "code": "not_found"}
        return {"ok": True, "tokens": [int(t) for t in tokens],
                "done": bool(done), "reason": reason}

    def _srv_cancel(self, req):
        try:
            rid = int(req["rid"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "error": "srv_cancel needs an integer rid",
                    "code": "invalid_argument"}
        return {"ok": True, "cancelled": self.engine.cancel(rid)}

    def _srv_stats(self, req):
        stats = self.engine.stats()
        stats["rpc_conns"] = self.server.active_connections()
        stats["role"] = "decode"
        return {"ok": True, **stats}

    def _srv_requests(self, req):
        # the router's scrape pump pulls recent request timelines here so
        # a kill -9'd worker's phases survive on the router's store —
        # re-route stitching depends on it (obs/requests.py)
        return {"ok": True, "requests": _export_requests(req)}

    # -- disaggregation receive side (KV-page adoption) --------------------
    def _srv_ship_pages(self, req):
        try:
            xid = str(req["xid"])
            seq, total = int(req["seq"]), int(req["total"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "code": "invalid_argument",
                    "error": "srv_ship_pages needs xid, seq, total, "
                    "data, crc"}
        try:
            with obs.server_span("srv_ship", req.get("trace"), xid=xid,
                                 seq=seq), self._ship_lock:
                asm = self._ships.get(xid)
                if asm is None:
                    asm = _ship.ChunkAssembler(total)
                    self._ships[xid] = asm
                    while len(self._ships) > _SHIP_CAP:
                        # oldest half-shipment pays for the new one — its
                        # sender died mid-ship or will see data_loss on
                        # adopt and re-ship
                        self._ships.popitem(last=False)
                        obs.count("serving.adopt_refused_total",
                                  reason="evicted")
            asm.add(seq, req.get("data", ""), req.get("crc", -1))
        except _ship.ShipError as e:
            # a damaged chunk poisons the whole shipment: drop the
            # reassembly so a retry starts clean instead of mixing eras
            with self._ship_lock:
                self._ships.pop(xid, None)
            obs.count("serving.adopt_refused_total", reason="chunk")
            return {"ok": False, "code": "data_loss", "error": str(e)}
        return {"ok": True}

    def _srv_adopt_pages(self, req):
        faults.fire("srv.adopt")   # chaos: the decode hop dying mid-adopt
        key = req.get("submit_key")
        xid = str(req.get("xid", ""))
        if key is None:
            if self._draining.is_set():
                return self._refuse_draining()
            with obs.server_span("srv_adopt", req.get("trace"), xid=xid):
                return self._do_adopt(req, xid)
        with self._submit_lock:
            # same idempotency ladder as srv_submit: a replay (lost reply,
            # OR a second prefill worker re-shipping after the first died
            # between adopt and its own reply) answers the ORIGINAL rid —
            # the decode request is never admitted twice
            seen = self._submit_seen.get(str(key))
            if seen is not None:
                with self._ship_lock:
                    self._ships.pop(xid, None)   # replay: payload unused
                return {k: v for k, v in seen.items()
                        if not k.startswith("_")}
            if self._draining.is_set():
                return self._refuse_draining()
            # the named server-side endpoint of the ship→adopt hop: the
            # merged Chrome trace draws its flow arrow into this span
            with obs.server_span("srv_adopt", req.get("trace"), xid=xid,
                                 key=str(key)):
                resp = self._do_adopt(req, xid)
            if resp.get("ok"):
                self._submit_seen[str(key)] = dict(resp, _prefix_len=None)
                while len(self._submit_seen) > 4096:
                    self._submit_seen.popitem(last=False)
            return resp

    def _do_adopt(self, req, xid):
        with self._ship_lock:
            asm = self._ships.get(xid)
        if asm is None:
            obs.count("serving.adopt_refused_total", reason="no_chunks")
            return {"ok": False, "code": "data_loss",
                    "error": f"adopt {xid!r}: no shipped chunks held here "
                    "(lost, expired, or a different worker received them)"}
        manifest = req.get("manifest")
        pool = self.engine.pool
        try:
            payload = asm.payload()
            arrays = _ship.unpack(manifest, payload)
        except _ship.ShipError as e:
            with self._ship_lock:
                self._ships.pop(xid, None)
            obs.count("serving.adopt_refused_total", reason="data_loss")
            return {"ok": False, "code": "data_loss", "error": str(e)}
        if int(manifest.get("page_block", -1)) != pool.bs or \
                str(manifest.get("kv_dtype") or "") != (pool.kv_dtype or ""):
            with self._ship_lock:
                self._ships.pop(xid, None)
            obs.count("serving.adopt_refused_total", reason="geometry")
            return {"ok": False, "code": "invalid_argument",
                    "error": f"shipment geometry (page_block="
                    f"{manifest.get('page_block')}, kv_dtype="
                    f"{manifest.get('kv_dtype') or None!r}) disagrees with "
                    f"this pool (page_block={pool.bs}, kv_dtype="
                    f"{pool.kv_dtype!r}) — prefill and decode pools must "
                    "be built alike"}
        eos = req.get("eos_id")
        timeout = req.get("timeout_s")
        skey = req.get("submit_key")
        try:
            rid = self.engine.submit_prefilled(
                int(manifest["plen"]), int(manifest["first"]), arrays,
                max_new=int(req.get("max_new", 0)),
                eos_id=None if eos is None else int(eos),
                timeout_s=None if timeout is None else float(timeout),
                tenant=str(req.get("tenant", "default")),
                slo=str(req.get("slo", "interactive")),
                submit_key=None if skey is None else str(skey))
        except Overloaded as e:
            # keep the reassembled chunks: the sender's backoff retry
            # re-adopts without re-shipping the payload
            return {"ok": False, "error": f"overloaded: {e}",
                    "code": "overloaded", "retry_after_s": e.retry_after_s}
        except (ValueError, TypeError, RuntimeError) as e:
            with self._ship_lock:
                self._ships.pop(xid, None)
            code = ("unavailable" if isinstance(e, RuntimeError)
                    else "invalid_argument")
            return {"ok": False, "error": str(e), "code": code}
        with self._ship_lock:
            self._ships.pop(xid, None)
        return {"ok": True, "rid": rid, "plen": int(manifest["plen"])}


class PrefillDaemon(_RouterMember):
    """A PREFILL-ONLY serving worker: owns a :class:`~.paged.PagePool`
    (and through it the prefix radix index — re-routes re-prefill here
    near-free) but runs NO decode scheduler. ``srv_prefill`` admits the
    prompt into a scratch slot, exports the slot's KV pages
    (serving/ship.py), frees the slot, ships the chunks to the named
    decode worker and adopts them there — the reply carries the DECODE
    worker's rid, which the caller polls on the decode worker directly.

    Admission is synchronous inside the RPC handler under one pool lock:
    a prefill worker's unit of work IS one admission, so there is nothing
    to schedule between. Idempotent by ``submit_key`` exactly like
    srv_submit, and the same key rides into ``srv_adopt_pages`` — if this
    process dies after the decode worker adopted but before our reply, a
    router retry through ANY prefill worker lands on the decode worker's
    replay cache and learns the original rid (no double admission)."""

    def __init__(self, pool, host: str = "127.0.0.1", port: int = 0):
        self.pool = pool
        self.server = MasterServer(host, port)
        for op, fn in (("srv_prefill", self._srv_prefill),
                       ("srv_stats", self._srv_stats),
                       ("srv_requests", self._srv_requests)):
            self.server.register_op(op, self._stamped(fn))
        obs.ensure_request_ledger()
        self._pool_lock = threading.Lock()
        self._busy: set = set()
        self._submit_lock = threading.Lock()
        self._submit_seen: "OrderedDict[str, dict]" = OrderedDict()
        self._clients_lock = threading.Lock()
        self._clients: Dict[Tuple[str, int], "ServingClient"] = {}

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> "PrefillDaemon":
        self.server.start()
        return self

    def stop(self) -> None:
        self._leave_router()
        self.server.stop()
        with self._clients_lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()

    def _decode_client(self, host: str, port: int) -> "ServingClient":
        with self._clients_lock:
            c = self._clients.get((host, port))
            if c is None:
                c = ServingClient(host, port)
                self._clients[(host, port)] = c
            return c

    # -- op handlers -------------------------------------------------------
    def _srv_stats(self, req):
        with self._pool_lock:
            live = len(self._busy)
        return {"ok": True, "role": "prefill", "slots_live": live,
                "queue_depth": 0,
                "rpc_conns": self.server.active_connections()}

    def _srv_requests(self, req):
        return {"ok": True, "requests": _export_requests(req)}

    def _srv_prefill(self, req):
        key = req.get("submit_key")
        if key is None:
            return self._do_prefill(req, None)
        with self._submit_lock:
            seen = self._submit_seen.get(str(key))
            if seen is not None:
                err = prefix_resubmission_error(req.get("prefix_len"),
                                                seen.get("_prefix_len"))
                if err is not None:
                    obs.count("serving.rejected_total",
                              reason="replay_prefix")
                    return {"ok": False, "error": err,
                            "code": "invalid_argument"}
                return {k: v for k, v in seen.items()
                        if not k.startswith("_")}
            resp = self._do_prefill(req, str(key))
            if resp.get("ok"):
                cached = dict(resp)
                pfx = req.get("prefix_len")
                cached["_prefix_len"] = None if pfx is None else int(pfx)
                self._submit_seen[str(key)] = cached
                while len(self._submit_seen) > 4096:
                    self._submit_seen.popitem(last=False)
            return resp

    def _do_prefill(self, req, key: Optional[str]):
        try:
            prompt = np.asarray(req.get("prompt", ()), np.int32).reshape(-1)
            max_new = int(req.get("max_new", 0))
            decode_host = str(req["decode_host"])
            decode_port = int(req["decode_port"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "code": "invalid_argument",
                    "error": "srv_prefill needs prompt, max_new, "
                    "decode_host, decode_port"}
        eos = req.get("eos_id")
        prefix = req.get("prefix_len")
        r = Request(-1, prompt, max_new,
                    None if eos is None else int(eos),
                    str(req.get("tenant", "default")),
                    str(req.get("slo", "interactive")),
                    None if prefix is None else int(prefix))
        t_pf = time.monotonic()
        try:
            with self._pool_lock:
                self.pool.validate(r)
                left = self.pool.effective_budget(r.prompt.size, max_new)
                plan = self.pool.plan_admission(r.prompt, left,
                                                tenant=r.tenant,
                                                prefix_len=r.prefix_len)
                free = [s for s in range(self.pool.n_slots)
                        if s not in self._busy]
                if not free or not self.pool.evict_for(plan.need_pages, 0,
                                                       protect=[plan]):
                    obs.count("serving.rejected_total", reason="prefill")
                    return {"ok": False, "code": "overloaded",
                            "error": "overloaded: prefill pool cannot "
                            "hold the prompt right now",
                            "retry_after_s": 0.2}
                slot = free[0]
                self._busy.add(slot)
                try:
                    first = int(self.pool.admit([(slot, plan)])[slot])
                    manifest, payload = self.pool.export_slot(slot, first)
                finally:
                    # the slot was only scratch space for the prefill —
                    # its pages return (and the prefix index keeps what
                    # the declared shared span stored)
                    self.pool.free_slot(slot)
                    self._busy.discard(slot)
        except (ValueError, TypeError) as e:
            return {"ok": False, "error": str(e),
                    "code": "invalid_argument"}
        # explicit dur (this worker measured the sub-interval itself): on
        # a shared in-process ledger a telescoped gap would mis-bill the
        # router's forward hop to the prefill phase
        obs.req_phase(key, "prefill", dur=time.monotonic() - t_pf,
                      plen=int(prompt.size), hit=bool(plan.offset > 0))
        # ship + adopt OUTSIDE the pool lock: the wire hop must not
        # serialize other admissions
        client = self._decode_client(decode_host, decode_port)
        xid = uuid.uuid4().hex
        adopt_req = {"op": "srv_adopt_pages", "xid": xid,
                     "manifest": manifest, "max_new": max_new,
                     "tenant": r.tenant, "slo": r.slo}
        if r.eos_id is not None:
            adopt_req["eos_id"] = int(r.eos_id)
        if req.get("timeout_s") is not None:
            adopt_req["timeout_s"] = float(req["timeout_s"])
        if key is not None:
            adopt_req["submit_key"] = key
        t_ship = time.monotonic()
        try:
            # the client-side endpoint of the ship→adopt hop: chunk and
            # adopt RPCs nest under this span, so the merged Chrome trace
            # reads prefill lane → flow arrow → decode lane
            with obs.span("serving.ship", xid=xid,
                          bytes=len(payload), key=key or ""):
                for _seq, _total, frame in _ship.iter_chunks(payload):
                    rc = client._call(dict(frame, op="srv_ship_pages",
                                           xid=xid))
                    if not rc.get("ok"):
                        return {"ok": False,
                                "code": rc.get("code", "data_loss"),
                                "error": f"decode worker refused chunk "
                                f"{_seq}/{_total}: {rc.get('error')}"}
                obs.req_phase(key, "ship",
                              dur=time.monotonic() - t_ship,
                              bytes=len(payload))
                ra = client._call(adopt_req)
        except ConnectionError as e:
            return {"ok": False, "code": "unavailable",
                    "error": f"decode worker {decode_host}:{decode_port} "
                    f"unreachable mid-ship: {e}"}
        if not ra.get("ok"):
            out = {"ok": False, "code": ra.get("code", "unavailable"),
                   "error": str(ra.get("error", "adopt failed"))}
            if ra.get("retry_after_s") is not None:
                out["retry_after_s"] = ra["retry_after_s"]
            return out
        return {"ok": True, "rid": int(ra["rid"]),
                "plen": int(prompt.size), "hit": bool(plan.offset > 0)}


class ServingClient(_RpcClient):
    """Client for the serving daemon. Reuses the runtime's reconnecting
    frame plumbing (per-call deadline, endpoint failover, RetryPolicy on
    transport errors); ADMISSION backpressure is handled one level up —
    ``submit`` surfaces the structured ``overloaded`` reply as
    :class:`Overloaded`, and :meth:`generate`/:meth:`stream` retry it
    through a client-side RetryPolicy honoring the server's
    ``retry_after_s`` hint."""

    _rpc_name = "serving rpc"

    # op names as class attrs so RouterClient (serving/router.py) reuses
    # every method over its route_* surface by overriding four strings
    _op_submit = "srv_submit"
    _op_poll = "srv_poll"
    _op_cancel = "srv_cancel"
    _op_stats = "srv_stats"

    def _conn_err(self, msg: str, attempts: int = 1) -> ConnectionError:
        """Build the connection-class error with the diagnosis an operator
        needs: how hard we tried and how current our membership view was
        when the server went away (``last_epoch`` is stamped from every
        srv_*/route_* reply of a router-joined daemon)."""
        seen = ("unknown" if self.last_epoch is None
                else str(self.last_epoch))
        return ConnectionError(
            f"{msg} (after {int(attempts)} attempt(s); last seen "
            f"membership epoch {seen})")

    def submit(self, prompt, max_new: int, *, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None, tenant: str = "default",
               slo: str = "interactive",
               prefix_len: Optional[int] = None,
               submit_key: Optional[str] = None) -> int:
        # submit_key makes the op idempotent across the transport's
        # at-least-once retry: a lost reply re-sends the SAME key and the
        # daemon answers with the original rid instead of admitting twice
        # (callers pass their own key to make RESUBMISSION idempotent too
        # — the router's re-route ladder). tenant/slo ride the wire into
        # the weighted-fair scheduler and the per-tenant SLO labels;
        # prefix_len declares the shared-prefix span worth caching
        # (docs/design/serving.md)
        req = {"op": self._op_submit,
               "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
               "max_new": int(max_new),
               "submit_key": submit_key or uuid.uuid4().hex}
        if eos_id is not None:
            req["eos_id"] = int(eos_id)
        if timeout_s is not None:
            req["timeout_s"] = float(timeout_s)
        if tenant != "default":
            req["tenant"] = str(tenant)
        if slo != "interactive":
            req["slo"] = str(slo)
        if prefix_len is not None:
            req["prefix_len"] = int(prefix_len)
        r = self._call(req)
        if not r.get("ok"):
            if r.get("code") == "overloaded":
                raise Overloaded(str(r.get("error")),
                                 float(r.get("retry_after_s", 0.2)))
            if r.get("code") == "unavailable":
                # server fault (engine failed/stopped), not a malformed
                # request — surface as the connection-class error callers
                # failover on, never as ValueError
                raise self._conn_err(str(r.get("error", "unavailable")))
            raise ValueError(str(r.get("error", "submit failed")))
        return int(r["rid"])

    def poll(self, rid: int, cursor: int = 0) -> Tuple[List[int], bool, str]:
        r = self._call({"op": self._op_poll, "rid": int(rid),
                        "cursor": int(cursor)})
        if not r.get("ok"):
            raise KeyError(str(r.get("error", "poll failed")))
        return list(r.get("tokens", ())), bool(r.get("done")), \
            str(r.get("reason", ""))

    def cancel(self, rid: int) -> bool:
        r = self._call({"op": self._op_cancel, "rid": int(rid)})
        return bool(r.get("cancelled"))

    def serving_stats(self) -> dict:
        r = self._call({"op": self._op_stats})
        if not r.get("ok"):
            raise self._conn_err(
                str(r.get("error", f"{self._op_stats} failed")))
        return {k: v for k, v in r.items() if k != "ok"}

    def serving_requests(self, n: int = 128) -> list:
        """The worker's recent request timelines (srv_requests) — what
        the router's scrape pump aggregates for stitching."""
        r = self._call({"op": "srv_requests", "n": int(n)})
        if not r.get("ok"):
            raise self._conn_err(
                str(r.get("error", "srv_requests failed")))
        rq = r.get("requests")
        return rq if isinstance(rq, list) else []

    def submit_with_backoff(self, prompt, max_new: int, *,
                            eos_id: Optional[int] = None,
                            timeout_s: Optional[float] = None,
                            tenant: str = "default",
                            slo: str = "interactive",
                            prefix_len: Optional[int] = None,
                            policy: Optional[RetryPolicy] = None,
                            submit_key: Optional[str] = None) -> int:
        """Submit, retrying structured ``overloaded`` refusals — the client
        half of the backpressure contract. Each retry sleeps the LONGER of
        the policy's capped-exponential delay and the server's
        ``retry_after_s`` hint (the server knows its drain rate better
        than our schedule does); the policy supplies the attempt budget
        and the injectable sleep/clock."""
        policy = policy or RetryPolicy(
            max_attempts=16, base_delay=0.1, multiplier=1.5, max_delay=2.0,
            jitter=0.25, retryable=lambda e: isinstance(e, Overloaded))
        attempt = 0
        while True:
            try:
                return self.submit(prompt, max_new, eos_id=eos_id,
                                   timeout_s=timeout_s, tenant=tenant,
                                   slo=slo, prefix_len=prefix_len,
                                   submit_key=submit_key)
            except Overloaded as e:
                attempt += 1
                if policy.max_attempts is not None \
                        and attempt >= policy.max_attempts:
                    raise Overloaded(
                        f"server still overloaded after {attempt} submit "
                        f"attempt(s): {e}") from e
                policy.sleep(max(policy.delay_for(attempt - 1),
                                 e.retry_after_s))

    def stream(self, prompt, max_new: int, *, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None, tenant: str = "default",
               slo: str = "interactive", prefix_len: Optional[int] = None,
               poll_interval_s: float = 0.02,
               policy: Optional[RetryPolicy] = None):
        """Generator: submit (with backpressure backoff) then yield tokens
        as poll exposes them, until the request finishes. Tokens arrive in
        segment-sized bursts — the streaming granularity the decode loop
        actually has."""
        rid = self.submit_with_backoff(prompt, max_new, eos_id=eos_id,
                                       timeout_s=timeout_s, tenant=tenant,
                                       slo=slo, prefix_len=prefix_len,
                                       policy=policy)
        cursor = 0
        finished = False
        try:
            while True:
                tokens, done, reason = self.poll(rid, cursor)
                for t in tokens:
                    yield t
                cursor += len(tokens)
                if done:
                    finished = True
                    # length/eos are the normal completions; an interrupted
                    # request must surface, not read as a short generation
                    if reason == "timeout":
                        raise TimeoutError(
                            f"request {rid} timed out server-side")
                    if reason in ("cancelled", "error"):
                        raise RuntimeError(
                            f"request {rid} ended server-side with reason="
                            f"{reason} after {cursor} token(s)")
                    return
                time.sleep(poll_interval_s)
        finally:
            # an abandoned stream (break / GeneratorExit / error mid-yield)
            # must not keep decoding server-side: the slot and its reserved
            # pages would stay pinned for the full budget or timeout
            if not finished:
                try:
                    self.cancel(rid)
                except Exception:
                    pass    # best effort; the server timeout still bounds it

    def generate(self, prompt, max_new: int, *,
                 eos_id: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 tenant: str = "default", slo: str = "interactive",
                 prefix_len: Optional[int] = None,
                 poll_interval_s: float = 0.02) -> np.ndarray:
        """Blocking convenience: the full generated id array."""
        return np.asarray(list(self.stream(
            prompt, max_new, eos_id=eos_id, timeout_s=timeout_s,
            tenant=tenant, slo=slo, prefix_len=prefix_len,
            poll_interval_s=poll_interval_s)), np.int32)
