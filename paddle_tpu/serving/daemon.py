"""The serving daemon + client — ``paddle_tpu serve`` over the native RPC
plane.

The daemon is a :class:`~paddle_tpu.runtime.master_service.MasterServer`
whose control plane grew three ops (``register_op`` — they ride the
``ptms_set_fallback`` unknown-op path, so the C++ data plane never learns
their payloads):

* ``srv_submit {prompt, max_new, eos_id?, timeout_s?}`` -> ``{rid}``, or a
  STRUCTURED refusal: ``code="overloaded"`` (+ ``retry_after_s``) when the
  admission queue is full — backpressure is a reply, never a dead
  connection — and ``code="invalid_argument"`` for requests the
  validation-hardening layer rejects at submit time;
* ``srv_poll {rid, cursor}`` -> ``{tokens, done, reason}`` — token
  STREAMING is cursor-based polling (tokens materialize at segment
  boundaries, so poll cadence ~ segment cadence loses nothing);
* ``srv_cancel {rid}`` -> frees the request's slot and pages at the next
  segment boundary.

``srv_stats`` rides along for load visibility, and the engine's metric
registry is pushed into the master-side ClusterAggregator (worker label
``serving``) so ``obs_stats`` / ``paddle_tpu obs serve --master`` expose
the TTFT/TPOT histograms exactly like any worker's metrics (PR 4
contract).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np

from .. import obs
from ..runtime.master_service import MasterServer, _RpcClient
from ..utils.retry import RetryPolicy
from .engine import Overloaded, ServingEngine


class ServingDaemon:
    """Long-lived serving process: engine + RPC surface + telemetry push.

    ``start()`` registers the srv_* ops, starts the native server and the
    engine's scheduler thread. ``stop(drain_s=N)`` gives in-flight and
    queued requests up to N seconds to finish (and connected clients to
    collect them — ``ptms_active_conns`` is the signal) before tearing
    the server down; the default ``drain_s=0`` stops immediately
    (in-process tests)."""

    def __init__(self, engine: ServingEngine, host: str = "127.0.0.1",
                 port: int = 0, *, obs_interval_s: float = 1.0):
        self.engine = engine
        self.server = MasterServer(host, port)
        self.server.register_op("srv_submit", self._srv_submit)
        self.server.register_op("srv_poll", self._srv_poll)
        self.server.register_op("srv_cancel", self._srv_cancel)
        self.server.register_op("srv_stats", self._srv_stats)
        # the engine's SLO burn-rate defaults join the aggregator's rule
        # set, so the daemon's own TTFT/TPOT pushes are alertable at the
        # engine's configured targets (obs serve /alerts, obs_health)
        self.server.aggregator.alerts.add_rules(self.engine.alert_rules())
        self._obs_interval = obs_interval_s
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._obs_thread: Optional[threading.Thread] = None
        # submit idempotency: srv_submit rides the transport's at-least-
        # once retry, so a lost REPLY must not duplicate the admission —
        # replays of a client's submit_key return the original rid
        self._submit_lock = threading.Lock()
        self._submit_seen: "OrderedDict[str, dict]" = OrderedDict()

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def start(self) -> "ServingDaemon":
        self.engine.start()
        self.server.start()
        self._obs_thread = threading.Thread(target=self._push_obs,
                                            daemon=True, name="serving-obs")
        self._obs_thread.start()
        return self

    def stop(self, drain_s: float = 0.0) -> None:
        self._draining.set()     # refuse new submissions from here on
        if drain_s > 0:
            # drain: let the scheduler finish live + queued work, then let
            # clients poll the finished results home, all inside one
            # deadline — only then sever connections. The second signal is
            # UNDELIVERED RESULTS, not raw connection count: an idle-but-
            # connected client must not make every shutdown burn the full
            # window (active_connections stays a telemetry signal)
            deadline = time.monotonic() + drain_s
            while time.monotonic() < deadline:
                st = self.engine.stats()
                if st["slots_live"] == 0 and st["queue_depth"] == 0:
                    break
                time.sleep(0.05)
            while time.monotonic() < deadline \
                    and self.engine.pending_results() > 0:
                # early-out only on an AUTHORITATIVE zero: a stale .so
                # without ptms_active_conns also reads 0, and skipping the
                # collection wait there would sever mid-stream clients
                if self.server.conn_count_supported \
                        and self.server.active_connections() == 0:
                    break
                time.sleep(0.05)
        self._stop.set()
        if self._obs_thread is not None:
            self._obs_thread.join(timeout=5.0)
            self._obs_thread = None
        self.server.stop()
        self.engine.stop()

    # -- telemetry ---------------------------------------------------------
    def _push_obs(self) -> None:
        """Push the installed session's registry into the in-process
        aggregator under worker="serving" — the same snapshots a remote
        worker would obs_push, without a loopback RPC."""
        from ..obs.aggregate import wire_safe_samples
        while not self._stop.wait(self._obs_interval):
            s = obs.session()
            if s is None:
                continue
            try:
                self.server.aggregator.push(
                    "serving", wire_safe_samples(s.registry.collect()))
            except Exception:
                pass    # telemetry must never take the daemon down

    # -- op handlers (RPC fallback threads) --------------------------------
    def _srv_submit(self, req):
        key = req.get("submit_key")
        if key is None:
            if self._draining.is_set():
                return self._refuse_draining()
            return self._do_submit(req)
        # check + admit + record under ONE lock: a transport-retry replay
        # racing the slow original would otherwise find the cache empty
        # and double-admit. engine.submit is host-side bookkeeping (no
        # device work), so serializing submits here is cheap.
        with self._submit_lock:
            # replay lookup BEFORE the drain gate: a retry of an ALREADY-
            # admitted submit (lost reply) must learn its rid even during
            # shutdown — its result is exactly what the drain window is
            # waiting for the client to collect
            seen = self._submit_seen.get(str(key))
            if seen is not None:
                return dict(seen)      # replay: same rid
            if self._draining.is_set():
                return self._refuse_draining()
            resp = self._do_submit(req)
            if resp.get("ok"):
                # capacity refusals are NOT remembered: the retry that
                # matters there is the deliberate backoff one (must re-ask)
                self._submit_seen[str(key)] = dict(resp)
                while len(self._submit_seen) > 4096:
                    self._submit_seen.popitem(last=False)
            return resp

    def _refuse_draining(self):
        # shutdown gate: new work is refused structured (clients back
        # off / fail over) so the drain window can actually drain
        obs.count("serving.rejected_total", reason="draining")
        return {"ok": False, "error": "overloaded: daemon is draining "
                "for shutdown", "code": "overloaded",
                "retry_after_s": 2.0}

    def _do_submit(self, req):
        try:
            prompt = np.asarray(req.get("prompt", ()), np.int32)
            max_new = int(req.get("max_new", 0))
            eos = req.get("eos_id")
            timeout = req.get("timeout_s")
            prefix = req.get("prefix_len")
            rid = self.engine.submit(
                prompt, max_new, eos_id=None if eos is None else int(eos),
                timeout_s=None if timeout is None else float(timeout),
                tenant=str(req.get("tenant", "default")),
                slo=str(req.get("slo", "interactive")),
                prefix_len=None if prefix is None else int(prefix))
        except Overloaded as e:
            return {"ok": False, "error": f"overloaded: {e}",
                    "code": "overloaded", "retry_after_s": e.retry_after_s}
        except (ValueError, TypeError, RuntimeError) as e:
            code = ("unavailable" if isinstance(e, RuntimeError)
                    else "invalid_argument")
            return {"ok": False, "error": str(e), "code": code}
        return {"ok": True, "rid": rid}

    def _srv_poll(self, req):
        try:
            rid = int(req["rid"])
            cursor = int(req.get("cursor", 0))
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "error": "srv_poll needs an integer rid "
                    "(+ optional integer cursor)",
                    "code": "invalid_argument"}
        try:
            tokens, done, reason = self.engine.poll(rid, cursor)
        except KeyError:
            return {"ok": False, "error": f"unknown rid {rid}",
                    "code": "not_found"}
        return {"ok": True, "tokens": [int(t) for t in tokens],
                "done": bool(done), "reason": reason}

    def _srv_cancel(self, req):
        try:
            rid = int(req["rid"])
        except (KeyError, TypeError, ValueError):
            return {"ok": False, "error": "srv_cancel needs an integer rid",
                    "code": "invalid_argument"}
        return {"ok": True, "cancelled": self.engine.cancel(rid)}

    def _srv_stats(self, req):
        stats = self.engine.stats()
        stats["rpc_conns"] = self.server.active_connections()
        return {"ok": True, **stats}


class ServingClient(_RpcClient):
    """Client for the serving daemon. Reuses the runtime's reconnecting
    frame plumbing (per-call deadline, endpoint failover, RetryPolicy on
    transport errors); ADMISSION backpressure is handled one level up —
    ``submit`` surfaces the structured ``overloaded`` reply as
    :class:`Overloaded`, and :meth:`generate`/:meth:`stream` retry it
    through a client-side RetryPolicy honoring the server's
    ``retry_after_s`` hint."""

    _rpc_name = "serving rpc"

    def submit(self, prompt, max_new: int, *, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None, tenant: str = "default",
               slo: str = "interactive",
               prefix_len: Optional[int] = None) -> int:
        # submit_key makes the op idempotent across the transport's
        # at-least-once retry: a lost reply re-sends the SAME key and the
        # daemon answers with the original rid instead of admitting twice.
        # tenant/slo ride the wire into the weighted-fair scheduler and
        # the per-tenant SLO labels; prefix_len declares the shared-
        # prefix span worth caching (docs/design/serving.md)
        req = {"op": "srv_submit",
               "prompt": [int(t) for t in np.asarray(prompt).reshape(-1)],
               "max_new": int(max_new),
               "submit_key": uuid.uuid4().hex}
        if eos_id is not None:
            req["eos_id"] = int(eos_id)
        if timeout_s is not None:
            req["timeout_s"] = float(timeout_s)
        if tenant != "default":
            req["tenant"] = str(tenant)
        if slo != "interactive":
            req["slo"] = str(slo)
        if prefix_len is not None:
            req["prefix_len"] = int(prefix_len)
        r = self._call(req)
        if not r.get("ok"):
            if r.get("code") == "overloaded":
                raise Overloaded(str(r.get("error")),
                                 float(r.get("retry_after_s", 0.2)))
            if r.get("code") == "unavailable":
                # server fault (engine failed/stopped), not a malformed
                # request — surface as the connection-class error callers
                # failover on, never as ValueError
                raise ConnectionError(str(r.get("error", "unavailable")))
            raise ValueError(str(r.get("error", "submit failed")))
        return int(r["rid"])

    def poll(self, rid: int, cursor: int = 0) -> Tuple[List[int], bool, str]:
        r = self._call({"op": "srv_poll", "rid": int(rid),
                        "cursor": int(cursor)})
        if not r.get("ok"):
            raise KeyError(str(r.get("error", "poll failed")))
        return list(r.get("tokens", ())), bool(r.get("done")), \
            str(r.get("reason", ""))

    def cancel(self, rid: int) -> bool:
        r = self._call({"op": "srv_cancel", "rid": int(rid)})
        return bool(r.get("cancelled"))

    def serving_stats(self) -> dict:
        r = self._call({"op": "srv_stats"})
        if not r.get("ok"):
            raise ConnectionError(str(r.get("error", "srv_stats failed")))
        return {k: v for k, v in r.items() if k != "ok"}

    def submit_with_backoff(self, prompt, max_new: int, *,
                            eos_id: Optional[int] = None,
                            timeout_s: Optional[float] = None,
                            tenant: str = "default",
                            slo: str = "interactive",
                            prefix_len: Optional[int] = None,
                            policy: Optional[RetryPolicy] = None) -> int:
        """Submit, retrying structured ``overloaded`` refusals — the client
        half of the backpressure contract. Each retry sleeps the LONGER of
        the policy's capped-exponential delay and the server's
        ``retry_after_s`` hint (the server knows its drain rate better
        than our schedule does); the policy supplies the attempt budget
        and the injectable sleep/clock."""
        policy = policy or RetryPolicy(
            max_attempts=16, base_delay=0.1, multiplier=1.5, max_delay=2.0,
            jitter=0.25, retryable=lambda e: isinstance(e, Overloaded))
        attempt = 0
        while True:
            try:
                return self.submit(prompt, max_new, eos_id=eos_id,
                                   timeout_s=timeout_s, tenant=tenant,
                                   slo=slo, prefix_len=prefix_len)
            except Overloaded as e:
                attempt += 1
                if policy.max_attempts is not None \
                        and attempt >= policy.max_attempts:
                    raise Overloaded(
                        f"server still overloaded after {attempt} submit "
                        f"attempt(s): {e}") from e
                policy.sleep(max(policy.delay_for(attempt - 1),
                                 e.retry_after_s))

    def stream(self, prompt, max_new: int, *, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None, tenant: str = "default",
               slo: str = "interactive", prefix_len: Optional[int] = None,
               poll_interval_s: float = 0.02,
               policy: Optional[RetryPolicy] = None):
        """Generator: submit (with backpressure backoff) then yield tokens
        as poll exposes them, until the request finishes. Tokens arrive in
        segment-sized bursts — the streaming granularity the decode loop
        actually has."""
        rid = self.submit_with_backoff(prompt, max_new, eos_id=eos_id,
                                       timeout_s=timeout_s, tenant=tenant,
                                       slo=slo, prefix_len=prefix_len,
                                       policy=policy)
        cursor = 0
        finished = False
        try:
            while True:
                tokens, done, reason = self.poll(rid, cursor)
                for t in tokens:
                    yield t
                cursor += len(tokens)
                if done:
                    finished = True
                    # length/eos are the normal completions; an interrupted
                    # request must surface, not read as a short generation
                    if reason == "timeout":
                        raise TimeoutError(
                            f"request {rid} timed out server-side")
                    if reason in ("cancelled", "error"):
                        raise RuntimeError(
                            f"request {rid} ended server-side with reason="
                            f"{reason} after {cursor} token(s)")
                    return
                time.sleep(poll_interval_s)
        finally:
            # an abandoned stream (break / GeneratorExit / error mid-yield)
            # must not keep decoding server-side: the slot and its reserved
            # pages would stay pinned for the full budget or timeout
            if not finished:
                try:
                    self.cancel(rid)
                except Exception:
                    pass    # best effort; the server timeout still bounds it

    def generate(self, prompt, max_new: int, *,
                 eos_id: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 tenant: str = "default", slo: str = "interactive",
                 prefix_len: Optional[int] = None,
                 poll_interval_s: float = 0.02) -> np.ndarray:
        """Blocking convenience: the full generated id array."""
        return np.asarray(list(self.stream(
            prompt, max_new, eos_id=eos_id, timeout_s=timeout_s,
            tenant=tenant, slo=slo, prefix_len=prefix_len,
            poll_interval_s=poll_interval_s)), np.int32)
