"""The serving engine — a long-lived continuous-batching scheduler with an
async submit/poll/cancel surface, admission control, and SLO telemetry.

Threading model (the actor discipline): ONE scheduler thread owns every
device dispatch and every :class:`~paddle_tpu.serving.paged.PagePool`
mutation. RPC handler threads (daemon.py) only touch engine records under
``_lock`` — submit appends to the queue, poll reads a token buffer, cancel
marks a flag the scheduler honors at the next segment boundary. Device
work (prefill admission, decode segments) runs OUTSIDE the lock, so a poll
never waits on a dispatch.

The scheduler loop is deliberately split into two phases with no shared
state beyond the pool —

* :meth:`admit_prefill`: queues -> slots (weighted-fair deficit
  scheduling across tenant SLO classes, page-budget check with
  cold-prefix eviction, ragged/suffix prefill, first-token emission,
  TTFT);
* :meth:`decode_segment`: one batched decode dispatch + collection
  (budget/EOS/cancel/timeout finalization, page free);

— the prefill/decode DISAGGREGATION seam: running the two phases on
different workers (prefill nodes shipping pages to decode nodes) changes
the transport between them, not the scheduler contract
(docs/design/serving.md).

Backpressure is structured: a full queue raises :class:`Overloaded`
(carrying ``retry_after_s``), which the daemon answers as a structured
reply and the client retries through the shared RetryPolicy — never a
dead connection.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import obs
from ..obs.goodput import maybe_bucket
from .batcher import SLO_CLASSES, Request, clip_emission
from .paged import PagePool


class Overloaded(RuntimeError):
    """Admission refused for capacity (queue cap) — retryable; the server
    keeps serving. ``retry_after_s`` is the server's backoff hint."""

    def __init__(self, msg: str, retry_after_s: float = 0.2):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class _Rec:
    """One request's lifecycle record (engine-internal)."""

    __slots__ = ("rid", "prompt", "eos_id", "left", "deadline", "t_submit",
                 "t_first", "t_done", "tokens", "done", "reason", "slot",
                 "skip", "cancelled", "collected", "tenant", "slo",
                 "prefix_len", "ship", "key")

    def __init__(self, rid, prompt, left, eos_id, deadline, t_submit,
                 tenant="default", slo="interactive", prefix_len=None):
        self.rid, self.prompt, self.left = rid, prompt, left
        self.eos_id, self.deadline, self.t_submit = eos_id, deadline, t_submit
        self.tenant, self.slo, self.prefix_len = tenant, slo, prefix_len
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        self.tokens: List[int] = []
        self.done = False
        self.reason = ""
        self.slot: Optional[int] = None
        self.skip = 0              # segment tokens already delivered early
        self.cancelled = False
        self.collected = False     # a poll has observed done=True
        #: a shipped admission's payload (disaggregation): dict with plen,
        #: first, arrays, need — consumed (and dropped) at adoption
        self.ship = None
        #: the fabric-wide submit_key this request's timeline records
        #: under (obs/requests.py); None = no timeline (embedded use)
        self.key: Optional[str] = None


class ServingEngine:
    """Continuous-batching scheduler over the paged pool with an async
    request surface. ``start()`` spawns the scheduler thread; in-process
    tests may instead drive :meth:`step` directly (deterministic)."""

    def __init__(self, model, params, *, slots: int = 8, segment: int = 32,
                 page_block: Optional[int] = None,
                 pages: Optional[int] = None,
                 cache_bucket: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 kv_dtype: Optional[str] = None, queue_cap: int = 64,
                 default_timeout_s: Optional[float] = None,
                 prefix_cache: bool = False,
                 class_weights: Optional[Dict[str, float]] = None,
                 max_tenants: int = 32,
                 slo_ttft_s: float = 1.0, slo_tpot_s: float = 0.25,
                 slo_budget: float = 0.1,
                 clock=time.monotonic):
        self.pool = PagePool(model, params, slots=slots, segment=segment,
                             page_block=page_block, pages=pages,
                             cache_bucket=cache_bucket,
                             prompt_buckets=prompt_buckets,
                             kv_dtype=kv_dtype, prefix_cache=prefix_cache)
        self.model = model
        self.queue_cap = queue_cap
        self.default_timeout_s = default_timeout_s
        # weighted-fair deficit scheduling across SLO classes: each class
        # accrues weight-proportional service credit per scheduling round
        # and admission debits the admitted request's token budget, so
        # slots (the decode resource) divide ~weight-proportionally under
        # contention while staying work-conserving when one class idles
        self.class_weights = dict(class_weights
                                  or {"interactive": 4.0, "batch": 1.0})
        for c in SLO_CLASSES:
            self.class_weights.setdefault(c, 1.0)
        for c, w in self.class_weights.items():
            # a zero/negative weight would silently pin that class's
            # deficit balance negative — the INVERSE of the documented
            # QoS intent; refuse structured like every other bad config
            if not (w > 0):
                raise ValueError(
                    f"class_weights[{c!r}] must be > 0, got {w!r}")
        # the bounded-cardinality contract behind the per-tenant metric
        # labels: the engine refuses to mint series for more than
        # max_tenants distinct tenants (structured at submit)
        self.max_tenants = max_tenants
        # SLO targets the default burn-rate alert rules are derived from
        # (obs/alerts.py serving_slo_rules; the daemon registers them on
        # the master aggregator's alert engine)
        if slo_ttft_s <= 0 or slo_tpot_s <= 0:
            raise ValueError("slo_ttft_s / slo_tpot_s must be > 0")
        if not (0.0 < slo_budget < 1.0):
            # fail at the parameter the operator set, not from AlertRule
            # deep inside daemon construction
            raise ValueError(
                f"slo_budget must be in (0, 1), got {slo_budget!r}")
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_tpot_s = float(slo_tpot_s)
        self.slo_budget = float(slo_budget)
        self._tenants = set()
        self._clock = clock
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._queues: Dict[str, List[_Rec]] = {c: [] for c in SLO_CLASSES}
        self._deficit: Dict[str, float] = {c: 0.0 for c in SLO_CLASSES}
        self._live: Dict[int, _Rec] = {}      # slot -> record
        self._recs: Dict[int, _Rec] = {}      # rid -> record (incl. done)
        self._done_order: List[int] = []      # finished rids, oldest first
        self._next_rid = 0
        self._stop = False
        self._failed: Optional[str] = None
        self._thread: Optional[threading.Thread] = None
        # goodput ledger for the scheduler thread (None when the obs
        # plane is off): opened by _run, so in-process tests driving
        # step() directly stay ledger-free and deterministic
        self._gp = None

    # -- client surface (any thread) ---------------------------------------
    def _queue_len_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def submit(self, prompt, max_new: int, *, eos_id: Optional[int] = None,
               timeout_s: Optional[float] = None, tenant: str = "default",
               slo: str = "interactive",
               prefix_len: Optional[int] = None,
               submit_key: Optional[str] = None) -> int:
        """Queue one request; returns its rid. Raises ValueError for a
        malformed/unservable request (structured at submit time — the
        validation-hardening contract, now covering tenant labels, SLO
        classes and declared prefixes) and :class:`Overloaded` when the
        queue cap is reached (backpressure).

        ``tenant`` labels this request's SLO metrics (bounded
        cardinality: charset-validated AND capped at ``max_tenants``
        distinct values per engine); ``slo`` picks the weighted-fair
        scheduling class; ``prefix_len`` declares how many leading prompt
        tokens are a shared prefix worth caching (matching is always
        attempted — the declaration only gates index insertion);
        ``submit_key`` keys this request's phase timeline on the obs
        request ledger (None = record nothing)."""
        r = Request(-1, np.asarray(prompt), int(max_new), eos_id,
                    tenant=str(tenant), slo=str(slo), prefix_len=prefix_len)
        self.pool.validate(r)                  # mutates r.prompt to int32
        left = self.pool.effective_budget(r.prompt.size, r.max_new)
        timeout = timeout_s if timeout_s is not None else \
            self.default_timeout_s
        now = self._clock()
        deadline = None if timeout is None else now + float(timeout)
        with self._lock:
            if self._failed is not None:
                raise RuntimeError(
                    f"serving engine failed and stopped: {self._failed}")
            if (r.tenant not in self._tenants
                    and len(self._tenants) >= self.max_tenants):
                # the other half of the bounded-cardinality contract: a
                # rotating tenant value must not mint unbounded series
                raise ValueError(
                    f"request: tenant {r.tenant!r} would exceed this "
                    f"engine's {self.max_tenants}-tenant label budget "
                    "(bounded-cardinality contract; raise max_tenants or "
                    "reuse a tenant id)")
            if self._queue_len_locked() >= self.queue_cap:
                obs.count("serving.rejected_total", reason="overloaded")
                raise Overloaded(
                    f"queue full ({self.queue_cap} waiting); retry later")
            self._tenants.add(r.tenant)
            rid = self._next_rid
            self._next_rid += 1
            rec = _Rec(rid, r.prompt, left, eos_id, deadline, now,
                       tenant=r.tenant, slo=r.slo, prefix_len=r.prefix_len)
            rec.key = submit_key
            self._recs[rid] = rec
            self._queues[r.slo].append(rec)
            obs.gauge_set("serving.queue_depth", self._queue_len_locked())
            self._wake.notify_all()
        obs.req_phase(submit_key, "admitted", tenant=str(tenant),
                      slo=str(slo))
        return rid

    def submit_prefilled(self, plen: int, first: int, arrays, *,
                         max_new: int, eos_id: Optional[int] = None,
                         timeout_s: Optional[float] = None,
                         tenant: str = "default",
                         slo: str = "interactive",
                         submit_key: Optional[str] = None) -> int:
        """Queue a SHIPPED admission (disaggregation): the prompt was
        prefilled on another worker and arrives as ``arrays`` — the slot's
        page rows for every pool array (serving/ship.py ``unpack`` output)
        — plus the prefill's first generated token. The scheduler adopts
        it into the pool instead of prefilling (admit_prefill's adopt
        branch); from there the record is indistinguishable from a local
        admission: same weighted-fair scheduling, budget/EOS/timeout
        finalization, SLO telemetry and backpressure."""
        plen = int(plen)
        # a placeholder prompt of the shipped length drives the shared
        # validation (length bounds, tenant charset, slo class, the
        # page-budget check) — token VALUES are never needed decode-side
        r = Request(-1, np.zeros(plen, np.int32), int(max_new), eos_id,
                    tenant=str(tenant), slo=str(slo))
        need = self.pool.validate(r)
        # refuse a layout-mismatched shipment HERE (structured, at the
        # wire edge) — not mid-adoption on the scheduler thread
        self.pool.check_shipment(plen, arrays)
        left = self.pool.effective_budget(plen, int(max_new))
        timeout = timeout_s if timeout_s is not None else \
            self.default_timeout_s
        now = self._clock()
        deadline = None if timeout is None else now + float(timeout)
        with self._lock:
            if self._failed is not None:
                raise RuntimeError(
                    f"serving engine failed and stopped: {self._failed}")
            if (r.tenant not in self._tenants
                    and len(self._tenants) >= self.max_tenants):
                raise ValueError(
                    f"request: tenant {r.tenant!r} would exceed this "
                    f"engine's {self.max_tenants}-tenant label budget "
                    "(bounded-cardinality contract; raise max_tenants or "
                    "reuse a tenant id)")
            if self._queue_len_locked() >= self.queue_cap:
                obs.count("serving.rejected_total", reason="overloaded")
                raise Overloaded(
                    f"queue full ({self.queue_cap} waiting); retry later")
            self._tenants.add(r.tenant)
            rid = self._next_rid
            self._next_rid += 1
            rec = _Rec(rid, None, left, eos_id, deadline, now,
                       tenant=r.tenant, slo=r.slo)
            rec.key = submit_key
            rec.ship = {"plen": plen, "first": int(first),
                        "arrays": arrays, "need": need}
            self._recs[rid] = rec
            self._queues[r.slo].append(rec)
            obs.gauge_set("serving.queue_depth", self._queue_len_locked())
            self._wake.notify_all()
        obs.req_phase(submit_key, "admitted", tenant=str(tenant),
                      slo=str(slo), shipped=True)
        return rid

    def poll(self, rid: int, cursor: int = 0):
        """Tokens generated so far from ``cursor`` on: returns
        (tokens list, done, reason). Raises KeyError for an unknown rid.
        A poll that observes done marks the result COLLECTED — only
        collected records are eligible for the done-record purge, so a
        finished result is never dropped before its client has seen it."""
        with self._lock:
            rec = self._recs[rid]
            if rec.done:
                rec.collected = True
            return list(rec.tokens[cursor:]), rec.done, rec.reason

    def pending_results(self) -> int:
        """Finished results no poll has collected yet — the daemon's drain
        signal (live/queued work is a separate, earlier drain phase)."""
        with self._lock:
            return sum(1 for rid in self._done_order
                       if rid in self._recs
                       and not self._recs[rid].collected)

    def cancel(self, rid: int) -> bool:
        """Request cancellation; True if the request was still running (or
        queued). A live slot's pages free at the next segment boundary."""
        with self._lock:
            rec = self._recs.get(rid)
            if rec is None or rec.done:
                return False
            rec.cancelled = True
            queue = self._queues.get(rec.slo, ())
            if rec.slot is None and rec in queue:
                queue.remove(rec)
                self._finalize_locked(rec, "cancelled")
            self._wake.notify_all()
            return True

    def timings(self, rid: int) -> Dict[str, Optional[float]]:
        """Engine-clock timestamps for one request (benches/tests read
        TTFT/TPOT without scraping histograms): t_submit, t_first (None
        until the first token), t_done (None until finalized)."""
        with self._lock:
            rec = self._recs[rid]
            return {"t_submit": rec.t_submit, "t_first": rec.t_first,
                    "t_done": rec.t_done}

    def alert_rules(self):
        """The engine's SLO alert defaults: multi-window burn-rate rules
        over ``serving.ttft_seconds`` / ``serving.tpot_seconds`` at THIS
        engine's configured targets — what the daemon registers on the
        master aggregator's alert engine (docs/design/observability.md
        "Fleet health & alerting")."""
        from ..obs.alerts import serving_slo_rules
        return serving_slo_rules(self.slo_ttft_s, self.slo_tpot_s,
                                 self.slo_budget)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            live = len(self._live)
            queued = self._queue_len_locked()
            per_class = {f"queue_{c}": len(q)
                         for c, q in self._queues.items()}
        pool = self.pool
        out = {"queue_depth": queued, "slots_live": live,
               "slots_total": pool.n_slots,
               "pages_used": pool.pages_used,
               "pages_reserved": pool.reserved,
               "pages_total": pool.capacity_pages,
               "page_block": pool.bs,
               "peak_pages_used": pool.peak_pages_used}
        out.update(per_class)
        out.update(pool.prefix_stats())
        return out

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ServingEngine":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stop = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def _run(self) -> None:
        # goodput window over the scheduler's whole life: device work is
        # the prefill/segment dispatches, the admission-wait below is
        # idle, and goodput.ratio says what fraction of the daemon's wall
        # time the chip was decoding — the serving twin of the trainer's
        # ledger (docs/design/observability.md "Goodput ledger")
        self._gp = obs.goodput.open_ledger("serving")
        try:
            while True:
                with self._lock:
                    while (not self._stop and not self._live
                           and self._queue_len_locked() == 0):
                        self._wake.wait(timeout=1.0)
                    if self._stop:
                        return
                try:
                    self.step()
                except Exception as e:  # a dead scheduler must not look alive
                    self._fail_all(e)
                    return
        finally:
            gp, self._gp = self._gp, None
            if gp is not None:
                gp.close()

    def _fail_all(self, exc: BaseException) -> None:
        """A dispatch blew up (device OOM, a bug in a jitted path). After a
        failed donated call the pool buffers are unreliable, so don't limp:
        finalize EVERY outstanding request with reason="error" (pollers see
        done instead of hanging forever), refuse new submissions with the
        cause, and stop scheduling."""
        import traceback
        traceback.print_exc()
        with self._lock:
            self._failed = f"{type(exc).__name__}: {exc}"
            for queue in self._queues.values():
                for rec in list(queue):
                    self._finalize_locked(rec, "error")
                queue.clear()
            for slot, rec in list(self._live.items()):
                self._release_locked(rec, "error")
            self._set_gauges_locked()

    # -- the scheduler (scheduler thread only) -----------------------------
    def step(self) -> None:
        """One scheduling iteration: reap -> admit/prefill -> decode."""
        self._reap()
        self.admit_prefill()
        if self._live:
            self.decode_segment()

    def _reap(self) -> None:
        """Honor cancels and deadlines at the segment boundary: queued
        victims just finalize; live victims free their slot AND pages
        immediately — mid-flight cancel is a first-class path."""
        now = self._clock()
        with self._lock:
            for queue in self._queues.values():
                for rec in list(queue):
                    if rec.cancelled or (rec.deadline is not None
                                         and now >= rec.deadline):
                        queue.remove(rec)
                        self._finalize_locked(
                            rec, "cancelled" if rec.cancelled else "timeout")
            for slot, rec in list(self._live.items()):
                if rec.cancelled or (rec.deadline is not None
                                     and now >= rec.deadline):
                    self._release_locked(
                        rec, "cancelled" if rec.cancelled else "timeout")
            self._set_gauges_locked()

    def admit_prefill(self) -> int:
        """Phase 1: assign free slots to queued requests by WEIGHTED-FAIR
        DEFICIT scheduling across SLO classes (slots are the decode
        resource — whoever holds one decodes every segment, so slot
        assignment IS the segment scheduler): each class with waiting
        work accrues ``weight * segment`` tokens of service credit per
        round, admission debits the admitted request's token budget, and
        the class with the largest balance goes first. Within a class,
        arrival order holds (FIFO — the latency contract); across
        classes, interactive traffic pre-empts queued batch work at the
        weight ratio without ever idling a slot (work-conserving: credit
        resets while a class has nothing queued, and debt never blocks
        the only nonempty class). A class head that does not fit the page
        budget (even after cold-prefix eviction) blocks only ITS class —
        a huge batch prompt cannot head-of-line-block interactive.

        Then run the batched prefill — full ragged prefill for misses,
        CoW + suffix-only prefill for prefix-cache hits — and emit each
        admission's first token (TTFT stops here). Returns the number
        admitted."""
        with maybe_bucket(self._gp, "host_input"), self._lock:
            group, adopts, members, pending = [], [], [], 0
            busy = set(self._live)
            free_slots = [s for s in range(self.pool.n_slots)
                          if s not in busy]
            quantum = float(self.pool.segment)
            for c in SLO_CLASSES:
                if self._queues[c]:
                    w = self.class_weights[c]
                    self._deficit[c] = min(self._deficit[c] + quantum * w,
                                           8 * quantum * w)
                else:
                    self._deficit[c] = 0.0      # no banking while idle
            blocked = set()
            while free_slots:
                avail = [c for c in SLO_CLASSES
                         if self._queues[c] and c not in blocked]
                if not avail:
                    break
                c = max(avail, key=lambda k: self._deficit[k])
                rec = self._queues[c][0]
                if rec.ship is not None:
                    # a shipped admission owns its worst-case pages like
                    # any other; it just skips the prefill dispatch
                    if not self.pool.evict_for(rec.ship["need"], pending,
                                               protect=[p for _, p
                                                        in group]):
                        blocked.add(c)
                        continue
                    self._queues[c].pop(0)
                    self._deficit[c] -= float(rec.left)
                    pending += rec.ship["need"]
                    slot = free_slots.pop(0)
                    rec.slot = slot
                    self._live[slot] = rec
                    adopts.append((slot, rec))
                    members.append(rec)
                    if rec.key is not None:
                        # queue wait of a shipped admission ends here
                        obs.req_phase(rec.key, "scheduled", slot=slot)
                    continue
                plan = self.pool.plan_admission(
                    rec.prompt, rec.left, tenant=rec.tenant,
                    prefix_len=rec.prefix_len)
                if not self.pool.evict_for(plan.need_pages, pending,
                                           protect=[p for _, p in group]
                                           + [plan]):
                    blocked.add(c)  # pages free at segment boundaries
                    continue
                self._queues[c].pop(0)
                self._deficit[c] -= float(rec.left)
                pending += plan.need_pages
                slot = free_slots.pop(0)
                rec.slot = slot
                self._live[slot] = rec
                group.append((slot, plan))
                members.append(rec)
                if rec.key is not None:
                    obs.req_phase(rec.key, "queued", slot=slot)
        if not group and not adopts:
            return 0
        adopted = {rec.rid for _, rec in adopts}
        with obs.span("serving.prefill", batch=len(group) + len(adopts)), \
                maybe_bucket(self._gp, "device"):
            first = self.pool.admit(group)      # device work, lock released
            for slot, rec in adopts:            # ditto: scheduler thread
                s = rec.ship
                self.pool.adopt_slot(slot, s["plen"], s["first"],
                                     s["arrays"], s["need"])
                first[slot] = s["first"]
                rec.ship = None                 # payload consumed
        now = self._clock()
        with maybe_bucket(self._gp, "host_sync"), self._lock:
            for rec in members:
                # a cancel landing during the prefill only sets the flag
                # (this thread owns finalization); the next _reap honors it
                rec.t_first = now
                obs.observe("serving.ttft_seconds", now - rec.t_submit,
                            tenant=rec.tenant)
                if rec.key is not None:
                    # telescoped dur: device prefill (or local adoption)
                    # wall since the queued/scheduled record above
                    obs.req_phase(rec.key,
                                  "adopt" if rec.rid in adopted
                                  else "prefill")
                    obs.req_phase(rec.key, "first_token",
                                  ttft_s=round(now - rec.t_submit, 6))
                tok = first[rec.slot]
                if rec.eos_id is not None and tok == rec.eos_id:
                    self._release_locked(rec, "eos")
                    continue
                rec.tokens.append(tok)
                obs.count("decode.tokens_total", route="serve")
                rec.left -= 1
                rec.skip = 1        # the next segment re-emits this token
                if rec.left <= 0:
                    self._release_locked(rec, "length")
            self._set_gauges_locked()
        return len(group) + len(adopts)

    def decode_segment(self) -> None:
        """Phase 2: one batched decode dispatch over every live slot, then
        collect tokens / finish requests / return pages."""
        with self._lock:
            live = sorted(self._live)
        if not live:
            return
        with obs.span("serving.segment", live=len(live)), \
                maybe_bucket(self._gp, "device"):
            block = self.pool.run_segment(live)  # device work, lock released
        now = self._clock()
        with maybe_bucket(self._gp, "host_sync"), self._lock:
            for slot in live:
                rec = self._live.get(slot)
                if rec is None or rec.done:
                    continue
                usable = block[slot, rec.skip:]
                rec.skip = 0
                take, done, reason = clip_emission(usable, rec.left,
                                                   rec.eos_id)
                rec.tokens.extend(int(t) for t in take)
                obs.count("decode.tokens_total", len(take), route="serve")
                if rec.key is not None and len(take):
                    # consecutive segments fold into one ledger record
                    obs.req_phase(rec.key, "decode", n=len(take))
                rec.left -= len(take)
                if done:
                    self._release_locked(rec, reason)
            self._set_gauges_locked()

    # -- internals (call with _lock held) ----------------------------------
    def _release_locked(self, rec: _Rec, reason: str) -> None:
        if rec.slot is not None:
            self._live.pop(rec.slot, None)
            self.pool.free_slot(rec.slot)
        self._finalize_locked(rec, reason)

    def _finalize_locked(self, rec: _Rec, reason: str) -> None:
        rec.done, rec.reason = True, reason
        rec.t_done = self._clock()
        obs.count("serving.requests_total", outcome=reason,
                  tenant=rec.tenant)
        if rec.key is not None:
            obs.req_phase(rec.key,
                          "cancel" if reason == "cancelled" else "done",
                          reason=reason, tokens=len(rec.tokens))
        if rec.t_first is not None and len(rec.tokens) > 1:
            # time-per-output-token over the tokens AFTER the first (TTFT
            # owns the first) — the SLO pair dashboards alert on
            obs.observe("serving.tpot_seconds",
                        (rec.t_done - rec.t_first)
                        / (len(rec.tokens) - 1), tenant=rec.tenant)
        self._done_order.append(rec.rid)
        # bound the finished-record memory of a long-lived daemon without
        # dropping results nobody has read: purge COLLECTED records first,
        # and touch uncollected ones only past a hard cap (a client that
        # polls a purged rid gets the same KeyError an unknown rid does)
        cap = max(4 * self.queue_cap, 256)
        while len(self._done_order) > cap:
            victim = next((rid for rid in self._done_order
                           if rid not in self._recs
                           or self._recs[rid].collected), None)
            if victim is None:
                if len(self._done_order) <= 4 * cap:
                    break
                victim = self._done_order[0]
            self._done_order.remove(victim)
            self._recs.pop(victim, None)

    def _set_gauges_locked(self) -> None:
        pool = self.pool
        obs.gauge_set("serving.queue_depth", self._queue_len_locked())
        obs.gauge_set("serving.slots_live", len(self._live))
        obs.gauge_set("serving.pages_used", pool.pages_used)
        obs.gauge_set("serving.pages_reserved", pool.reserved)
        used = pool.pages_used * pool.bs
        obs.gauge_set("serving.page_occupancy",
                      pool.live_tokens(list(self._live)) / used
                      if used else 0.0)
        if pool.index is not None:
            obs.gauge_set("serving.prefix_pages_shared",
                          pool.index.live_pages())
