"""Paged KV-cache — the serving plane's block-pool memory manager.

The pinned batcher (batcher.py) gives every slot a max_len-padded cache row:
a 9-token request in a 1024-position pool pins 1024 rows of HBM for its
whole life. Here the cache is a shared POOL of fixed-size pages
(``page_block`` positions each, vLLM-style) plus a per-slot block table
naming which pages hold positions ``j*bs .. (j+1)*bs-1`` — HBM holds live
tokens instead of padding, mixed-length sessions share one pool, pages
allocate as positions grow, and a finished/cancelled request returns its
pages to the free list immediately.

Invariants the exactness contract rides on:

* live slots never share a page (allocation pops unique pages);
* page 0 is the reserved NULL page: padded block-table entries and
  drained-slot writes land there, and no live read is ever unmasked into
  it (assembled position ``j*bs + r`` of a padded entry is > ``pos``);
* admission RESERVES each request's worst-case page count up front
  (prompt + capped budget + one segment of overshoot), so a live slot can
  never fail a mid-flight allocation — backpressure happens at admission,
  not in the decode loop;
* the paged read (ops/pallas_kernels.paged_decode_attention) shares the
  dense-row masked-softmax formulation, so greedy tokens are bit-equal to
  the pinned pool and to solo decode (tests/test_serving_paged.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..core.lod import bucket_length
from .batcher import Request, clip_emission, validate_request


class PagePool:
    """Device page pools + host page accounting + the jitted admit/segment
    programs. Compile surface is bounded exactly like the pinned batcher:
    one admission program per prompt-pad bucket, one segment program per
    cache-read bucket (in pages)."""

    def __init__(self, model, params, *, slots: int, segment: int = 32,
                 page_block: int = 64, pages: Optional[int] = None,
                 cache_bucket: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128, 256, 512),
                 kv_dtype: Optional[str] = None):
        if model.max_len % page_block:
            raise ValueError(f"page_block {page_block} must divide "
                             f"max_len {model.max_len}")
        if cache_bucket % page_block:
            raise ValueError(f"cache_bucket {cache_bucket} must be a "
                             f"multiple of page_block {page_block}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r}")
        self.model, self.params = model, params
        self.n_slots, self.segment = slots, segment
        self.bs = page_block
        self.cache_bucket = cache_bucket
        self.prompt_buckets = prompt_buckets
        self.kv_dtype = kv_dtype
        self.nb_max = model.max_len // page_block
        # pool sizing: default worst case (every slot at max_len) + null
        # page — callers shrink it for the residency win and let admission
        # control queue what no longer fits
        self.pages = (slots * self.nb_max + 1) if pages is None else pages
        if self.pages < 2:
            raise ValueError("pages must be >= 2 (null page + one live)")
        self.capacity_pages = self.pages - 1
        self.capacity_tokens = self.capacity_pages * self.bs

        H = model.blocks[0].n_heads
        Dh = model.blocks[0].d_head
        dt = jnp.int8 if kv_dtype == "int8" else model._compute_dtype(params)
        pools = {}
        for i in range(len(model.blocks)):
            pools[f"k{i}"] = jnp.zeros((self.pages, self.bs, H, Dh), dt)
            pools[f"v{i}"] = jnp.zeros((self.pages, self.bs, H, Dh), dt)
            if kv_dtype == "int8":
                # scale 1.0 everywhere so dequant of (masked) null/garbage
                # rows stays finite — the prefill padded-scale convention
                pools[f"k{i}_scale"] = jnp.ones((self.pages, self.bs, H),
                                                jnp.float32)
                pools[f"v{i}_scale"] = jnp.ones((self.pages, self.bs, H),
                                                jnp.float32)
        self.pools = pools
        self._H, self._Dh = H, Dh
        self._itemsize = jnp.dtype(dt).itemsize

        # host accounting
        self.free: List[int] = list(range(self.pages - 1, 0, -1))
        self.tables = np.zeros((slots, self.nb_max), np.int32)
        self.pos = np.zeros((slots,), np.int64)
        self.cur = np.zeros((slots,), np.int32)
        self.slot_pages: List[List[int]] = [[] for _ in range(slots)]
        self.slot_reserve = np.zeros((slots,), np.int64)
        self.reserved = 0
        self.peak_pages_used = 0
        # roofline/occupancy tallies (plain host ints — always on, the
        # bench rows read them without an obs session)
        self.segments_total = 0
        self.read_bytes_total = 0
        self.occupancy_num = 0      # live tokens, summed per segment
        self.occupancy_den = 0      # allocated page capacity, ditto
        self._admit_fns = {}        # (tpad, nbp) -> jitted admission
        self._seg_fns = {}          # nb -> jitted segment scan

    # -- accounting --------------------------------------------------------
    @property
    def pages_used(self) -> int:
        return self.capacity_pages - len(self.free)

    def reset_tallies(self) -> None:
        """Zero the always-on measurement tallies (peak pages, segment and
        byte counts, occupancy sums) — benches call this between a warm-up
        pass and the measured pass so warm-up traffic never leaks into the
        reported row."""
        self.peak_pages_used = 0
        self.segments_total = 0
        self.read_bytes_total = 0
        self.occupancy_num = 0
        self.occupancy_den = 0

    def required_pages(self, plen: int, left: int) -> int:
        """Worst-case pages a (prompt, capped budget) request can touch:
        positions up to plen + left - 1 live, plus up to one segment of
        discarded overshoot in its final dispatch, all capped at max_len
        (overshoot past max_len clamps into already-owned pages)."""
        hi = min(plen + left + self.segment - 1, self.model.max_len)
        return -(-hi // self.bs)

    def fits(self, need_pages: int, pending: int = 0) -> bool:
        """Can a request needing ``need_pages`` be admitted? ``pending`` is
        the page count the CURRENT admission wave has already claimed:
        ``reserved`` only updates inside :meth:`admit`, so a wave checking
        each request against the pre-wave value alone would over-commit
        the pool and exhaust the free list mid-decode — exactly the
        failure reservations exist to prevent."""
        return self.reserved + pending + need_pages <= self.capacity_pages

    def effective_budget(self, prompt_len: int, max_new: int) -> int:
        """The max_len-capped token budget a (prompt, max_new) can hold."""
        return min(max_new, self.model.max_len - prompt_len)

    def validate(self, r: Request) -> int:
        """Submit-time validation; returns the request's worst-case page
        need. Raises ValueError for malformed requests AND for requests no
        empty pool could ever hold (the page-budget check)."""
        validate_request(r, self.model)
        need = self.required_pages(
            r.prompt.size, self.effective_budget(r.prompt.size, r.max_new))
        if need > self.capacity_pages:
            who = f"request {r.rid}" if r.rid >= 0 else "request"
            raise ValueError(
                f"{who}: needs {need} pages (prompt "
                f"{r.prompt.size} + budget "
                f"{self.effective_budget(r.prompt.size, r.max_new)} at "
                f"page_block {self.bs}) but the pool holds "
                f"{self.capacity_pages}; shrink max_new or grow pages")
        return need

    def _alloc(self) -> int:
        if not self.free:       # reservation accounting makes this a bug
            raise RuntimeError("page pool exhausted past its reservations")
        page = self.free.pop()
        self.peak_pages_used = max(self.peak_pages_used, self.pages_used)
        return page

    def _ensure(self, slot: int, upto_pos: int) -> None:
        """Grow ``slot``'s table to cover positions < upto_pos."""
        need = -(-min(upto_pos, self.model.max_len) // self.bs)
        pages = self.slot_pages[slot]
        while len(pages) < need:
            self.tables[slot, len(pages)] = self._alloc()
            pages.append(int(self.tables[slot, len(pages)]))

    def free_slot(self, slot: int) -> None:
        """Return every page immediately and park the slot: table -> null
        page, pos -> 0, so its idle decode writes/reads only ever touch
        page 0 (no park_idle dance — pos is host-owned here)."""
        self.free.extend(self.slot_pages[slot])
        self.slot_pages[slot] = []
        self.reserved -= int(self.slot_reserve[slot])
        self.slot_reserve[slot] = 0
        self.tables[slot, :] = 0
        self.pos[slot] = 0

    # -- jitted programs ---------------------------------------------------
    def _admit_fn(self, tpad: int, nbp: int):
        fn = self._admit_fns.get((tpad, nbp))
        if fn is None:
            model, kv_dtype, bs = self.model, self.kv_dtype, self.bs
            tpp = nbp * bs

            def admit(params, pools, prompts, lens, pages):
                # pad_to=tpp: the transient cell holds prompt-bucket rows,
                # not a max_len-padded (pinned-pool-sized) cache — the
                # admission HBM spike stays proportional to the prompts
                cell, last = model.prefill(params, prompts, lens,
                                           kv_dtype=kv_dtype,
                                           pad_to=tpp)
                first = jnp.argmax(last, axis=-1).astype(prompts.dtype)
                out = {}
                for i in range(len(model.blocks)):
                    for nm in (f"k{i}", f"v{i}"):
                        rows = cell[nm][:, :tpp].reshape(
                            (prompts.shape[0], nbp, bs) + cell[nm].shape[2:])
                        out[nm] = pools[nm].at[pages].set(
                            rows.astype(pools[nm].dtype))
                    if kv_dtype == "int8":
                        for nm in (f"k{i}_scale", f"v{i}_scale"):
                            rows = cell[nm][:, :tpp].reshape(
                                prompts.shape[0], nbp, bs, -1)
                            out[nm] = pools[nm].at[pages].set(rows)
                return out, first
            fn = jax.jit(admit, donate_argnums=(1,))
            self._admit_fns[(tpad, nbp)] = fn
        return fn

    def _seg_fn(self, nb: int):
        fn = self._seg_fns.get(nb)
        if fn is None:
            model, segment = self.model, self.segment

            def seg(params, pools, tables, pos, cur):
                cell = dict(pools, pos=pos)

                def body(carry, _):
                    cell, cur = carry
                    logits, cell = model.decode_step_paged(params, cell,
                                                           cur, tables)
                    nxt = jnp.argmax(logits, axis=-1).astype(cur.dtype)
                    return (cell, nxt), cur
                (cell, cur), toks = jax.lax.scan(body, (cell, cur), None,
                                                 length=segment)
                pools_out = {k: v for k, v in cell.items() if k != "pos"}
                return pools_out, cur, jnp.moveaxis(toks, 0, 1)
            fn = jax.jit(seg, donate_argnums=(1,))
            self._seg_fns[nb] = fn
        return fn

    # -- the two scheduler-visible operations ------------------------------
    def admit(self, group: List[Tuple[int, np.ndarray, int]]) -> Dict[int, int]:
        """Prefill + page placement for ``group`` = [(slot, prompt, left)]
        (left = the CAPPED token budget). Reserves worst-case pages,
        allocates the prompt's pages, runs ONE full-pool-width jitted
        prefill-and-scatter, and returns {slot: first generated token}.
        Caller has checked :meth:`fits` per request."""
        if not group:
            return {}
        for slot, prompt, left in group:
            need = self.required_pages(prompt.size, left)
            self.slot_reserve[slot] = need
            self.reserved += need
            self._ensure(slot, prompt.size)
        tpad = bucket_length(max(p.size for _, p, _ in group),
                             self.prompt_buckets)
        tpad = min(tpad, self.model.max_len - 1)
        nbp = -(-tpad // self.bs)
        prompts = np.zeros((self.n_slots, tpad), np.int32)
        lens = np.zeros((self.n_slots,), np.int32)
        pages = np.zeros((self.n_slots, nbp), np.int32)
        for slot, prompt, _ in group:
            prompts[slot, :prompt.size] = prompt
            lens[slot] = prompt.size
            n = min(nbp, len(self.slot_pages[slot]))
            pages[slot, :n] = self.slot_pages[slot][:n]
        self.pools, first = self._admit_fn(tpad, nbp)(
            self.params, self.pools, jnp.asarray(prompts), jnp.asarray(lens),
            jnp.asarray(pages))
        first = np.asarray(first)
        out = {}
        for slot, prompt, _ in group:
            self.pos[slot] = prompt.size
            self.cur[slot] = int(first[slot])
            out[slot] = int(first[slot])
        return out

    def run_segment(self, live: Sequence[int]) -> np.ndarray:
        """One decode segment across the whole pool; returns the emitted
        token block [slots, segment] (drained slots' rows are garbage).
        Grows live slots' tables first, so no mid-scan allocation exists."""
        for i in live:
            self._ensure(i, int(self.pos[i]) + self.segment)
        max_pos = max((int(self.pos[i]) for i in live), default=0)
        cache_len = min(
            -(-(max_pos + self.segment + 1) // self.cache_bucket)
            * self.cache_bucket, self.model.max_len)
        nb = cache_len // self.bs
        self.pools, cur, toks = self._seg_fn(nb)(
            self.params, self.pools, jnp.asarray(self.tables[:, :nb]),
            jnp.asarray(self.pos, jnp.int32).clip(0, self.model.max_len - 1),
            jnp.asarray(self.cur))
        obs.count("decode.dispatches_total", route="serve_segment")
        # modeled cache-read bytes through the ONE registered model
        # (ops/pallas_kernels._paged_decode_attention_bytes) — the same
        # resolution the bench rows and the roofline ledger use
        read = obs.roofline.kernel_cost(
            "paged_decode_attention", batch=self.n_slots, pages=nb,
            page_block=self.bs, n_heads=self._H, d_head=self._Dh,
            layers=len(self.model.blocks), kv_dtype=self.kv_dtype,
            itemsize=self._itemsize, steps=self.segment) or 0.0
        obs.count("kernels.bytes_total", read,
                  kernel="paged_decode_attention")
        self.segments_total += 1
        self.read_bytes_total += read
        self.occupancy_num += self.live_tokens(live)
        self.occupancy_den += max(self.pages_used, 1) * self.bs
        self.pos += self.segment
        self.cur = np.array(cur)    # writable copy: admit() merges into it
        return np.asarray(toks)                       # [slots, segment]

    def live_tokens(self, live: Sequence[int]) -> int:
        """Cache rows written across ``live`` slots (occupancy numerator).
        Rows 0..pos-1 exist (each step writes AT pos then advances), so the
        count is pos, capped at max_len where overshoot writes clamp."""
        return int(sum(min(int(self.pos[i]), self.model.max_len)
                       for i in live))


class PagedBatcher:
    """Continuous batching over the paged pool — same serve() contract as
    :class:`~paddle_tpu.serving.batcher.ContinuousBatcher` (greedy outputs
    token-for-token equal to solo decode; schedule is a throughput knob
    only), with cache residency proportional to LIVE tokens instead of
    slots * max_len."""

    def __init__(self, model, params, *, slots: int = 8, segment: int = 32,
                 page_block: int = 64, pages: Optional[int] = None,
                 cache_bucket: int = 256,
                 prompt_buckets: Sequence[int] = (32, 64, 128, 256, 512),
                 schedule: str = "longest_first",
                 kv_dtype: Optional[str] = None):
        if schedule not in ("longest_first", "fifo"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.model, self.params = model, params
        self.schedule = schedule
        self.pool = PagePool(model, params, slots=slots, segment=segment,
                             page_block=page_block, pages=pages,
                             cache_bucket=cache_bucket,
                             prompt_buckets=prompt_buckets,
                             kv_dtype=kv_dtype)

    def _effective_budget(self, r: Request) -> int:
        return self.pool.effective_budget(r.prompt.size, r.max_new)

    def validate(self, r: Request) -> int:
        return self.pool.validate(r)

    def serve(self, requests: Sequence[Request]) -> Dict[int, np.ndarray]:
        pool = self.pool
        queue = list(requests)
        for r in queue:
            self.validate(r)
        if self.schedule == "longest_first":
            queue.sort(key=lambda r: -self._effective_budget(r))
        slots: List[Optional[Request]] = [None] * pool.n_slots
        left = np.zeros((pool.n_slots,), np.int64)
        outs: List[List[int]] = [[] for _ in range(pool.n_slots)]
        results: Dict[int, np.ndarray] = {}

        def admit():
            group, pending = [], 0
            for i in range(pool.n_slots):
                if slots[i] is not None or not queue:
                    continue
                need = pool.required_pages(
                    queue[0].prompt.size, self._effective_budget(queue[0]))
                if not pool.fits(need, pending):
                    break          # head-of-line: wait for pages to free
                pending += need
                r = queue.pop(0)
                slots[i] = r
                left[i] = self._effective_budget(r)
                outs[i] = []
                group.append((i, r.prompt, int(left[i])))
            pool.admit(group)

        admit()
        while any(s is not None for s in slots):
            live = [i for i, s in enumerate(slots) if s is not None]
            block = pool.run_segment(live)
            for i in live:
                r = slots[i]
                take, done, _ = clip_emission(block[i], int(left[i]),
                                              r.eos_id)
                outs[i].extend(int(t) for t in take)
                obs.count("decode.tokens_total", len(take), route="serve")
                left[i] -= len(take)
                if done:
                    results[r.rid] = np.asarray(outs[i], np.int32)
                    slots[i] = None
                    pool.free_slot(i)   # pages return BEFORE next admit
            admit()
        return results
