"""Paged KV-cache — the serving plane's block-pool memory manager.

The pinned batcher (batcher.py) gives every slot a max_len-padded cache row:
a 9-token request in a 1024-position pool pins 1024 rows of HBM for its
whole life. Here the cache is a shared POOL of fixed-size pages
(``page_block`` positions each, vLLM-style) plus a per-slot block table
naming which pages hold positions ``j*bs .. (j+1)*bs-1`` — HBM holds live
tokens instead of padding, mixed-length sessions share one pool, pages
allocate as positions grow, and a finished/cancelled request returns its
pages to the free list immediately.

With ``prefix_cache=True`` the pool additionally shares pages ACROSS
requests through a radix index over prompt prefixes (serving/prefix.py):
a request whose prompt starts with a cached prefix admits with only the
non-shared suffix prefilled (``TransformerLM.prefill_paged`` — prefill
from an offset over pre-populated block tables), full prefix pages are
read in place under refcounts, and the last partial page copies on write
before any append touches it. Cold entries evict by measured reuse.

Invariants the exactness contract rides on:

* live slots never share an OWNED page (allocation pops unique pages);
  index-owned pages are shared read-only and never written after the
  admission wave that populated them;
* page 0 is the reserved NULL page: padded block-table entries and
  drained-slot writes land there, and no live read is ever unmasked into
  it (assembled position ``j*bs + r`` of a padded entry is > ``pos``);
* admission RESERVES each request's worst-case OWNED page count up front
  (prompt + capped budget + one segment of overshoot, minus the shared
  prefix pages), and the fit check counts index-held pages too — so a
  live slot can never fail a mid-flight allocation and a cold cache can
  always be evicted out of the way: backpressure happens at admission,
  not in the decode loop;
* the paged read (ops/pallas_kernels.paged_decode_attention) shares the
  dense-row masked-softmax formulation, so greedy tokens are bit-equal to
  the pinned pool and to solo decode (tests/test_serving_paged.py); the
  suffix-prefill hit path mirrors the same formulation and precision mix
  (tests/test_serving_prefix.py pins parity per interleaving).
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..core.lod import bucket_length
from . import ship
from .batcher import Request, clip_emission, validate_request
from .prefix import Match, PrefixIndex

#: per-model shared jitted-program cache: every PagePool over the same
#: model instance resolves its admit/hit/segment programs here, keyed by
#: the full closure signature (kind, kv_dtype, page size, segment, bucket
#: dims) — pool ARRAYS are call arguments, so pools of any page count
#: share one traced executable per shape family. Weak-keyed: a gc'd model
#: drops its programs with it.
_SHARED_FNS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _shared_fn_cache(model) -> dict:
    d = _SHARED_FNS.get(model)
    if d is None:
        d = _SHARED_FNS[model] = {}
    return d


class _AdmitPlan:
    """One request's host-side admission plan: the prefix-index match (or
    None), the OWNED pages it must reserve, and the labels its metrics
    carry. Computed by :meth:`PagePool.plan_admission` with no pool
    mutation, so schedulers can check :meth:`PagePool.fits` (and evict)
    before committing anything."""

    __slots__ = ("prompt", "left", "plen", "tenant", "prefix_cap",
                 "match", "need_pages", "offset")

    def __init__(self, prompt, left, tenant, prefix_cap, match, need_pages):
        self.prompt = prompt
        self.left = left
        self.plen = int(prompt.size)
        self.tenant = tenant
        self.prefix_cap = prefix_cap
        self.match: Optional[Match] = match
        self.need_pages = need_pages
        self.offset = match.shared_len if match is not None else 0


class PagePool:
    """Device page pools + host page accounting + the jitted admit/segment
    programs. Compile surface is bounded exactly like the pinned batcher:
    one admission program per prompt-pad bucket, one suffix-admission
    program per (suffix-pad, read-pages) bucket pair, one segment program
    per cache-read bucket (in pages)."""

    def __init__(self, model, params, *, slots: int, segment: int = 32,
                 page_block: Optional[int] = None,
                 pages: Optional[int] = None,
                 cache_bucket: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 kv_dtype: Optional[str] = None,
                 prefix_cache: bool = False,
                 prefix_half_life: int = 64):
        if cache_bucket is None or prompt_buckets is None:
            # bucket_grid consult: the measured compile-count-vs-padding
            # winner for this backend, legality-validated by the consult
            # (ascending, ≤ max_len, divisible by an explicit page_block);
            # heuristic grids otherwise. Resolved BEFORE the page_block
            # consult below — its validation needs the real cache_bucket.
            from .. import tune
            if cache_bucket is None:
                grid = tune.bucket_grid("cache", max_len=model.max_len,
                                        divisor=page_block)
                cache_bucket = grid[-1] if grid else 256
            if prompt_buckets is None:
                prompt_buckets = (
                    tune.bucket_grid("prompt", max_len=model.max_len)
                    or (32, 64, 128, 256, 512))
        if page_block is None:
            # autotune consult (paddle_tpu.tune, `paddle_tpu tune`): a
            # measured winner validated against THIS pool's grid
            # (divides max_len and cache_bucket), else the 64 heuristic.
            # Page size changes read geometry only — the assembled row
            # order is identical at any block, so tokens never change
            # (test_serving_paged.py holds paged==solo at page_block=8).
            from .. import tune
            page_block = tune.page_block(model.max_len, cache_bucket) or 64
        if model.max_len % page_block:
            raise ValueError(f"page_block {page_block} must divide "
                             f"max_len {model.max_len}")
        if cache_bucket % page_block:
            raise ValueError(f"cache_bucket {cache_bucket} must be a "
                             f"multiple of page_block {page_block}")
        if kv_dtype not in (None, "int8"):
            raise ValueError(f"unsupported kv_dtype {kv_dtype!r}")
        self.model, self.params = model, params
        self.n_slots, self.segment = slots, segment
        self.bs = page_block
        self.cache_bucket = cache_bucket
        self.prompt_buckets = prompt_buckets
        self.kv_dtype = kv_dtype
        self.nb_max = model.max_len // page_block
        # pool sizing: default worst case (every slot at max_len) + null
        # page — callers shrink it for the residency win and let admission
        # control queue what no longer fits
        self.pages = (slots * self.nb_max + 1) if pages is None else pages
        if self.pages < 2:
            raise ValueError("pages must be >= 2 (null page + one live)")
        self.capacity_pages = self.pages - 1
        self.capacity_tokens = self.capacity_pages * self.bs

        H = model.blocks[0].n_heads
        Dh = model.blocks[0].d_head
        dt = jnp.int8 if kv_dtype == "int8" else model._compute_dtype(params)
        pools = {}
        for i in range(len(model.blocks)):
            pools[f"k{i}"] = jnp.zeros((self.pages, self.bs, H, Dh), dt)
            pools[f"v{i}"] = jnp.zeros((self.pages, self.bs, H, Dh), dt)
            if kv_dtype == "int8":
                # scale 1.0 everywhere so dequant of (masked) null/garbage
                # rows stays finite — the prefill padded-scale convention
                pools[f"k{i}_scale"] = jnp.ones((self.pages, self.bs, H),
                                                jnp.float32)
                pools[f"v{i}_scale"] = jnp.ones((self.pages, self.bs, H),
                                                jnp.float32)
        self.pools = pools
        self._H, self._Dh = H, Dh
        self._itemsize = jnp.dtype(dt).itemsize
        # one (k + v) page in HBM bytes — the prefix index's reuse-ledger
        # credit unit (int8 rows carry a 4-byte scale per (row, head))
        row_b = H * (Dh + 4 if kv_dtype == "int8" else Dh * self._itemsize)
        self.page_bytes = 2.0 * self.bs * row_b * len(model.blocks)
        self.index: Optional[PrefixIndex] = (
            PrefixIndex(self.bs, self.page_bytes,
                        half_life=prefix_half_life)
            if prefix_cache else None)

        # host accounting
        self.free: List[int] = list(range(self.pages - 1, 0, -1))
        self.tables = np.zeros((slots, self.nb_max), np.int32)
        self.pos = np.zeros((slots,), np.int64)
        self.cur = np.zeros((slots,), np.int32)
        self.slot_pages: List[List[int]] = [[] for _ in range(slots)]
        self.slot_shared: List[list] = [[] for _ in range(slots)]
        self.slot_partial: List[Optional[object]] = [None] * slots
        self.slot_reserve = np.zeros((slots,), np.int64)
        self.reserved = 0
        self.peak_pages_used = 0
        # roofline/occupancy tallies (plain host ints — always on, the
        # bench rows read them without an obs session)
        self.segments_total = 0
        self.read_bytes_total = 0
        self.occupancy_num = 0      # live tokens, summed per segment
        self.occupancy_den = 0      # allocated page capacity, ditto
        self.prompt_tokens_total = 0     # tokens ADMITTED (prompt lengths)
        self.prefill_tokens_total = 0    # tokens actually PREFILLED
        self.cow_copies_total = 0        # last-partial-page CoW copies
        self.admit_flops_total = 0.0     # PR 9 cost-ledger FLOPs of the
        #                                  admission dispatches (0 when the
        #                                  obs plane is off)
        # jitted admission/segment programs are shared PER MODEL INSTANCE
        # across pools (keys carry everything else the closures capture:
        # kv_dtype, page size, segment, bucket dims): a rebuilt
        # pool/engine over the same model re-traces nothing, and the test
        # suite's session-shared model turns the paged parity suite's
        # per-test pools into one traced executable per shape family
        self._fns = _shared_fn_cache(model)

    # -- accounting --------------------------------------------------------
    @property
    def pages_used(self) -> int:
        return self.capacity_pages - len(self.free)

    @property
    def index_pages(self) -> int:
        return self.index.total_pages if self.index is not None else 0

    def reset_tallies(self) -> None:
        """Zero the always-on measurement tallies (peak pages, segment and
        byte counts, occupancy sums, prefix/prefill token counts) —
        benches call this between a warm-up pass and the measured pass so
        warm-up traffic never leaks into the reported row."""
        self.peak_pages_used = 0
        self.segments_total = 0
        self.read_bytes_total = 0
        self.occupancy_num = 0
        self.occupancy_den = 0
        self.prompt_tokens_total = 0
        self.prefill_tokens_total = 0
        self.cow_copies_total = 0
        self.admit_flops_total = 0.0
        if self.index is not None:
            self.index.hits = self.index.misses = 0
            self.index.evictions = 0

    def required_pages(self, plen: int, left: int) -> int:
        """Worst-case pages a (prompt, capped budget) request can touch:
        positions up to plen + left - 1 live, plus up to one segment of
        discarded overshoot in its final dispatch, all capped at max_len
        (overshoot past max_len clamps into already-owned pages)."""
        hi = min(plen + left + self.segment - 1, self.model.max_len)
        return -(-hi // self.bs)

    def fits(self, need_pages: int, pending: int = 0) -> bool:
        """Can a request needing ``need_pages`` OWNED pages be admitted?
        ``pending`` is the page count the CURRENT admission wave has
        already claimed: ``reserved`` only updates inside :meth:`admit`,
        so a wave checking each request against the pre-wave value alone
        would over-commit the pool and exhaust the free list mid-decode —
        exactly the failure reservations exist to prevent. Pages held by
        the prefix index count against capacity too (they are not in the
        free list); :meth:`evict_for` reclaims cold ones."""
        return (self.reserved + pending + need_pages + self.index_pages
                <= self.capacity_pages)

    def evict_for(self, need_pages: int, pending: int = 0,
                  protect: Sequence[_AdmitPlan] = ()) -> bool:
        """Evict cold prefix-cache entries (lowest decayed measured-reuse
        score first) until ``need_pages`` fits; True on success. Pinned
        entries never evict, so this cannot steal pages from live
        readers — and ``protect`` (the current admission wave's plans,
        including the one being priced) shields entries a plan has
        MATCHED but not yet pinned: plans pin only inside :meth:`admit`,
        so without the shield a same-wave eviction could free a page a
        block table is about to reference."""
        if self.index is None:
            return self.fits(need_pages, pending)
        keep = set()
        for plan in protect:
            if plan.match is not None:
                keep.update(id(n) for n in plan.match.nodes)
                if plan.match.partial is not None:
                    keep.add(id(plan.match.partial))
        while True:
            deficit = (self.reserved + pending + need_pages
                       + self.index_pages) - self.capacity_pages
            if deficit <= 0:
                return True
            freed = self.index.evict_pages(deficit, keep)
            if not freed:
                return False
            self.free.extend(freed)
            obs.count("serving.prefix_evictions_total", len(freed))

    def clear_prefix_cache(self) -> int:
        """Drop every unpinned prefix-cache entry back to the free list
        (drain / tests); returns the number of pages reclaimed. A drain
        is deliberate, not capacity pressure, so it does not count into
        ``serving.prefix_evictions_total``."""
        if self.index is None:
            return 0
        freed = self.index.clear()
        self.free.extend(freed)
        return len(freed)

    def effective_budget(self, prompt_len: int, max_new: int) -> int:
        """The max_len-capped token budget a (prompt, max_new) can hold."""
        return min(max_new, self.model.max_len - prompt_len)

    def validate(self, r: Request,
                 max_prefix_len: Optional[int] = None) -> int:
        """Submit-time validation; returns the request's worst-case page
        need (prefix hits can only shrink it). Raises ValueError for
        malformed requests AND for requests no empty pool could ever hold
        (the page-budget check). ``max_prefix_len`` passes the recorded
        original of a router-forwarded resubmission through to the
        replay-hardening check (batcher.prefix_resubmission_error)."""
        validate_request(r, self.model, max_prefix_len=max_prefix_len)
        need = self.required_pages(
            r.prompt.size, self.effective_budget(r.prompt.size, r.max_new))
        if need > self.capacity_pages:
            who = f"request {r.rid}" if r.rid >= 0 else "request"
            raise ValueError(
                f"{who}: needs {need} pages (prompt "
                f"{r.prompt.size} + budget "
                f"{self.effective_budget(r.prompt.size, r.max_new)} at "
                f"page_block {self.bs}) but the pool holds "
                f"{self.capacity_pages}; shrink max_new or grow pages")
        return need

    def plan_admission(self, prompt: np.ndarray, left: int, *,
                       tenant: str = "default",
                       prefix_len: Optional[int] = None) -> _AdmitPlan:
        """Match ``prompt`` against the prefix index (read-only — nothing
        is pinned until :meth:`admit` commits the plan) and price the
        admission in OWNED pages. The match is capped at ``plen - 1`` so
        at least one prompt token always re-prefills: the last token's
        logits are the admission's first-token source and logits are not
        cached."""
        plen = int(prompt.size)
        match = None
        if self.index is not None:
            match = self.index.match(prompt, plen - 1)
        shared_full = len(match.nodes) if match is not None else 0
        need = self.required_pages(plen, left) - shared_full
        return _AdmitPlan(prompt, left, tenant, prefix_len, match, need)

    def _alloc(self) -> int:
        if not self.free:       # reservation accounting makes this a bug
            raise RuntimeError("page pool exhausted past its reservations")
        page = self.free.pop()
        self.peak_pages_used = max(self.peak_pages_used, self.pages_used)
        return page

    def _ensure(self, slot: int, upto_pos: int) -> None:
        """Grow ``slot``'s table to cover positions < upto_pos. Shared
        prefix pages occupy the leading table entries; only the tail past
        them allocates."""
        need = -(-min(upto_pos, self.model.max_len) // self.bs)
        have = len(self.slot_shared[slot]) + len(self.slot_pages[slot])
        while have < need:
            page = self._alloc()
            self.tables[slot, have] = page
            self.slot_pages[slot].append(page)
            have += 1

    def free_slot(self, slot: int) -> None:
        """Return every OWNED page immediately, un-pin the shared prefix
        path (refcounts decrement; pages return to the free list only at
        refcount 0 via eviction), hand the last partial prompt page to the
        index (it keys a stored tail), and park the slot: table -> null
        page, pos -> 0, so its idle decode writes/reads only ever touch
        page 0."""
        entry = self.slot_partial[slot]
        pages = self.slot_pages[slot]
        if entry is not None:
            if (self.index is not None
                    and entry.node.partials.get(entry.key) is entry):
                # the index adopts the page: it stays allocated as a cold
                # cached tail instead of returning to the free list
                self.index.adopt(entry)
                pages.remove(entry.page)
            self.slot_partial[slot] = None
        self.free.extend(pages)
        self.slot_pages[slot] = []
        if self.index is not None and self.slot_shared[slot]:
            self.index.release(self.slot_shared[slot])
        self.slot_shared[slot] = []
        self.reserved -= int(self.slot_reserve[slot])
        self.slot_reserve[slot] = 0
        self.tables[slot, :] = 0
        self.pos[slot] = 0

    # -- disaggregation: export / adopt (serving/ship.py) ------------------
    def export_slot(self, slot: int, first: int):
        """Serialize ``slot``'s prefilled page contents for shipping to a
        decode worker's pool: gather the slot's table pages from every
        pool array (k/v per layer + int8 scales) and pack them with the
        request state (``pos``/first token) under a payload CRC. Rows past
        ``pos`` inside the last page are garbage on BOTH ends — the paged
        read masks by ``pos``, so shipping them changes nothing."""
        plen = int(self.pos[slot])
        npg = -(-plen // self.bs)
        pages = jnp.asarray(self.tables[slot, :npg])
        arrays = {nm: np.asarray(arr[pages])
                  for nm, arr in self.pools.items()}
        manifest, payload = ship.pack(arrays, plen=plen, first=first,
                                      page_block=self.bs,
                                      kv_dtype=self.kv_dtype)
        obs.count("serving.ship_pages_total", npg)
        obs.count("serving.ship_bytes_total", len(payload))
        return manifest, payload

    def check_shipment(self, plen: int, arrays: Dict[str, np.ndarray]
                       ) -> None:
        """Validate shipped arrays against THIS pool's layout without
        touching any page. Callable at submit time (the engine's
        ``submit_prefilled``) so a mismatched shipment is a structured
        ValueError refusal at the wire edge, never a scheduler-thread
        death mid-adoption."""
        npg = -(-int(plen) // self.bs)
        missing = set(self.pools) - set(arrays)
        extra = set(arrays) - set(self.pools)
        if missing or extra:
            raise ValueError(
                f"shipped arrays disagree with this pool's layout "
                f"(missing {sorted(missing)}, unexpected {sorted(extra)}) "
                "— prefill and decode pools must share model depth and "
                "kv_dtype")
        for nm, rows in arrays.items():
            ref = self.pools[nm]
            want = (npg,) + tuple(ref.shape[1:])
            if tuple(rows.shape) != want:
                raise ValueError(
                    f"shipped {nm!r} shape {tuple(rows.shape)} != expected "
                    f"{want} (page_block/heads/width mismatch)")
            if np.dtype(rows.dtype) != np.dtype(ref.dtype):
                raise ValueError(
                    f"shipped {nm!r} dtype {rows.dtype} != pool "
                    f"{ref.dtype}; refusing a lossy cast")

    def adopt_slot(self, slot: int, plen: int, first: int,
                   arrays: Dict[str, np.ndarray], need_pages: int) -> None:
        """Land a shipped slot (the decode half of :meth:`export_slot`):
        reserve its worst-case OWNED pages, allocate the table, scatter
        the shipped rows in BYTE-IDENTICAL (dtype-checked — a silent cast
        would break wire-greedy parity), and arm ``pos``/``cur`` so the
        next segment continues exactly where the prefill worker's
        admission stopped. Caller (the engine scheduler) has already
        checked :meth:`fits`/:meth:`evict_for` for ``need_pages``."""
        plen = int(plen)
        npg = -(-plen // self.bs)
        self.check_shipment(plen, arrays)
        self.slot_reserve[slot] = need_pages
        self.reserved += need_pages
        self.slot_shared[slot] = []
        self.slot_partial[slot] = None
        self._ensure(slot, plen)
        pages = jnp.asarray(self.tables[slot, :npg])
        for nm, rows in arrays.items():
            ref = self.pools[nm]
            self.pools[nm] = ref.at[pages].set(
                jnp.asarray(np.ascontiguousarray(rows)))
        self.pos[slot] = plen
        self.cur[slot] = int(first)
        self.prompt_tokens_total += plen
        obs.count("serving.adopted_total")

    # -- jitted programs ---------------------------------------------------
    def _admit_fn(self, tpad: int, nbp: int):
        key = ("admit", self.kv_dtype, self.bs, tpad, nbp)
        fn = self._fns.get(key)
        if fn is None:
            model, kv_dtype, bs = self.model, self.kv_dtype, self.bs
            tpp = nbp * bs

            def admit(params, pools, prompts, lens, pages):
                # pad_to=tpp: the transient cell holds prompt-bucket rows,
                # not a max_len-padded (pinned-pool-sized) cache — the
                # admission HBM spike stays proportional to the prompts
                cell, last = model.prefill(params, prompts, lens,
                                           kv_dtype=kv_dtype,
                                           pad_to=tpp)
                first = jnp.argmax(last, axis=-1).astype(prompts.dtype)
                out = {}
                for i in range(len(model.blocks)):
                    for nm in (f"k{i}", f"v{i}"):
                        rows = cell[nm][:, :tpp].reshape(
                            (prompts.shape[0], nbp, bs) + cell[nm].shape[2:])
                        out[nm] = pools[nm].at[pages].set(
                            rows.astype(pools[nm].dtype))
                    if kv_dtype == "int8":
                        for nm in (f"k{i}_scale", f"v{i}_scale"):
                            rows = cell[nm][:, :tpp].reshape(
                                prompts.shape[0], nbp, bs, -1)
                            out[nm] = pools[nm].at[pages].set(rows)
                return out, first
            # cost-instrumented (PR 9 ledger): under an obs session the
            # dispatch feeds fluid.device_flops_total and admit() reads
            # the per-executable FLOPs into admit_flops_total — the
            # prefill-FLOPs-per-token evidence of the prefix bench row
            fn = obs.roofline.instrument(
                jax.jit(admit, donate_argnums=(1,)), "serving.admit")
            self._fns[key] = fn
        return fn

    def _hit_fn(self, tpad: int, nbr: int):
        """The prefix-HIT admission program: copy-on-write the matched
        partial pages, then prefill only the non-shared suffixes from
        their offsets against the pre-populated block tables
        (models/transformer.py prefill_paged). One compile per
        (suffix-pad, read-pages) bucket pair."""
        key = ("hit", self.kv_dtype, self.bs, tpad, nbr)
        fn = self._fns.get(key)
        if fn is None:
            model = self.model

            def admit_sfx(params, pools, suffix, offsets, lens, tables,
                          copy_src, copy_dst):
                # CoW first: dst pages are freshly-owned copies of the
                # stored partial pages (no-copy slots pass (0, 0) — the
                # null page absorbs the self-copy like any drained write)
                out = {nm: v.at[copy_dst].set(v[copy_src])
                       for nm, v in pools.items()}
                out, last = model.prefill_paged(params, out, suffix,
                                                offsets, lens, tables)
                first = jnp.argmax(last, axis=-1).astype(suffix.dtype)
                return out, first
            fn = obs.roofline.instrument(
                jax.jit(admit_sfx, donate_argnums=(1,)),
                "serving.admit_prefix")
            self._fns[key] = fn
        return fn

    def _seg_fn(self, nb: int):
        key = ("seg", self.kv_dtype, self.bs, self.segment, nb)
        fn = self._fns.get(key)
        if fn is None:
            model, segment = self.model, self.segment

            def seg(params, pools, tables, pos, cur):
                cell = dict(pools, pos=pos)

                def body(carry, _):
                    cell, cur = carry
                    logits, cell = model.decode_step_paged(params, cell,
                                                           cur, tables)
                    nxt = jnp.argmax(logits, axis=-1).astype(cur.dtype)
                    return (cell, nxt), cur
                (cell, cur), toks = jax.lax.scan(body, (cell, cur), None,
                                                 length=segment)
                pools_out = {k: v for k, v in cell.items() if k != "pos"}
                return pools_out, cur, jnp.moveaxis(toks, 0, 1)
            fn = obs.roofline.instrument(
                jax.jit(seg, donate_argnums=(1,)), "serving.segment")
            self._fns[key] = fn
        return fn

    # -- the two scheduler-visible operations ------------------------------
    def admit(self, group: List[Tuple[int, _AdmitPlan]]) -> Dict[int, int]:
        """Commit ``group`` = [(slot, plan)] (plans from
        :meth:`plan_admission`; caller has checked :meth:`fits` /
        :meth:`evict_for` per plan): reserve worst-case OWNED pages, pin
        matched prefix paths, allocate the prompts' tail pages, run the
        full-prefill dispatch for misses and the CoW + suffix-prefill
        dispatch for hits, insert the new full prompt blocks (and the
        last partial page) into the index, and return {slot: first
        generated token}."""
        if not group:
            return {}
        if self.index is not None:
            self.index.tick += 1
        miss: List[Tuple[int, _AdmitPlan]] = []
        hits: List[Tuple[int, _AdmitPlan]] = []
        cow: Dict[int, Tuple[int, int]] = {}      # slot -> (src, dst)
        for slot, plan in group:
            self.slot_reserve[slot] = plan.need_pages
            self.reserved += plan.need_pages
            self.slot_partial[slot] = None
            if plan.match is not None:
                self.index.acquire(plan.match)
                self.slot_shared[slot] = list(plan.match.nodes)
                for j, node in enumerate(plan.match.nodes):
                    self.tables[slot, j] = node.page
                if plan.offset:
                    obs.count("serving.prefix_hits_total",
                              tenant=plan.tenant)
                else:
                    obs.count("serving.prefix_misses_total",
                              tenant=plan.tenant)
            else:
                self.slot_shared[slot] = []
            self._ensure(slot, plan.plen)
            if plan.match is not None and plan.match.partial_len > 0:
                # CoW: the block after the shared full pages is this
                # slot's first OWNED page; the stored tail copies into it
                # before the suffix prefill appends a single row
                dst = self.slot_pages[slot][0]
                cow[slot] = (plan.match.partial.page, dst)
                self.cow_copies_total += 1
            self.prompt_tokens_total += plan.plen
            self.prefill_tokens_total += plan.plen - plan.offset
            (hits if plan.offset else miss).append((slot, plan))

        first = np.zeros((self.n_slots,), np.int32)
        if miss:
            self._dispatch_miss(miss, first)
        if hits:
            self._dispatch_hits(hits, cow, first)
        if self.index is not None:
            for slot, plan in group:
                self._insert_after(slot, plan)
        out = {}
        for slot, plan in group:
            self.pos[slot] = plan.plen
            self.cur[slot] = int(first[slot])
            out[slot] = int(first[slot])
        return out

    def _dispatch_miss(self, miss, first) -> None:
        """The cold path: ONE full-pool-width jitted prefill-and-scatter,
        numerically identical to the pre-prefix-cache admission."""
        tpad = bucket_length(max(p.plen for _, p in miss),
                             self.prompt_buckets)
        tpad = min(tpad, self.model.max_len - 1)
        nbp = -(-tpad // self.bs)
        prompts = np.zeros((self.n_slots, tpad), np.int32)
        lens = np.zeros((self.n_slots,), np.int32)
        pages = np.zeros((self.n_slots, nbp), np.int32)
        for slot, plan in miss:
            prompts[slot, :plan.plen] = plan.prompt
            lens[slot] = plan.plen
            n = min(nbp, len(self.slot_pages[slot]))
            pages[slot, :n] = self.slot_pages[slot][:n]
        fn = self._admit_fn(tpad, nbp)
        args = (self.params, self.pools, jnp.asarray(prompts),
                jnp.asarray(lens), jnp.asarray(pages))
        self.pools, f = fn(*args)
        self._note_admit_cost(fn, args)
        f = np.asarray(f)
        for slot, _ in miss:
            first[slot] = f[slot]

    def _dispatch_hits(self, hits, cow, first) -> None:
        """The warm path: CoW copies + suffix prefill from each slot's
        offset, reading the shared prefix pages through the block table."""
        max_sfx = max(p.plen - p.offset for _, p in hits)
        tpad = min(bucket_length(max_sfx, self.prompt_buckets),
                   self.model.max_len - 1)
        nbr = -(-min(bucket_length(max(p.plen for _, p in hits),
                                   self.prompt_buckets),
                     self.model.max_len) // self.bs)
        suffix = np.zeros((self.n_slots, tpad), np.int32)
        offsets = np.zeros((self.n_slots,), np.int32)
        lens = np.zeros((self.n_slots,), np.int32)
        src = np.zeros((self.n_slots,), np.int32)
        dst = np.zeros((self.n_slots,), np.int32)
        for slot, plan in hits:
            sfx = plan.prompt[plan.offset:]
            suffix[slot, :sfx.size] = sfx
            offsets[slot] = plan.offset
            lens[slot] = sfx.size
            if slot in cow:
                src[slot], dst[slot] = cow[slot]
        fn = self._hit_fn(tpad, nbr)
        args = (self.params, self.pools, jnp.asarray(suffix),
                jnp.asarray(offsets), jnp.asarray(lens),
                jnp.asarray(self.tables[:, :nbr]), jnp.asarray(src),
                jnp.asarray(dst))
        self.pools, f = fn(*args)
        self._note_admit_cost(fn, args)
        # modeled HBM bytes of the gathered prefix read (the hit path's
        # bytes term), through the ONE registered model
        read = obs.roofline.kernel_cost(
            "paged_prefill_attention", batch=self.n_slots, pages=nbr,
            page_block=self.bs, n_heads=self._H, d_head=self._Dh,
            layers=len(self.model.blocks), kv_dtype=self.kv_dtype,
            itemsize=self._itemsize) or 0.0
        obs.count("kernels.bytes_total", read,
                  kernel="paged_prefill_attention")
        f = np.asarray(f)
        for slot, _ in hits:
            first[slot] = f[slot]

    def _note_admit_cost(self, fn, args) -> None:
        """Accumulate the admission executable's FLOPs from the PR 9 cost
        ledger (None while the obs plane is off or analysis failed) —
        benchmarks/serving_prefix.py divides this by prompt tokens for
        its prefill-FLOPs-per-token column."""
        cost = fn.cost_of(*args)
        if cost is not None and cost.flops:
            self.admit_flops_total += cost.flops

    def _insert_after(self, slot: int, plan: _AdmitPlan) -> None:
        """Grow the radix index from this admission: every full prompt
        block past the matched depth becomes a shared node (the slot's
        page transfers to the index, or dedups onto an existing node's
        page), and a partial prompt tail registers for copy-on-write
        sharing. ``prefix_len`` (when declared) caps what is cached so
        unique continuations never pollute the index."""
        idx = self.index
        prompt, plen = plan.prompt, plan.plen
        cap = plen if plan.prefix_cap is None else min(plan.prefix_cap,
                                                       plen)
        q0 = len(plan.match.nodes) if plan.match is not None else 0
        parent = (plan.match.nodes[-1] if plan.match is not None
                  and plan.match.nodes else idx.root)
        kfull = cap // self.bs
        for j in range(q0, kfull):
            page = self.slot_pages[slot].pop(0)
            key = tuple(int(t) for t in prompt[j * self.bs:
                                               (j + 1) * self.bs])
            node, created = idx.insert_full(parent, key, page)
            if created:
                # first use counts as one reuse credit, so a brand-new
                # prefix survives an eviction scan long enough to be hit
                idx._credit(node, idx.page_bytes)
            else:
                # duplicate admission (e.g. two misses sharing a prefix
                # in one wave): keep the existing shared page, free ours
                self.free.append(page)
                self.tables[slot, j] = node.page
            idx.ref(node)
            self.slot_shared[slot].append(node)
            # the page is no longer (to be) owned by the slot
            self.slot_reserve[slot] -= 1
            self.reserved -= 1
            parent = node
        tail = tuple(int(t) for t in prompt[kfull * self.bs:cap])
        if tail and kfull >= q0 and self.slot_pages[slot]:
            entry = idx.insert_partial(parent, tail,
                                       self.slot_pages[slot][0], slot)
            if entry is not None:
                idx._credit(entry, idx.page_bytes * len(tail) / self.bs)
                self.slot_partial[slot] = entry

    def run_segment(self, live: Sequence[int]) -> np.ndarray:
        """One decode segment across the whole pool; returns the emitted
        token block [slots, segment] (drained slots' rows are garbage).
        Grows live slots' tables first, so no mid-scan allocation exists."""
        for i in live:
            self._ensure(i, int(self.pos[i]) + self.segment)
        max_pos = max((int(self.pos[i]) for i in live), default=0)
        cache_len = min(
            -(-(max_pos + self.segment + 1) // self.cache_bucket)
            * self.cache_bucket, self.model.max_len)
        nb = cache_len // self.bs
        self.pools, cur, toks = self._seg_fn(nb)(
            self.params, self.pools, jnp.asarray(self.tables[:, :nb]),
            jnp.asarray(self.pos, jnp.int32).clip(0, self.model.max_len - 1),
            jnp.asarray(self.cur))
        obs.count("decode.dispatches_total", route="serve_segment")
        # modeled cache-read bytes through the ONE registered model
        # (ops/pallas_kernels._paged_decode_attention_bytes) — the same
        # resolution the bench rows and the roofline ledger use
        read = obs.roofline.kernel_cost(
            "paged_decode_attention", batch=self.n_slots, pages=nb,
            page_block=self.bs, n_heads=self._H, d_head=self._Dh,
            layers=len(self.model.blocks), kv_dtype=self.kv_dtype,
            itemsize=self._itemsize, steps=self.segment) or 0.0
        obs.count("kernels.bytes_total", read,
                  kernel="paged_decode_attention")
        self.segments_total += 1
        self.read_bytes_total += read
        self.occupancy_num += self.live_tokens(live)
        self.occupancy_den += max(self.pages_used, 1) * self.bs
        self.pos += self.segment
        self.cur = np.array(cur)    # writable copy: admit() merges into it
        return np.asarray(toks)                       # [slots, segment]

    def live_tokens(self, live: Sequence[int]) -> int:
        """Cache rows written across ``live`` slots (occupancy numerator).
        Rows 0..pos-1 exist (each step writes AT pos then advances), so the
        count is pos, capped at max_len where overshoot writes clamp.
        Shared prefix rows count once per READER (each slot's positions
        include them), so occupancy can legitimately exceed 1.0 under
        prefix sharing — the sharing win made visible."""
        return int(sum(min(int(self.pos[i]), self.model.max_len)
                       for i in live))

    def prefix_stats(self) -> Dict[str, float]:
        """Host tallies for stats()/benches: hit/miss counts, shared and
        cached page counts, prefill-vs-prompt token totals."""
        out = {"prefix_cache": 1.0 if self.index is not None else 0.0,
               "prompt_tokens": self.prompt_tokens_total,
               "prefill_tokens": self.prefill_tokens_total,
               "cow_copies": self.cow_copies_total}
        if self.index is not None:
            out.update(self.index.stats())
        return out


class PagedBatcher:
    """Continuous batching over the paged pool — same serve() contract as
    :class:`~paddle_tpu.serving.batcher.ContinuousBatcher` (greedy outputs
    token-for-token equal to solo decode; schedule is a throughput knob
    only), with cache residency proportional to LIVE tokens instead of
    slots * max_len. ``prefix_cache=True`` turns on cross-request prefix
    sharing (copy-on-write radix index; see :class:`PagePool`)."""

    def __init__(self, model, params, *, slots: int = 8, segment: int = 32,
                 page_block: Optional[int] = None,
                 pages: Optional[int] = None,
                 cache_bucket: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 schedule: str = "longest_first",
                 kv_dtype: Optional[str] = None,
                 prefix_cache: bool = False):
        if schedule not in ("longest_first", "fifo"):
            raise ValueError(f"unknown schedule {schedule!r}")
        self.model, self.params = model, params
        self.schedule = schedule
        self.pool = PagePool(model, params, slots=slots, segment=segment,
                             page_block=page_block, pages=pages,
                             cache_bucket=cache_bucket,
                             prompt_buckets=prompt_buckets,
                             kv_dtype=kv_dtype, prefix_cache=prefix_cache)

    def _effective_budget(self, r: Request) -> int:
        return self.pool.effective_budget(r.prompt.size, r.max_new)

    def validate(self, r: Request) -> int:
        return self.pool.validate(r)

    def serve(self, requests: Sequence[Request]) -> Dict[int, np.ndarray]:
        pool = self.pool
        queue = list(requests)
        for r in queue:
            self.validate(r)
        if self.schedule == "longest_first":
            queue.sort(key=lambda r: -self._effective_budget(r))
        slots: List[Optional[Request]] = [None] * pool.n_slots
        left = np.zeros((pool.n_slots,), np.int64)
        outs: List[List[int]] = [[] for _ in range(pool.n_slots)]
        results: Dict[int, np.ndarray] = {}

        def admit():
            group, pending = [], 0
            for i in range(pool.n_slots):
                if slots[i] is not None or not queue:
                    continue
                r = queue[0]
                plan = pool.plan_admission(
                    r.prompt, self._effective_budget(r), tenant=r.tenant,
                    prefix_len=r.prefix_len)
                if not pool.evict_for(plan.need_pages, pending,
                                      protect=[p for _, p in group]
                                      + [plan]):
                    break          # head-of-line: wait for pages to free
                pending += plan.need_pages
                queue.pop(0)
                slots[i] = r
                left[i] = self._effective_budget(r)
                outs[i] = []
                group.append((i, plan))
            pool.admit(group)

        admit()
        while any(s is not None for s in slots):
            live = [i for i, s in enumerate(slots) if s is not None]
            block = pool.run_segment(live)
            for i in live:
                r = slots[i]
                take, done, _ = clip_emission(block[i], int(left[i]),
                                              r.eos_id)
                outs[i].extend(int(t) for t in take)
                obs.count("decode.tokens_total", len(take), route="serve")
                left[i] -= len(take)
                if done:
                    results[r.rid] = np.asarray(outs[i], np.int32)
                    slots[i] = None
                    pool.free_slot(i)   # pages return BEFORE next admit
            admit()
        return results
