"""Prefix index — the radix trie over the paged KV-cache.

Production traffic is a few thousand system prompts × millions of
continuations: re-prefilling a 2k-token system prompt for every request
burns prefill FLOPs recomputing KV rows the pool already holds. The index
maps shared prompt PREFIXES to refcounted pages at ``block`` (page)
granularity, so a request whose prompt starts with a known prefix admits
with only the non-shared suffix prefilled (serving/paged.py owns the
device side; this module is pure host bookkeeping — no jax).

Sharing rules the exactness contract rides on:

* **full blocks share in place.** A trie node keys one full page of prompt
  tokens (positions ``j*bs .. (j+1)*bs - 1``); its KV rows depend only on
  tokens before the block's end (causality), so any prompt with the same
  token prefix reads the SAME page. Nodes are refcounted: a live request
  pins its matched path; ``release`` decrements, and the page returns to
  the free list only via eviction at refcount 0 — never under a reader.
* **index-owned pages are never written.** Appends happen strictly past a
  request's prompt, which by construction lands in slot-owned pages.
* **the last partial page copies on write.** A prompt tail shorter than a
  block is stored as a *partial* entry; a hit COPIES the page into a
  fresh slot-owned page before any append touches it (the CoW), so the
  stored page stays immutable while its owner keeps appending to it
  (owner appends land at positions >= its own prompt length — rows the
  tail key never covers).

Eviction is scored by MEASURED reuse, not a hand heuristic (the TVM
lesson, PAPERS.md): every hit credits the entry with the bytes it saved
(rows * page row bytes), and the credit decays with a half-life measured
in admission ticks — a once-hot prefix that stopped hitting decays below
a steadily-reused one regardless of insertion order. Only cold leaves
(refcount 0, no children/partials, no live owner) are evictable, so a
pinned path can never be broken mid-read.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class _Entry:
    """Shared bookkeeping of one cached page (full-block node or partial
    tail): the page id, liveness pins, and the measured-reuse ledger."""

    __slots__ = ("page", "refs", "score", "tick", "hits")

    def __init__(self, page: int, tick: int):
        self.page = page
        self.refs = 0          # live requests reading this page
        self.score = 0.0       # decayed bytes-saved credit
        self.tick = tick       # admission tick of the last credit
        self.hits = 0


class _Node(_Entry):
    """One full-block trie node: ``key`` is the page's token tuple."""

    __slots__ = ("key", "parent", "children", "partials")

    def __init__(self, key, page: int, parent, tick: int):
        super().__init__(page, tick)
        self.key = key
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        # partial prompt tails hanging off this depth: tail tokens -> entry
        self.partials: Dict[tuple, "_Partial"] = {}


class _Partial(_Entry):
    """A stored prompt tail shorter than a block. While ``owner`` names a
    live slot the page belongs to that slot (it is still appending past
    its prompt); on slot free the index adopts the page. Hits always COPY
    (never pin), so partials carry no refcount-liveness — only the
    owner-liveness gate."""

    __slots__ = ("key", "node", "owner")

    def __init__(self, key, page: int, node: _Node, owner: Optional[int],
                 tick: int):
        super().__init__(page, tick)
        self.key = key
        self.node = node
        self.owner = owner


class Match:
    """Result of one lookup: the pinned-able full-block path, an optional
    partial-tail entry with its matched token count, and the total shared
    position count (= the admission offset)."""

    __slots__ = ("nodes", "partial", "partial_len", "shared_len")

    def __init__(self, nodes: List[_Node], partial: Optional[_Partial],
                 partial_len: int, block: int):
        self.nodes = nodes
        self.partial = partial
        self.partial_len = partial_len
        self.shared_len = len(nodes) * block + partial_len


class PrefixIndex:
    """The radix index. All methods are host-side and must run under the
    pool owner's single-threaded discipline (the engine's scheduler
    thread / a batcher's serve loop)."""

    def __init__(self, block: int, page_bytes: float, *,
                 half_life: int = 64):
        self.block = block
        self.page_bytes = float(page_bytes)   # reuse-ledger credit unit
        self.half_life = max(int(half_life), 1)
        self.root = _Node((), -1, None, 0)
        self.tick = 0                 # advanced once per admission wave
        # scalar tallies maintained incrementally so telemetry readers
        # (engine gauges under the lock, daemon stats from RPC threads)
        # never WALK the trie the scheduler thread is mutating — a walk
        # mid-insert would raise dictionary-changed-size; int reads are
        # GIL-atomic and an instant-stale value is fine for a gauge
        self.total_pages = 0          # pages the INDEX owns (not slots)
        self.pinned = 0               # nodes with refs > 0 (pages shared)
        self.n_nodes = 0
        self.n_partials = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- lookup ------------------------------------------------------------
    def match(self, tokens: Sequence[int], limit: int) -> Match:
        """Deepest shared prefix of ``tokens`` usable for positions
        ``< limit`` (callers pass ``plen - 1`` so at least one prompt
        token is always re-prefilled — the last token's logits are what
        admission emits, and logits are not cached)."""
        bs = self.block
        node, nodes = self.root, []
        j = 0
        while (j + 1) * bs <= limit:
            child = node.children.get(tuple(int(t) for t in
                                            tokens[j * bs:(j + 1) * bs]))
            if child is None:
                break
            nodes.append(child)
            node, j = child, j + 1
        best, best_m = None, 0
        rest = [int(t) for t in tokens[j * bs:limit]]
        if rest:
            for tail, entry in node.partials.items():
                m = 0
                while m < min(len(tail), len(rest)) and tail[m] == rest[m]:
                    m += 1
                if m > best_m:
                    best, best_m = entry, m
        return Match(nodes, best, best_m, bs)

    def ref(self, node: _Node) -> None:
        """Pin one node (a live request reads its page)."""
        node.refs += 1
        if node.refs == 1:
            self.pinned += 1

    def acquire(self, match: Match) -> None:
        """Pin a matched path for one admitted request and credit the
        reuse ledger: each shared entry earns the bytes this hit did not
        re-prefill (partials credit only the matched rows)."""
        for node in match.nodes:
            self.ref(node)
            self._credit(node, self.page_bytes)
        if match.partial is not None and match.partial_len > 0:
            self._credit(match.partial,
                         self.page_bytes * match.partial_len / self.block)
        if match.shared_len > 0:
            self.hits += 1
        else:
            self.misses += 1

    def release(self, nodes: Sequence[_Node]) -> None:
        """Un-pin a freed request's path. Pages STAY cached (cold) until
        eviction needs them — refcount 0 means evictable, not freed."""
        for node in nodes:
            node.refs -= 1
            assert node.refs >= 0, "prefix-index refcount underflow"
            if node.refs == 0:
                self.pinned -= 1

    # -- insertion ---------------------------------------------------------
    def insert_full(self, parent: _Node, key: tuple,
                    page: int) -> Tuple[_Node, bool]:
        """Insert/find the full-block node for ``key`` under ``parent``
        (the caller walks/extends the path block by block, so the parent
        is always at hand). Returns (node, created): when created, the
        index takes ownership of ``page``; when the key already existed
        (a duplicate admission — e.g. two misses sharing a prefix in one
        wave), the caller keeps ``page``, frees it, and points its block
        table at the existing node's page instead (dedup)."""
        existing = parent.children.get(key)
        if existing is not None:
            return existing, False
        child = _Node(key, page, parent, self.tick)
        parent.children[key] = child
        self.total_pages += 1
        self.n_nodes += 1
        return child, True

    def insert_partial(self, node: _Node, tail: tuple, page: int,
                       owner: int) -> Optional[_Partial]:
        """Register a live slot's last partial prompt page under ``node``.
        The page remains SLOT-owned until :meth:`adopt`; an identical tail
        already present wins (no duplicate entry, returns None)."""
        if not tail or tail in node.partials:
            return None
        entry = _Partial(tail, page, node, owner, self.tick)
        node.partials[tail] = entry
        self.n_partials += 1
        return entry

    def adopt(self, entry: _Partial) -> None:
        """The owning slot freed: the index takes the page (cold)."""
        entry.owner = None
        self.total_pages += 1

    # -- eviction ----------------------------------------------------------
    def _effective(self, e: _Entry) -> float:
        return e.score * 0.5 ** ((self.tick - e.tick) / self.half_life)

    def _credit(self, e: _Entry, saved_bytes: float) -> None:
        e.score = self._effective(e) + saved_bytes
        e.tick = self.tick
        e.hits += 1

    def _candidates(self) -> List[Tuple[float, _Entry, _Node]]:
        out = []
        stack = [self.root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            for p in n.partials.values():
                if p.owner is None:
                    out.append((self._effective(p), p, n))
            if (n is not self.root and n.refs == 0 and not n.children
                    and not n.partials):
                out.append((self._effective(n), n, n.parent))
        return out

    def evict_pages(self, n: int, keep=frozenset(), *,
                    count: bool = True) -> List[int]:
        """Evict up to ``n`` of the coldest evictable entries (lowest
        decayed bytes-saved credit first); returns the freed page ids
        (possibly fewer than ``n`` — everything else is pinned). Only
        leaves evict, so matched paths stay intact. ``keep`` is a set of
        ``id(entry)`` values to skip — the CURRENT admission wave's
        matched-but-not-yet-pinned entries (plans pin only inside
        ``PagePool.admit``, so without this guard an eviction in the same
        wave could free a page a block table is about to reference).
        ``count=False`` suppresses the eviction tally (drains are not
        pressure evictions).

        One candidate walk serves a whole batch; the walk repeats only
        when evicting a leaf turned its parent into a new candidate."""
        freed: List[int] = []
        while len(freed) < n:
            progressed = False
            for _, entry, parent in sorted(self._candidates(),
                                           key=lambda c: c[0]):
                if len(freed) >= n:
                    break
                if id(entry) in keep:
                    continue
                if isinstance(entry, _Partial):
                    del parent.partials[entry.key]
                    self.n_partials -= 1
                else:
                    del parent.children[entry.key]
                    self.n_nodes -= 1
                self.total_pages -= 1
                if count:
                    self.evictions += 1
                freed.append(entry.page)
                progressed = True
            if not progressed:
                break
        return freed

    def evict_one(self, keep=frozenset()) -> Optional[int]:
        """Single-page :meth:`evict_pages`; None when nothing evicts."""
        freed = self.evict_pages(1, keep)
        return freed[0] if freed else None

    def clear(self) -> List[int]:
        """Drop EVERY evictable entry (drain/tests); returns freed pages.
        Entries pinned by live requests (refs > 0) survive. A drain is
        not a pressure eviction: the evictions tally is untouched."""
        return self.evict_pages(1 << 62, count=False)

    # -- introspection (scalar reads only: safe from any thread) -----------
    def live_pages(self) -> int:
        """Index pages currently pinned by >= 1 live request (a page read
        by N requests counts once) — the serving.prefix_pages_shared
        gauge. O(1): maintained on the 0<->1 refcount transitions."""
        return self.pinned

    def stats(self) -> Dict[str, float]:
        return {"prefix_nodes": self.n_nodes,
                "prefix_partials": self.n_partials,
                "prefix_pages": self.total_pages,
                "prefix_pages_live": self.pinned,
                "prefix_hits": self.hits, "prefix_misses": self.misses,
                "prefix_evictions": self.evictions}
